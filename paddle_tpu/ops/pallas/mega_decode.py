"""Persistent decode-layer megakernels — the round-16 mega-kernelized hot loop.

The unified serving step (PR 4-9) is ONE jit, but inside it every
transformer layer is still a CHAIN of separate kernels — quant GEMMs,
ragged paged attention, fused MLP — stitched by XLA, with each
intermediate activation round-tripping through HBM between them. At
decode geometry (chunk = 1 token per lane) those tensors are tiny, so
per-kernel dispatch overhead and the activation HBM traffic dominate
device time. Following MPK ("A Compiler and Runtime for Mega-Kernelizing
Tensor Programs", PAPERS.md) and the ragged-blocking discipline of Ragged
Paged Attention (PAPERS.md), this module fuses a FULL layer's decode path
into TWO persistent ``pallas_call``s with the activations pinned in VMEM:

- :func:`mega_attn_layer` — ONE kernel per layer covering
  ``LN1 -> QKV projection (fp or int8 tile-dequant via the quant_matmul
  BlockSpec scale-row machinery) -> inline int8 quantize of the new K/V
  token rows -> ragged paged attention over the block-paged pools (online
  softmax across pages + an in-register causal block over the lane's own
  new tokens) -> output GEMM (per-head partials accumulated in a VMEM-
  revisited block) -> residual add -> LN2``. Grid ``(batch, heads,
  pages)``: weights stream per-head through BlockSpec index maps, the
  activations (x block, softmax state, attention output, the cross-head
  output accumulator) never leave VMEM between stages.
- :func:`mega_mlp` — ONE kernel per layer covering
  ``GEMM1 (+dequant) -> bias + tanh-gelu -> GEMM2 (+dequant) -> residual
  + bias`` with the ffn dim streamed in autotuned ``bn`` tiles and the
  ``[tokens, hidden]`` activation resident across tiles; the 4h-wide
  hidden state NEVER materializes in HBM.

Round 22 generalizes both kernels to the MIXED ragged-chunk geometry:
a lane may feed any ``1..chunk`` new rows per step (``q_lens`` already
drove the per-row causal limits — the in-register new-token block IS
small-chunk prefill), so the unified step routes EVERY round here, not
just all-decode rounds. What stays XLA-stitched (by design, documented
in ARCHITECTURE.md rounds 16/22): the page-pool SCATTER of the
kernel-quantized new K/V rows (pure data movement the donated-buffer
scatter already does optimally — the quantization itself is fused, the
kernel emits int8 + scales), the embedding gather, and the sampling
epilogue.

Contracts shared with the sibling kernels: interpret mode off-TPU (the
CPU suite runs the real kernel bodies), jnp composed references
(:func:`mega_attn_layer_reference` / :func:`mega_mlp_reference`) as the
numerical oracle and the non-TPU fallback, ``(bm, bn, bk)`` geometry on
the shared ``autotune_cache`` (pages-per-block is pinned at 1: the page-
table BlockSpec indirection fetches exactly one pool page per grid step —
a multi-page block would need contiguous pages, which paging exists to
avoid). int4 weights are NOT served here (split-half nibble packing
interleaves the K rows the per-head tiles slice); ``validate_mega_config``
rejects them loudly and the per-op path keeps serving int4.

SPMD (round 22): the kernels compose with the fully-manual ``shard_map``
mp mesh. Head-sharded weight columns and KV pools are already
chip-local; the ONLY mp-sensitive piece was the fused epilogue (residual
add + LN2 / + b2), which must sit AFTER the row-parallel psum. Under
mp > 1 the caller passes ``fuse_epilogue=False``: the kernels emit the
pre-psum output-GEMM partial instead, and ``models/gpt.py`` completes
``psum -> +bias -> residual -> LN2`` with the exact per-op spelling —
one psum per kernel, the same two collectives per layer as the per-op
build. At mesh size 1/None the epilogue stays fused (bit-identical to
round 16).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import autotune_cache as _atc

NEG_INF = -1e30

_MXU = jax.lax.Precision.DEFAULT

# tanh-gelu constants (jax.nn.gelu approximate=True — the GPT activation)
_K0 = 0.7978845608028654  # sqrt(2/pi)
_A = 0.044715


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def use_kernel_default() -> bool:
    return jax.default_backend() == "tpu"


def _dotf32(a, b, dims):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32,
                               precision=_MXU)


def _ln_f32(x32, g, b, eps):
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    return (x32 - mu) * jax.lax.rsqrt(var + eps) * g + b


def _gelu_f32(u):
    return 0.5 * u * (1.0 + jnp.tanh(_K0 * (u + _A * u * u * u)))


def _deq(w_ref, s_ref, dtype):
    """Widen a weight tile and apply its scale rows: ``s_ref`` is None
    (fp weights), one broadcast row, or ``rows`` dividing the tile's K
    extent (repeated to cover it) — the quant_matmul scale-row contract."""
    w = w_ref[...].astype(jnp.float32)
    if s_ref is None:
        return w.astype(dtype)
    s = s_ref[...].astype(jnp.float32)
    if s.shape[0] not in (1, w.shape[0]):
        s = jnp.repeat(s, w.shape[0] // s.shape[0], axis=0)
    return (w * s).astype(dtype)


def _quantize_rows_f32(x32):
    """Per-row-per-head symmetric int8 — the EXACT
    ``kv_cache.paged_write_packed_quant`` formula, fused in-kernel so the
    new K/V token quantizes inline instead of in a separate XLA pass.
    x32: [rows, hd] fp32. Returns (q int8 [rows, hd], s fp32 [rows, 1])."""
    absmax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    s = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x32 / s), -127, 127).astype(jnp.int8)
    return q, s


# ---------------------------------------------------------------------------
# config validation (the build-time gate)
# ---------------------------------------------------------------------------


def validate_mega_config(weight_dtype, group_size, head_dim, mp=1,
                         moe_experts=0) -> None:
    """Reject geometries the megakernel cannot serve — callers fall back
    to (or stay on) the per-op path with a loud reason instead of
    silently computing something else. ``mp`` is accepted (and ignored)
    since round 22: mp > 1 serves through ``fuse_epilogue=False`` — the
    kernels emit pre-psum partials and the caller's shard_map completes
    the row-parallel reduction, so no mesh size is rejected here."""
    del mp  # round 22: every mp degree is servable (see the docstring)
    if moe_experts:
        raise ValueError(
            "mega_decode is dense-only: the fused MLP kernel has no "
            "routed-expert path (moe_experts="
            f"{moe_experts}) — serve MoE configs through the per-op "
            "unified step (mega_decode=False)")
    if weight_dtype == "int4":
        raise ValueError(
            "mega_decode does not serve int4 weights: split-half nibble "
            "packing interleaves the K rows the per-head wqkv/wo tiles "
            "slice — use weight_dtype='int8' (or the per-op int4 path)")
    if weight_dtype == "int8" and group_size and group_size > 0:
        if head_dim % group_size and group_size % head_dim:
            raise ValueError(
                f"mega_decode needs the weight scale group size "
                f"({group_size}) aligned with head_dim ({head_dim}): the "
                "per-head wo tile must see whole scale groups "
                "(head_dim % group == 0 or group % head_dim == 0)")


# ---------------------------------------------------------------------------
# weight views: per-head BlockSpec plumbing (the scale-row machinery)
# ---------------------------------------------------------------------------


def _split_wq(leaf):
    """(qweight-or-weight, scales-or-None) for a serving weight leaf."""
    if isinstance(leaf, dict):
        return leaf["q"], leaf["s"]
    return leaf, None


def _qkv_views(p, nh, hd, head_major):
    """wqkv reshaped so ONE BlockSpec index map slices a (component,
    head) column tile: eager layout orders columns [3, nh, hd]; the
    mesh layout is head-major [nh, 3, hd]."""
    w, s = _split_wq(p["wqkv"])
    h_in = w.shape[0]
    shape = (h_in, nh, 3, hd) if head_major else (h_in, 3, nh, hd)
    w4 = w.reshape(shape)
    s4 = s.reshape((s.shape[0],) + shape[1:]) if s is not None else None
    bshape = ((1, nh, 3, hd) if head_major else (1, 3, nh, hd))
    b4 = p["bqkv"].reshape(bshape)
    return w4, s4, b4


def _qkv_spec(h_in, hd, c, head_major):
    if head_major:
        return pl.BlockSpec((h_in, None, None, hd),
                            lambda bi, hh, j, *_: (0, hh, c, 0))
    return pl.BlockSpec((h_in, None, None, hd),
                        lambda bi, hh, j, *_: (0, c, hh, 0))


def _kdim_scale_view(s, k, tile, nh):
    """(view, spec) serving a K-sharded weight's scale rows per head tile
    (wo: K = h, tile = head_dim at offset head*tile). Three shapes:
    per-channel broadcast, multiple groups per tile (reshape so the head
    index IS the block index), or one group spanning tiles (index-map
    arithmetic selects the row)."""
    groups, n = s.shape
    if groups == 1:
        return s, pl.BlockSpec((1, n), lambda bi, hh, j, *_: (0, 0))
    gs = k // groups
    if tile % gs == 0:
        view = s.reshape(nh, tile // gs, n)
        return view, pl.BlockSpec((None, tile // gs, n),
                                  lambda bi, hh, j, *_: (hh, 0, 0))
    # gs % tile == 0 (validate_mega_config enforced): one row per tile
    step = gs // tile
    return s, pl.BlockSpec((1, n), lambda bi, hh, j, *_: (hh // step, 0))


# ---------------------------------------------------------------------------
# attention-side megakernel
# ---------------------------------------------------------------------------


def _mega_attn_kernel(ctx_ref, qlen_ref, pt_ref, *refs, page_size, scale,
                      eps, wq_quant, wo_quant, kv_quant, fuse_epilogue):
    """One (lane, head, page) grid step of the fused attention-side layer.

    Stage schedule (all state VMEM-resident across the grid):
    - ``j == 0``: LN1 + this head's QKV column tiles -> q rows saved, the
      new K/V rows quantized inline (int8 KV) and emitted;
    - every ``j``: one pool page through the online softmax (int8 pages
      dequantize against their [page_size, 1] scale column on the way in);
    - ``j == last``: the lane's own new tokens as an in-register causal
      block, then this head's rows of the output GEMM accumulate into the
      cross-head ``yacc`` block;
    - ``(head, j) == last``: residual add + LN2 epilogue emits (y2, s).
    """
    it = iter(refs)
    x_ref = next(it)
    g1_ref, b1g_ref, g2_ref, b2g_ref = (next(it) for _ in range(4))
    wq_ref, wk_ref, wv_ref = (next(it) for _ in range(3))
    sq_ref = sk_ref = sv_ref = None
    if wq_quant:
        sq_ref, sk_ref, sv_ref = (next(it) for _ in range(3))
    bq_ref, bk_ref, bv_ref = (next(it) for _ in range(3))
    wo_ref = next(it)
    so_ref = next(it) if wo_quant else None
    bo_ref = next(it)
    k_ref, v_ref = next(it), next(it)
    ks_ref = vs_ref = None
    if kv_quant:
        ks_ref, vs_ref = next(it), next(it)
    y2_ref, s_ref = next(it), next(it)
    ko_ref, vo_ref = next(it), next(it)
    kso_ref = vso_ref = None
    if kv_quant:
        kso_ref, vso_ref = next(it), next(it)
    yacc_ref, q_ref, m_ref, l_ref, o_ref = (next(it) for _ in range(5))

    b = pl.program_id(0)
    hh = pl.program_id(1)
    j = pl.program_id(2)
    hkv = pl.num_programs(1)
    pps = pl.num_programs(2)
    ctx = ctx_ref[b]       # context length BEFORE this step's tokens
    q_len = qlen_ref[b]    # valid new rows this step (0 = idle lane)
    dtype = x_ref.dtype

    @pl.when((hh == 0) & (j == 0))
    def _init_lane():
        yacc_ref[...] = jnp.zeros_like(yacc_ref)

    @pl.when(j == 0)
    def _init_head():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        o_ref[...] = jnp.zeros_like(o_ref)

    @pl.when((j == 0) & (q_len > 0))
    def _qkv():
        # LN1 + this head's QKV column tiles; rows past q_len are padding
        # whose garbage nothing downstream reads (their K/V scatter drops)
        x32 = x_ref[...].astype(jnp.float32)
        y1 = _ln_f32(x32, g1_ref[...].astype(jnp.float32),
                     b1g_ref[...].astype(jnp.float32), eps).astype(dtype)
        dims = ((1,), (0,))
        q = (_dotf32(y1, _deq(wq_ref, sq_ref, dtype), dims)
             + bq_ref[...].astype(jnp.float32))
        k_new = (_dotf32(y1, _deq(wk_ref, sk_ref, dtype), dims)
                 + bk_ref[...].astype(jnp.float32))
        v_new = (_dotf32(y1, _deq(wv_ref, sv_ref, dtype), dims)
                 + bv_ref[...].astype(jnp.float32))
        q_ref[...] = q.astype(dtype)
        if kv_quant:
            kq, ks = _quantize_rows_f32(k_new)
            vq, vs = _quantize_rows_f32(v_new)
            ko_ref[...] = kq
            vo_ref[...] = vq
            kso_ref[...] = ks
            vso_ref[...] = vs
        else:
            ko_ref[...] = k_new.astype(dtype)
            vo_ref[...] = v_new.astype(dtype)

    @pl.when((j * page_size < ctx) & (q_len > 0))
    def _pages():
        # one pool page through the online softmax (every new row attends
        # the WHOLE prior context — per-row limits only exist inside the
        # new-token block below)
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        if kv_quant:
            k = (k.astype(jnp.float32) * ks_ref[...]).astype(q.dtype)
            v = (v.astype(jnp.float32) * vs_ref[...]).astype(q.dtype)
        s = _dotf32(q, k, ((1,), (1,))) * scale          # [C8, ps] f32
        col = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(col < ctx, s, NEG_INF)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_next = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next)
        l_next = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        l_safe = jnp.where(l_next == 0.0, 1.0, l_next)
        pv = _dotf32(p.astype(v.dtype), v, ((1,), (0,)))
        o_ref[...] = ((o_ref[...] * (l_prev * alpha) + pv) / l_safe
                      ).astype(o_ref.dtype)
        m_ref[...] = m_next
        l_ref[...] = l_next

    @pl.when((j == pps - 1) & (q_len > 0))
    def _new_block():
        # the lane's OWN new tokens, still in VMEM: row i attends new
        # col c while c <= i (causal within the chunk — exactly the spec
        # verify-row semantics) and c < q_len. int8 KV attends the
        # quantize-dequantize image, matching what later steps will read
        # back from the pool.
        q = q_ref[...]
        kd = ko_ref[...]
        vd = vo_ref[...]
        if kv_quant:
            kd = (kd.astype(jnp.float32) * kso_ref[...]).astype(q.dtype)
            vd = (vd.astype(jnp.float32) * vso_ref[...]).astype(q.dtype)
        s = _dotf32(q, kd, ((1,), (1,))) * scale         # [C8, C8]
        row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where((col <= row) & (col < q_len), s, NEG_INF)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_next = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next)
        l_next = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        l_safe = jnp.where(l_next == 0.0, 1.0, l_next)
        pv = _dotf32(p.astype(vd.dtype), vd, ((1,), (0,)))
        o_ref[...] = ((o_ref[...] * (l_prev * alpha) + pv) / l_safe
                      ).astype(o_ref.dtype)
        m_ref[...] = m_next
        l_ref[...] = l_next

    @pl.when(j == pps - 1)
    def _out_gemm():
        # this head's rows of the output GEMM: o [C8, hd] against wo's
        # [hd, h] row band, accumulated into the cross-head yacc block
        # (idle lanes accumulate zeros — o is init-zero)
        wo_t = _deq(wo_ref, so_ref, dtype)
        yacc_ref[...] += _dotf32(o_ref[...].astype(dtype), wo_t,
                                 ((1,), (0,)))

    @pl.when((hh == hkv - 1) & (j == pps - 1))
    def _epilogue():
        # residual + LN2, still in VMEM: s = x + attn + bo; y2 = LN2(s).
        # s round-trips through the storage dtype before the LN read so
        # the statistics match the per-op path's (which LNs the STORED
        # residual stream). Under mp > 1 (fuse_epilogue=False) the
        # residual/bias/LN2 must sit AFTER the row-parallel psum, so the
        # kernel emits the raw output-GEMM partial instead and the
        # shard_map caller completes the epilogue post-reduction.
        if fuse_epilogue:
            x32 = x_ref[...].astype(jnp.float32)
            s_out = x32 + yacc_ref[...] + bo_ref[...].astype(jnp.float32)
            s_ref[...] = s_out.astype(dtype)
            s32 = s_ref[...].astype(jnp.float32)
            y2 = _ln_f32(s32, g2_ref[...].astype(jnp.float32),
                         b2g_ref[...].astype(jnp.float32), eps)
            y2_ref[...] = y2.astype(dtype)
        else:
            y2_ref[...] = yacc_ref[...].astype(dtype)
            s_ref[...] = jnp.zeros_like(s_ref)


def mega_attn_layer(xb, p, k_pages, v_pages, page_table, ctx_lens, q_lens,
                    *, eps=1e-5, k_scales=None, v_scales=None,
                    head_major=False, use_kernel=None, fuse_epilogue=True):
    """The fused attention-side decode layer over ragged chunk blocks.

    xb: [b, chunk, h] per-lane token blocks (``q_lens[b]`` valid rows —
    any 1..chunk per lane, so mixed prefill+decode rounds serve here);
    p: ONE layer's serving weight dict (``_SRV_LAYER_WEIGHTS`` keys; wqkv
    /wo may be quantized ``{"q", "s"}`` stacks); pages/scales/page_table/
    ctx_lens as in ``ragged_paged_attention`` — ``ctx_lens`` counts
    tokens ALREADY IN THE POOL (this step's tokens are handled
    in-register and emitted for the caller's scatter). Returns
    ``(y2, s, k_new, v_new)`` — y2/s ``[b, chunk, h]`` (LN2 output and
    the residual stream), k_new/v_new ``[b, chunk, kv_heads, head_dim]``
    — plus ``(k_sc, v_sc)`` ``[b, chunk, kv_heads]`` scale rows when the
    pools are int8 (k_new/v_new are then the int8 payloads, quantized
    inline with the ``paged_write_packed_quant`` formula).

    ``fuse_epilogue=False`` (the mp > 1 spelling, round 22): the
    residual add + bo + LN2 must follow the caller's row-parallel psum,
    so the return drops (y2, s) in favor of the single pre-psum partial:
    ``(y_part, k_new, v_new[, k_sc, v_sc])`` with y_part ``[b, chunk,
    h]`` = (this shard's heads' attention output) @ wo — NO residual,
    NO bias, NO LN. Head-sharded callers pass their LOCAL wqkv/wo
    columns and head-sharded pools; q/kv head count derives from the
    pool's head axis.

    ``use_kernel``: None = kernel on TPU / composed jnp reference
    elsewhere; True forces the kernel (interpret off-TPU); False forces
    :func:`mega_attn_layer_reference`.
    """
    if use_kernel is None:
        use_kernel = use_kernel_default()
    if not use_kernel:
        return mega_attn_layer_reference(
            xb, p, k_pages, v_pages, page_table, ctx_lens, q_lens,
            eps=eps, k_scales=k_scales, v_scales=v_scales,
            head_major=head_major, fuse_epilogue=fuse_epilogue)
    b, chunk, h = xb.shape
    num_pages, page_size, hkv, hd = k_pages.shape
    # group-1 attention per shard: q heads == kv heads. The pool's head
    # axis is authoritative — under the mp mesh it carries this shard's
    # LOCAL heads while xb keeps the full (replicated) hidden width.
    nh = hkv
    assert _split_wq(p["wqkv"])[0].shape[1] == 3 * nh * hd, (
        f"mega_attn_layer: wqkv columns "
        f"{_split_wq(p['wqkv'])[0].shape[1]} do not match the pool's "
        f"{nh} heads x {hd} head_dim (group-1: q heads == kv heads)")
    kv_quant = k_scales is not None
    wq, sq, bq4 = _qkv_views(p, nh, hd, head_major)
    wo, so = _split_wq(p["wo"])
    wo3 = wo.reshape(nh, hd, h)
    scale = 1.0 / math.sqrt(hd)
    c8 = max(8, ((chunk + 7) // 8) * 8)
    if c8 != chunk:
        xb = jnp.pad(xb, ((0, 0), (0, c8 - chunk), (0, 0)))
    h_in = wq.shape[0]
    dtype = xb.dtype

    row = lambda: pl.BlockSpec((1, h), lambda bi, hh, j, *_: (0, 0))  # noqa: E731
    lane = pl.BlockSpec((None, c8, h), lambda bi, hh, j, *_: (bi, 0, 0))

    def kv_page(bi, hh, j, ctx_ref, qlen_ref, pt_ref):
        # pages past the last context page re-fetch it (compute skipped);
        # empty/unallocated entries clamp to page 0 — the paged_attention
        # clamping discipline
        ps = jnp.int32(page_size)
        last = jnp.maximum(
            jax.lax.div(ctx_ref[bi] + ps - jnp.int32(1), ps) - jnp.int32(1),
            jnp.int32(0))
        page = pt_ref[bi, jnp.minimum(jnp.int32(j), last)]
        return jnp.clip(page, 0, num_pages - 1)

    kv_spec = pl.BlockSpec((None, page_size, None, hd),
                           lambda bi, hh, j, *r: (kv_page(bi, hh, j, *r),
                                                  0, hh, 0))
    sc_spec = pl.BlockSpec((None, page_size, 1),
                           lambda bi, hh, j, *r: (kv_page(bi, hh, j, *r),
                                                  0, hh))
    head_rows = pl.BlockSpec((None, c8, hd),
                             lambda bi, hh, j, *_: (bi, 0, 0))

    in_specs = [lane, row(), row(), row(), row()]
    args = [xb, p["ln1_g"].reshape(1, h), p["ln1_b"].reshape(1, h),
            p["ln2_g"].reshape(1, h), p["ln2_b"].reshape(1, h)]
    in_specs += [_qkv_spec(h_in, hd, c, head_major) for c in range(3)]
    args += [wq, wq, wq]
    if sq is not None:
        g_rows = sq.shape[0]
        in_specs += [pl.BlockSpec(
            (g_rows,) + _qkv_spec(h_in, hd, c, head_major).block_shape[1:],
            _qkv_spec(h_in, hd, c, head_major).index_map)
            for c in range(3)]
        args += [sq, sq, sq]
    in_specs += [_qkv_spec(1, hd, c, head_major) for c in range(3)]
    args += [bq4, bq4, bq4]
    in_specs += [pl.BlockSpec((None, hd, h),
                              lambda bi, hh, j, *_: (hh, 0, 0))]
    args += [wo3]
    if so is not None:
        so_view, so_spec = _kdim_scale_view(so, h, hd, nh)
        in_specs += [so_spec]
        args += [so_view]
    in_specs += [row()]
    args += [p["bo"].reshape(1, h)]
    in_specs += [kv_spec, kv_spec]
    args += [k_pages, v_pages]
    if kv_quant:
        in_specs += [sc_spec, sc_spec]
        args += [k_scales.astype(jnp.float32), v_scales.astype(jnp.float32)]

    kv_out_dtype = jnp.int8 if kv_quant else dtype
    out_specs = [lane, lane, head_rows, head_rows]
    out_shape = [
        jax.ShapeDtypeStruct((b, c8, h), dtype),           # y2
        jax.ShapeDtypeStruct((b, c8, h), dtype),           # s
        jax.ShapeDtypeStruct((b, hkv, c8, hd), kv_out_dtype),
        jax.ShapeDtypeStruct((b, hkv, c8, hd), kv_out_dtype),
    ]
    ko_spec = pl.BlockSpec((None, None, c8, hd),
                           lambda bi, hh, j, *_: (bi, hh, 0, 0))
    out_specs[2] = out_specs[3] = ko_spec
    if kv_quant:
        ksc_spec = pl.BlockSpec((None, None, c8, 1),
                                lambda bi, hh, j, *_: (bi, hh, 0, 0))
        out_specs += [ksc_spec, ksc_spec]
        out_shape += [jax.ShapeDtypeStruct((b, hkv, c8, 1), jnp.float32)] * 2
    # VMEM-revisited stages: the cross-head output accumulator, this
    # head's q rows, and the online-softmax state — dropped by the caller
    out_specs += [lane,
                  ko_spec,
                  pl.BlockSpec((None, None, c8, 1),
                               lambda bi, hh, j, *_: (bi, hh, 0, 0)),
                  pl.BlockSpec((None, None, c8, 1),
                               lambda bi, hh, j, *_: (bi, hh, 0, 0)),
                  ko_spec]
    out_shape += [
        jax.ShapeDtypeStruct((b, c8, h), jnp.float32),          # yacc
        jax.ShapeDtypeStruct((b, hkv, c8, hd), dtype),          # q tmp
        jax.ShapeDtypeStruct((b, hkv, c8, 1), jnp.float32),     # m
        jax.ShapeDtypeStruct((b, hkv, c8, 1), jnp.float32),     # l
        jax.ShapeDtypeStruct((b, hkv, c8, hd), jnp.float32),    # o
    ]

    pps = page_table.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, hkv, pps),
        in_specs=in_specs,
        out_specs=out_specs,
    )
    kern = functools.partial(
        _mega_attn_kernel, page_size=page_size, scale=scale,
        eps=float(eps), wq_quant=sq is not None, wo_quant=so is not None,
        kv_quant=kv_quant, fuse_epilogue=fuse_epilogue)
    with _atc.x64_off():
        outs = pl.pallas_call(
            kern, grid_spec=grid_spec, out_shape=out_shape,
            compiler_params=pltpu.TPUCompilerParams(
                dimension_semantics=("parallel", "arbitrary", "arbitrary")),
            interpret=_interpret(),
        )(ctx_lens.astype(jnp.int32), q_lens.astype(jnp.int32),
          page_table.astype(jnp.int32), *args)
    y2, s = outs[0][:, :chunk], outs[1][:, :chunk]
    k_new = outs[2].transpose(0, 2, 1, 3)[:, :chunk]   # [b, chunk, hkv, hd]
    v_new = outs[3].transpose(0, 2, 1, 3)[:, :chunk]
    if kv_quant:
        k_sc = outs[4][..., 0].transpose(0, 2, 1)[:, :chunk]
        v_sc = outs[5][..., 0].transpose(0, 2, 1)[:, :chunk]
        if not fuse_epilogue:
            return y2, k_new, v_new, k_sc, v_sc   # y2 slot = y_part
        return y2, s, k_new, v_new, k_sc, v_sc
    if not fuse_epilogue:
        return y2, k_new, v_new
    return y2, s, k_new, v_new


def mega_attn_layer_reference(xb, p, k_pages, v_pages, page_table,
                              ctx_lens, q_lens, *, eps=1e-5, k_scales=None,
                              v_scales=None, head_major=False,
                              fuse_epilogue=True):
    """Composed jnp oracle for :func:`mega_attn_layer`: the existing
    per-op references (dequant matmul, gathered paged attention with the
    in-register new-token semantics, LN) chained in the megakernel's
    exact stage order — the numerical golden AND the non-TPU fallback.
    ``fuse_epilogue=False`` mirrors the kernel's mp spelling: the return
    is the pre-psum output-GEMM partial (no residual/bias/LN2)."""
    from .quant_matmul import dequantize_weight

    b, chunk, h = xb.shape
    num_pages, page_size, hkv, hd = k_pages.shape
    nh = hkv   # pool head axis is authoritative (head-sharded under mp)
    kv_quant = k_scales is not None
    dtype = xb.dtype

    def mm(y, leaf):
        if isinstance(leaf, dict):
            w = dequantize_weight(leaf["q"], leaf["s"],
                                  out_dtype=jnp.float32).astype(dtype)
        else:
            w = leaf
        return y @ w

    x32 = xb.astype(jnp.float32)
    y1 = _ln_f32(x32, p["ln1_g"].astype(jnp.float32),
                 p["ln1_b"].astype(jnp.float32), eps).astype(dtype)
    qkv = mm(y1, p["wqkv"]) + p["bqkv"]                  # [b, c, 3h]
    if head_major:
        q4 = qkv.reshape(b, chunk, nh, 3, hd)
        q, k_new, v_new = q4[..., 0, :], q4[..., 1, :], q4[..., 2, :]
    else:
        q4 = qkv.reshape(b, chunk, 3, nh, hd)
        q, k_new, v_new = (q4[:, :, 0], q4[:, :, 1], q4[:, :, 2])
    q = q.astype(jnp.float32)
    kf, vf = k_new.astype(jnp.float32), v_new.astype(jnp.float32)
    if kv_quant:
        k_q, k_sc = _quantize_rows_f32(kf.reshape(-1, hd))
        v_q, v_sc = _quantize_rows_f32(vf.reshape(-1, hd))
        k_emit = k_q.reshape(b, chunk, hkv, hd)
        v_emit = v_q.reshape(b, chunk, hkv, hd)
        k_scr = k_sc.reshape(b, chunk, hkv)
        v_scr = v_sc.reshape(b, chunk, hkv)
        # attend the quantize-dequantize image — what later steps read
        kf = k_emit.astype(jnp.float32) * k_scr[..., None]
        vf = v_emit.astype(jnp.float32) * v_scr[..., None]
    else:
        k_emit, v_emit = k_new.astype(dtype), v_new.astype(dtype)
    # gathered context (dequantized when the pools are int8)
    pt = jnp.clip(page_table, 0, num_pages - 1)
    pps = page_table.shape[1]
    kc = k_pages[pt].reshape(b, pps * page_size, hkv, hd)
    vc = v_pages[pt].reshape(b, pps * page_size, hkv, hd)
    if kv_quant:
        kc = (kc.astype(jnp.float32)
              * k_scales[pt].reshape(b, pps * page_size, hkv)[..., None])
        vc = (vc.astype(jnp.float32)
              * v_scales[pt].reshape(b, pps * page_size, hkv)[..., None])
    kc, vc = kc.astype(jnp.float32), vc.astype(jnp.float32)
    scale = 1.0 / math.sqrt(hd)
    s_ctx = jnp.einsum("bcnd,bsnd->bncs", q, kc, precision=_MXU) * scale
    s_new = jnp.einsum("bcnd,bknd->bnck", q, kf, precision=_MXU) * scale
    col = jnp.arange(pps * page_size)[None, None, None, :]
    rowi = jnp.arange(chunk).reshape(1, 1, -1, 1)
    valid_ctx = ((col < ctx_lens.reshape(-1, 1, 1, 1))
                 & (rowi < q_lens.reshape(-1, 1, 1, 1)))
    colk = jnp.arange(chunk)[None, None, None, :]
    valid_new = ((colk <= rowi) & (colk < q_lens.reshape(-1, 1, 1, 1))
                 & (rowi < q_lens.reshape(-1, 1, 1, 1)))
    s_all = jnp.concatenate(
        [jnp.where(valid_ctx, s_ctx, NEG_INF),
         jnp.where(valid_new, s_new, NEG_INF)], axis=-1)
    pr = jax.nn.softmax(s_all, axis=-1)
    valid_any = jnp.concatenate(
        [jnp.broadcast_to(valid_ctx, s_ctx.shape),
         jnp.broadcast_to(valid_new, s_new.shape)], axis=-1)
    pr = jnp.where(valid_any, pr, 0.0)
    v_all = jnp.concatenate([vc, vf.astype(jnp.float32)], axis=1)
    o = jnp.einsum("bncs,bsnd->bcnd", pr, v_all, precision=_MXU)
    a = o.reshape(b, chunk, nh * hd).astype(dtype)
    if not fuse_epilogue:
        # the mp spelling: emit this shard's pre-psum partial; the caller
        # completes psum -> +bo -> residual -> LN2 with the per-op math
        y_part = mm(a, p["wo"]).astype(dtype)
        if kv_quant:
            return y_part, k_emit, v_emit, k_scr, v_scr
        return y_part, k_emit, v_emit
    s_out32 = (xb.astype(jnp.float32)
               + mm(a, p["wo"]).astype(jnp.float32)
               + p["bo"].astype(jnp.float32))
    s_out = s_out32.astype(dtype)
    y2 = _ln_f32(s_out.astype(jnp.float32),
                 p["ln2_g"].astype(jnp.float32),
                 p["ln2_b"].astype(jnp.float32), eps).astype(dtype)
    if kv_quant:
        return y2, s_out, k_emit, v_emit, k_scr, v_scr
    return y2, s_out, k_emit, v_emit


# ---------------------------------------------------------------------------
# MLP-side megakernel
# ---------------------------------------------------------------------------


def _mega_mlp_kernel(y2_ref, s_res_ref, w1_ref, b1_ref, w2_ref, b2_ref,
                     *refs, wq_quant, fuse_epilogue):
    """One ffn tile of the fused MLP: GEMM1 column tile -> bias + tanh
    gelu -> GEMM2 row tile, accumulated into the residual-initialized
    output block (zero-initialized under ``fuse_epilogue=False`` — the
    mp caller adds residual + b2 after its psum). The [rows, 4h] hidden
    state lives only in VMEM."""
    if wq_quant:
        s1_ref, s2_ref, o_ref = refs
    else:
        (o_ref,) = refs
        s1_ref = s2_ref = None
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        if fuse_epilogue:
            o_ref[...] = (s_res_ref[...].astype(jnp.float32)
                          + b2_ref[...].astype(jnp.float32))
        else:
            o_ref[...] = jnp.zeros_like(o_ref)

    y2 = y2_ref[...]
    w1 = _deq(w1_ref, s1_ref, y2.dtype)
    u = _dotf32(y2, w1, ((1,), (0,))) + b1_ref[...].astype(jnp.float32)
    g = _gelu_f32(u).astype(y2.dtype)
    w2 = _deq(w2_ref, s2_ref, y2.dtype)
    o_ref[...] += _dotf32(g, w2, ((1,), (0,)))


BM_DEFAULT = 64
BN_DEFAULT = 512


def _mega_sig(h, f, dtype, chunk=1) -> str:
    """The autotune-cache key for a mega layer geometry. ``chunk`` (round
    22) keys the MIXED ragged-chunk geometry: a chunk-c step runs c times
    the token rows of the decode-only step, so its winning ffn tile can
    differ — the legacy ``chunk == 1`` spelling stays byte-identical so
    every decode-only entry persisted before round 22 still hits."""
    base = f"mega:{h}x{f}"
    if chunk and chunk > 1:
        base += f":c{int(chunk)}"
    return f"{base}:{jnp.dtype(dtype).name}"


def _div_pick(pref: int, dim: int) -> int:
    b = min(pref, dim)
    while dim % b:
        b //= 2
    return max(b, 1)


def preferred_mega_blocks(h, f, dtype=jnp.bfloat16, chunk=1):
    """The autotuned ``(bm, bn, bk)`` for this layer geometry (or the
    defaults): ``bn`` tiles the ffn dim through the MLP megakernel, ``bm``
    /``bk`` are currently whole-extent (the decode token block and the
    hidden contraction both fit VMEM at decode geometry) and pages-per-
    block is pinned at 1 (see the module docstring) — kept in the cached
    tuple so a future sweep can shrink them without a cache migration.
    The signature deliberately omits head_dim: nothing swept today
    depends on it (the attention kernel's tiles are pinned whole-extent),
    and a key the lookup side cannot reconstruct is a cache that never
    hits. ``chunk`` keys the mixed ragged-chunk geometry (round 22); a
    missing chunk-c entry falls back to the chunk-1 entry before the
    defaults (the decode sweep is a better prior than nothing)."""
    hit = _atc.lookup(_mega_sig(h, f, dtype, chunk))
    if not (hit and len(hit) == 3) and chunk and chunk > 1:
        hit = _atc.lookup(_mega_sig(h, f, dtype))
    if hit and len(hit) == 3:
        bm, bn, bk = hit
    else:
        bm, bn, bk = BM_DEFAULT, BN_DEFAULT, h
    return int(bm), int(bn), int(bk)


def _mlp_bn(f, groups, h, dtype, chunk=1) -> int:
    """The ffn tile: the autotuned bn, shrunk to divide the ffn dim and
    align with the w2 scale groups (the quant_matmul whole-groups
    discipline): a tile at least one group wide becomes a MULTIPLE of the
    group size (the kernel reshapes multiple scale rows per tile), a
    smaller tile a divisor of it (one scale row spans several tiles) —
    the autotuned width is preserved, not collapsed to the group size."""
    _, bn, _ = preferred_mega_blocks(h, f, dtype, chunk)
    if groups > 1:
        gs = f // groups
        if bn >= gs:
            return _div_pick(bn // gs, groups) * gs
        return _div_pick(bn, gs)
    return _div_pick(bn, f)


def mega_mlp(y2, s_res, p, *, use_kernel=None, fuse_epilogue=True,
             chunk=1):
    """The fused MLP half of the decode layer on the PACKED token stream:
    ``out = s_res + gelu(y2 @ w1 + b1) @ w2 + b2`` with the ffn dim
    streamed in ``bn`` tiles and the hidden state never touching HBM.
    y2/s_res: [t, h]; returns [t, h] in y2's dtype.

    ``fuse_epilogue=False`` (the mp > 1 spelling): returns the pre-psum
    GEMM2 partial ``gelu(y2 @ w1 + b1) @ w2`` — no residual, no b2; the
    caller completes ``psum -> +b2 -> residual`` after its collective
    (``s_res`` may be None). ``chunk`` only keys the autotune lookup —
    the mixed ragged-chunk geometry may prefer a different ffn tile."""
    if use_kernel is None:
        use_kernel = use_kernel_default()
    if not use_kernel:
        return mega_mlp_reference(y2, s_res, p,
                                  fuse_epilogue=fuse_epilogue)
    t, h = y2.shape
    if s_res is None:
        s_res = jnp.zeros_like(y2)   # never read: fuse_epilogue is False
    w1, s1 = _split_wq(p["w1"])
    w2, s2 = _split_wq(p["w2"])
    f = w1.shape[1]
    groups2 = s2.shape[0] if s2 is not None else 1
    bn = _mlp_bn(f, groups2, h, y2.dtype, chunk)
    t8 = max(8, ((t + 7) // 8) * 8)
    if t8 != t:
        y2 = jnp.pad(y2, ((0, t8 - t), (0, 0)))
        s_res = jnp.pad(s_res, ((0, t8 - t), (0, 0)))
    nf = f // bn
    dtype = y2.dtype

    full = lambda: pl.BlockSpec((t8, h), lambda i: (0, 0))  # noqa: E731
    in_specs = [full(), full(),
                pl.BlockSpec((h, bn), lambda i: (0, i)),
                pl.BlockSpec((1, bn), lambda i: (0, i)),
                pl.BlockSpec((bn, h), lambda i: (i, 0)),
                pl.BlockSpec((1, h), lambda i: (0, 0))]
    args = [y2, s_res, w1, p["b1"].reshape(1, f), w2,
            p["b2"].reshape(1, h)]
    wq_quant = s1 is not None
    if wq_quant:
        in_specs.append(pl.BlockSpec((s1.shape[0], bn), lambda i: (0, i)))
        args.append(s1)
        g2 = s2.shape[0]
        if g2 == 1:
            in_specs.append(pl.BlockSpec((1, h), lambda i: (0, 0)))
            args.append(s2)
        else:
            gs2 = f // g2
            if bn % gs2 == 0:
                in_specs.append(pl.BlockSpec((None, bn // gs2, h),
                                             lambda i: (i, 0, 0)))
                args.append(s2.reshape(nf, bn // gs2, h))
            else:  # gs2 % bn == 0 by the gcd pick
                step = gs2 // bn
                in_specs.append(pl.BlockSpec(
                    (1, h), lambda i, _s=step: (i // _s, 0)))
                args.append(s2)
    kern = functools.partial(_mega_mlp_kernel, wq_quant=wq_quant,
                             fuse_epilogue=fuse_epilogue)
    with _atc.x64_off():
        out = pl.pallas_call(
            kern, grid=(nf,), in_specs=in_specs,
            out_specs=pl.BlockSpec((t8, h), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((t8, h), jnp.float32),
            compiler_params=pltpu.TPUCompilerParams(
                dimension_semantics=("arbitrary",)),
            interpret=_interpret(),
        )(*args)
    return out[:t].astype(dtype)


def mega_mlp_reference(y2, s_res, p, *, fuse_epilogue=True):
    """Composed jnp oracle for :func:`mega_mlp` (and the non-TPU path).
    ``fuse_epilogue=False`` returns the pre-psum GEMM2 partial (see
    :func:`mega_mlp`)."""
    from .quant_matmul import dequantize_weight

    dtype = y2.dtype

    def mm(y, leaf):
        if isinstance(leaf, dict):
            w = dequantize_weight(leaf["q"], leaf["s"],
                                  out_dtype=jnp.float32).astype(dtype)
        else:
            w = leaf
        return y @ w

    u = (mm(y2, p["w1"]).astype(jnp.float32)
         + p["b1"].astype(jnp.float32))
    g = _gelu_f32(u).astype(dtype)
    if not fuse_epilogue:
        return mm(g, p["w2"]).astype(dtype)
    out = (s_res.astype(jnp.float32)
           + mm(g, p["w2"]).astype(jnp.float32)
           + p["b2"].astype(jnp.float32))
    return out.astype(dtype)


# ---------------------------------------------------------------------------
# geometry autotune (shared persisted cache)
# ---------------------------------------------------------------------------


def autotune_mega_decode(batch, h, f, dtype=jnp.bfloat16,
                         candidates=(256, 512, 1024, 2048), iters=10,
                         chunk=1):
    """Sweep the MLP megakernel's ffn tile (``bn``) for this layer
    geometry on the current device and persist the winning ``(bm, bn,
    bk)`` on the shared autotune cache (``bm``/``bk`` ride along whole-
    extent — see :func:`preferred_mega_blocks`). Candidates collapse to
    their EFFECTIVE tile first (``_div_pick`` shrinks a non-dividing bn
    at serve time, so that is what gets timed AND what gets persisted —
    the cached tuple always describes a program that actually ran) and
    duplicates are timed once. No-op off-TPU. Timing rides the
    observability clock (tpulint AL006: one clock for durations, traces
    and bench windows). ``chunk`` (round 22) sweeps the MIXED ragged-
    chunk geometry: the timed token block scales to ``batch * chunk``
    rows and the result persists under the chunk-keyed signature —
    decode-only (chunk 1) entries are never overwritten."""
    from ...observability import monotonic

    chunk = max(1, int(chunk))
    if _interpret():
        return preferred_mega_blocks(h, f, dtype, chunk)
    _atc.load()
    sig = _mega_sig(h, f, dtype, chunk)
    batch = batch * chunk   # the mixed round's packed token rows
    ky, ks, kw = jax.random.split(jax.random.PRNGKey(0), 3)
    y2 = jax.random.normal(ky, (batch, h), dtype)
    s_res = jax.random.normal(ks, (batch, h), dtype)
    p = {"w1": jax.random.normal(kw, (h, f), dtype) * 0.02,
         "b1": jnp.zeros((f,), dtype),
         "w2": jnp.zeros((f, h), dtype),
         "b2": jnp.zeros((h,), dtype)}
    saved = _atc.CACHE.get(sig)
    best, best_t = None, float("inf")
    tried: set[int] = set()
    for bn in candidates:
        eff = _div_pick(int(bn), f)
        if eff in tried:
            continue
        tried.add(eff)
        _atc.CACHE[sig] = [BM_DEFAULT, eff, int(h)]
        try:
            step = jax.jit(functools.partial(mega_mlp, use_kernel=True,
                                             chunk=chunk))
            step(y2, s_res, p).block_until_ready()
            t0 = monotonic()
            for _ in range(iters):
                out = step(y2, s_res, p)
            out.block_until_ready()
            t = monotonic() - t0
        except Exception:
            continue
        if t < best_t:
            best, best_t = [BM_DEFAULT, eff, int(h)], t
    if best is not None:
        _atc.CACHE[sig] = best
        _atc.save()
    elif saved is None:
        _atc.CACHE.pop(sig, None)
    else:
        _atc.CACHE[sig] = saved
    return preferred_mega_blocks(h, f, dtype, chunk)
