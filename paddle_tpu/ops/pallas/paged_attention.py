"""Paged decode attention — Pallas TPU kernel over a block-paged KV cache.

The serving-side sibling of ``flash_attention.py``: one query token per
sequence attends over that sequence's K/V prefix, which lives in a POOL of
fixed-size pages (``[num_pages, page_size, kv_heads, head_dim]``) indexed by
a per-sequence page table — the vLLM/Ragged-Paged-Attention memory layout
(arxiv 2604.15464) that lets a continuous-batching scheduler admit/evict
sequences without copying or fragmenting the cache.

Kernel shape (TPU-idiomatic, following the flash kernel's conventions):

- grid ``(batch, kv_heads, pages_per_seq)``; the page table and the ragged
  per-sequence lengths ride in scalar-prefetch SMEM, and the K/V BlockSpec
  index maps read them to DMA exactly the pages each sequence owns —
  the page-table indirection costs no gather/materialization, and Pallas's
  grid pipeline double-buffers the page fetches automatically.
- GQA: q is viewed as ``[batch, kv_heads, group, head_dim]``; each program
  computes all ``group`` q-heads sharing one kv head (group padded to >= 8
  rows so the dot rides the MXU sublane tiling).
- online softmax across pages: m/l and the running (normalized) output are
  carried in outputs whose index maps ignore the page grid dim, so Mosaic
  keeps them VMEM-resident across the inner steps (same revisiting pattern
  as the flash backward's dq accumulator).
- ragged occupancy: a sequence's page loop is masked by its length; pages
  past the last valid one skip compute entirely (``pl.when``) and their DMA
  is clamped onto the last valid page. ``length == 0`` marks an empty slot
  (output rows zero) — the scheduler parks evicted slots that way.

Decode is inference-only: no VJP (the op registers as non-differentiable).
Interpret-capable on CPU like the other Pallas kernels; the jnp
gather-based :func:`paged_attention_reference` is both the numerical oracle
and the non-TPU fallback. Page-size autotune rides the shared
``autotune_cache`` (the page size IS the kernel's kv block size, fixed at
cache construction — see :func:`autotune_page_size`).

Round 9 adds the RAGGED sibling :func:`ragged_paged_attention` — the
unified-step kernel (Ragged Paged Attention, arxiv 2604.15464): each
sequence contributes 1..chunk query tokens per step (decode lanes feed 1,
prefill chunks feed up to ``chunk``), causal within the chunk, online
softmax across that sequence's pages. Query rows for one (sequence,
kv-head) program are laid out ``[chunk * group, head_dim]`` (chunk-major,
GQA group minor) so one MXU dot serves the whole chunk; the per-row causal
limit is ``kv_start + row // group + 1``. The per-step chunk size is a
trace-time constant autotuned on the shared cache
(:func:`preferred_chunk_size` / :func:`autotune_chunk_size`).

SPMD contract (round 11): under the multi-chip serving mesh these kernels
run PER CHIP inside a fully-manual ``shard_map`` over ``Mesh(("mp",))`` —
the caller hands in its chip's head shard of q and the head-sharded page
pools / scale planes, and the grid's ``kv_heads`` dim is simply the local
head count. Heads are embarrassingly parallel in paged attention (each
(slot, head) program reads only its own pages), so no collectives exist
at this level and GSPMD never has to partition the ``pallas_call`` — the
same per-shard discipline as the flash kernel under TP training.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import autotune_cache as _atc

NEG_INF = -1e30

# MXU note (see flash_attention.py): explicit DEFAULT precision keeps bf16
# operands on the native MXU pass under the framework's "highest" default.
_MXU = jax.lax.Precision.DEFAULT


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _dotf32(a, b, dims):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32,
                               precision=_MXU)


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------


def _decode_kernel(lens_ref, pt_ref, q_ref, k_ref, v_ref,
                   o_ref, m_ref, l_ref, *, page_size, scale):
    b = pl.program_id(0)
    j = pl.program_id(2)
    length = lens_ref[b]

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        o_ref[...] = jnp.zeros_like(o_ref)

    @pl.when(j * page_size < length)
    def _accumulate():
        q = q_ref[...]           # [G8, d] input dtype (MXU wants bf16)
        k = k_ref[...]           # [page_size, d] (None block dims dropped)
        v = v_ref[...]
        s = _dotf32(q, k, ((1,), (1,))) * scale          # [G8, ps] f32
        col = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(col < length, s, NEG_INF)
        m_prev = m_ref[...]                               # [G8, 1]
        l_prev = l_ref[...]
        m_next = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next)
        l_next = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        l_safe = jnp.where(l_next == 0.0, 1.0, l_next)
        # running NORMALIZED output (jax paged-attention kernel recurrence):
        # no final rescale pass needed after the last page
        pv = _dotf32(p.astype(v.dtype), v, ((1,), (0,)))  # [G8, d]
        o_ref[...] = ((o_ref[...] * (l_prev * alpha) + pv) / l_safe
                      ).astype(o_ref.dtype)
        m_ref[...] = m_next
        l_ref[...] = l_next


def _kernel_impl(q4, k_pages, v_pages, page_table, lengths, scale):
    """q4: [b, kv_heads, G8, d] (group padded); returns [b, kv_heads, G8, d]
    fp32."""
    b, hkv, g8, d = q4.shape
    num_pages, page_size = k_pages.shape[0], k_pages.shape[1]
    pps = page_table.shape[1]
    grid = (b, hkv, pps)

    def kv_imap(bi, h, j, lens_ref, pt_ref):
        # pages past the sequence's last valid one re-fetch the last valid
        # page (their compute is skipped); empty slots / unallocated (-1)
        # entries clamp to page 0. All-int32 arithmetic: weak python-int
        # constants would promote to i64 under the framework's x64 mode.
        ps = jnp.int32(page_size)
        last = jnp.maximum(
            jax.lax.div(lens_ref[bi] + ps - jnp.int32(1), ps) - jnp.int32(1),
            jnp.int32(0))
        page = pt_ref[bi, jnp.minimum(jnp.int32(j), last)]
        return (jnp.clip(page, 0, num_pages - 1), 0, h, 0)

    q_spec = pl.BlockSpec((None, None, g8, d), lambda bi, h, j, *_: (bi, h, 0, 0))
    kv_spec = pl.BlockSpec((None, page_size, None, d), kv_imap)
    o_spec = pl.BlockSpec((None, None, g8, d), lambda bi, h, j, *_: (bi, h, 0, 0))
    ml_spec = pl.BlockSpec((None, None, g8, 1), lambda bi, h, j, *_: (bi, h, 0, 0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[o_spec, ml_spec, ml_spec],
    )
    out_shape = [
        jax.ShapeDtypeStruct((b, hkv, g8, d), jnp.float32),
        jax.ShapeDtypeStruct((b, hkv, g8, 1), jnp.float32),
        jax.ShapeDtypeStruct((b, hkv, g8, 1), jnp.float32),
    ]
    kern = functools.partial(_decode_kernel, page_size=page_size, scale=scale)
    with _atc.x64_off():
        out, _, _ = pl.pallas_call(
            kern, grid_spec=grid_spec, out_shape=out_shape,
            compiler_params=pltpu.TPUCompilerParams(
                dimension_semantics=("parallel", "arbitrary", "arbitrary")),
            interpret=_interpret(),
        )(lengths.astype(jnp.int32), page_table.astype(jnp.int32),
          q4, k_pages, v_pages)
    return out


# ---------------------------------------------------------------------------
# jnp gather-based reference (oracle + non-TPU fallback + bench baseline)
# ---------------------------------------------------------------------------


def paged_attention_reference(q, k_pages, v_pages, page_table, lengths,
                              scale=None):
    """Gather the paged cache into a contiguous view and run masked decode
    attention — what a non-paged XLA implementation would do (one gather of
    ``pages_per_seq * page_size`` positions per sequence, materialized in
    HBM). Numerically the oracle for the kernel; also the measured baseline
    ``bench_serve.py`` compares the kernel against.

    q: [b, num_q_heads, d]; k/v_pages: [num_pages, page_size, kv_heads, d];
    page_table: [b, pages_per_seq] int; lengths: [b] int (0 = empty slot).
    Returns [b, num_q_heads, d] in q's dtype.
    """
    b, hq, d = q.shape
    num_pages, page_size, hkv, _ = k_pages.shape
    pps = page_table.shape[1]
    group = hq // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    pt = jnp.clip(page_table, 0, num_pages - 1)
    # [b, pps, ps, hkv, d] -> [b, S, hkv, d]
    k = k_pages[pt].reshape(b, pps * page_size, hkv, d)
    v = v_pages[pt].reshape(b, pps * page_size, hkv, d)
    qg = q.reshape(b, hkv, group, d)
    s = jnp.einsum("bhgd,bshd->bhgs", qg.astype(jnp.float32),
                   k.astype(jnp.float32),
                   precision=_MXU) * scale
    valid = (jnp.arange(pps * page_size)[None, :]
             < lengths.reshape(-1, 1))            # [b, S]
    s = jnp.where(valid[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # empty slots (length 0): all-masked softmax is uniform garbage — zero it
    p = jnp.where((lengths > 0).reshape(-1, 1, 1, 1), p, 0.0)
    out = jnp.einsum("bhgs,bshd->bhgd", p, v.astype(jnp.float32),
                     precision=_MXU)
    return out.reshape(b, hq, d).astype(q.dtype)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------


def use_kernel_default() -> bool:
    return jax.default_backend() == "tpu"


def paged_attention(q, k_pages, v_pages, page_table, lengths, scale=None,
                    use_kernel: bool | None = None):
    """Decode attention over the paged KV cache.

    ``use_kernel``: None = Pallas kernel on TPU, jnp reference elsewhere;
    True forces the kernel (interpret mode off-TPU — CPU tests); False
    forces the reference. See :func:`paged_attention_reference` for shapes.
    """
    b, hq, d = q.shape
    hkv = k_pages.shape[2]
    assert hq % hkv == 0, f"GQA needs q heads {hq} divisible by kv {hkv}"
    assert k_pages.shape == v_pages.shape
    assert page_table.shape[0] == b and lengths.shape == (b,)
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if use_kernel is None:
        use_kernel = use_kernel_default()
    if not use_kernel:
        return paged_attention_reference(q, k_pages, v_pages, page_table,
                                         lengths, scale=scale)
    group = hq // hkv
    # pad the GQA group to >= 8 rows (MXU sublane tile); padded q rows are
    # zeros — they compute garbage that the final slice drops
    g8 = max(8, ((group + 7) // 8) * 8)
    q4 = q.reshape(b, hkv, group, d)
    if g8 != group:
        q4 = jnp.pad(q4, ((0, 0), (0, 0), (0, g8 - group), (0, 0)))
    out = _kernel_impl(q4, k_pages, v_pages, page_table, lengths,
                       float(scale))
    out = out[:, :, :group, :].reshape(b, hq, d)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# ragged kernel: 1..chunk query tokens per sequence, causal within the chunk
# ---------------------------------------------------------------------------


def _ragged_kernel(lens_ref, qlens_ref, pt_ref, q_ref, k_ref, v_ref,
                   *refs, page_size, group, scale, quant=False):
    """``quant=False``: refs = (o, m, l) and K/V tiles arrive in the
    compute dtype. ``quant=True`` (round-10 int8 KV): refs = (ks, vs, o,
    m, l) — the page tiles arrive int8 with their per-(slot, head) scale
    columns ([page_size, 1] blocks of the scale plane) and dequantize in
    VMEM on the way into the two dots; the online-softmax recurrence is
    IDENTICAL (one body, so the paths cannot drift)."""
    if quant:
        ks_ref, vs_ref, o_ref, m_ref, l_ref = refs
    else:
        o_ref, m_ref, l_ref = refs
    b = pl.program_id(0)
    j = pl.program_id(2)
    kv_len = lens_ref[b]     # context INCLUDING this chunk's tokens
    q_len = qlens_ref[b]     # valid query tokens this step (0 = idle lane)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        o_ref[...] = jnp.zeros_like(o_ref)

    @pl.when((j * page_size < kv_len) & (q_len > 0))
    def _accumulate():
        q = q_ref[...]           # [R, d] rows = chunk-major * group-minor
        k = k_ref[...]           # [page_size, d]
        v = v_ref[...]
        if quant:
            k = (k.astype(jnp.float32) * ks_ref[...]).astype(q.dtype)
            v = (v.astype(jnp.float32) * vs_ref[...]).astype(q.dtype)
        s = _dotf32(q, k, ((1,), (1,))) * scale          # [R, ps] f32
        col = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        # row r serves query token r // group: it may attend every key up
        # to and including its own position kv_start + r // group
        qi = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // group
        limit = (kv_len - q_len) + qi + jnp.int32(1)
        s = jnp.where(col < jnp.minimum(limit, kv_len), s, NEG_INF)
        m_prev = m_ref[...]                               # [R, 1]
        l_prev = l_ref[...]
        m_next = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next)
        l_next = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        l_safe = jnp.where(l_next == 0.0, 1.0, l_next)
        pv = _dotf32(p.astype(v.dtype), v, ((1,), (0,)))  # [R, d]
        o_ref[...] = ((o_ref[...] * (l_prev * alpha) + pv) / l_safe
                      ).astype(o_ref.dtype)
        m_ref[...] = m_next
        l_ref[...] = l_next


def _ragged_kernel_impl(q4, k_pages, v_pages, page_table, kv_lens, q_lens,
                        group, scale, k_scales=None, v_scales=None):
    """q4: [b, kv_heads, R, d] with R = chunk*group padded to the sublane
    tile; returns [b, kv_heads, R, d] fp32. ``k_scales``/``v_scales``
    ([num_pages, page_size, kv_heads] or None) flip the int8-KV kernel."""
    b, hkv, r8, d = q4.shape
    num_pages, page_size = k_pages.shape[0], k_pages.shape[1]
    pps = page_table.shape[1]
    grid = (b, hkv, pps)
    quant = k_scales is not None

    def kv_page(bi, h, j, lens_ref, qlens_ref, pt_ref):
        # identical clamping to the decode kernel: pages past the last
        # valid one re-fetch it (their compute is skipped)
        ps = jnp.int32(page_size)
        last = jnp.maximum(
            jax.lax.div(lens_ref[bi] + ps - jnp.int32(1), ps) - jnp.int32(1),
            jnp.int32(0))
        page = pt_ref[bi, jnp.minimum(jnp.int32(j), last)]
        return jnp.clip(page, 0, num_pages - 1)

    def kv_imap(bi, h, j, *refs):
        return (kv_page(bi, h, j, *refs), 0, h, 0)

    def scale_imap(bi, h, j, *refs):
        return (kv_page(bi, h, j, *refs), 0, h)

    q_spec = pl.BlockSpec((None, None, r8, d), lambda bi, h, j, *_: (bi, h, 0, 0))
    kv_spec = pl.BlockSpec((None, page_size, None, d), kv_imap)
    sc_spec = pl.BlockSpec((None, page_size, 1), scale_imap)
    o_spec = pl.BlockSpec((None, None, r8, d), lambda bi, h, j, *_: (bi, h, 0, 0))
    ml_spec = pl.BlockSpec((None, None, r8, 1), lambda bi, h, j, *_: (bi, h, 0, 0))

    in_specs = [q_spec, kv_spec, kv_spec]
    args = [q4, k_pages, v_pages]
    if quant:
        in_specs += [sc_spec, sc_spec]
        args += [k_scales.astype(jnp.float32), v_scales.astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=grid,
        in_specs=in_specs,
        out_specs=[o_spec, ml_spec, ml_spec],
    )
    out_shape = [
        jax.ShapeDtypeStruct((b, hkv, r8, d), jnp.float32),
        jax.ShapeDtypeStruct((b, hkv, r8, 1), jnp.float32),
        jax.ShapeDtypeStruct((b, hkv, r8, 1), jnp.float32),
    ]
    kern = functools.partial(_ragged_kernel, page_size=page_size,
                             group=group, scale=scale, quant=quant)
    with _atc.x64_off():
        out, _, _ = pl.pallas_call(
            kern, grid_spec=grid_spec, out_shape=out_shape,
            compiler_params=pltpu.TPUCompilerParams(
                dimension_semantics=("parallel", "arbitrary", "arbitrary")),
            interpret=_interpret(),
        )(kv_lens.astype(jnp.int32), q_lens.astype(jnp.int32),
          page_table.astype(jnp.int32), *args)
    return out


def ragged_paged_attention_reference(q, k_pages, v_pages, page_table,
                                     kv_lens, q_lens, scale=None,
                                     k_scales=None, v_scales=None):
    """Gather-based oracle for the ragged kernel (and the non-TPU path).

    q: [b, chunk, num_q_heads, d] right-padded query chunks; kv_lens: [b]
    context length per slot INCLUDING this chunk; q_lens: [b] valid query
    rows (0 = idle lane — its output rows are zero). Query token t of slot
    b sits at absolute position ``kv_lens[b] - q_lens[b] + t`` and attends
    all keys at positions <= its own. With ``k_scales``/``v_scales``
    ([num_pages, page_size, kv_heads]) the pages are int8 and dequantize
    after the gather. Returns [b, chunk, num_q_heads, d].
    """
    b, c, hq, d = q.shape
    num_pages, page_size, hkv, _ = k_pages.shape
    pps = page_table.shape[1]
    group = hq // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    pt = jnp.clip(page_table, 0, num_pages - 1)
    k = k_pages[pt].reshape(b, pps * page_size, hkv, d)
    v = v_pages[pt].reshape(b, pps * page_size, hkv, d)
    if k_scales is not None:
        k = (k.astype(jnp.float32)
             * k_scales[pt].reshape(b, pps * page_size, hkv)[..., None])
        v = (v.astype(jnp.float32)
             * v_scales[pt].reshape(b, pps * page_size, hkv)[..., None])
    qg = q.reshape(b, c, hkv, group, d)
    s = jnp.einsum("bchgd,bshd->bhgcs", qg.astype(jnp.float32),
                   k.astype(jnp.float32), precision=_MXU) * scale
    kv_start = (kv_lens - q_lens).reshape(-1, 1, 1)              # [b,1,1]
    limit = kv_start + jnp.arange(c).reshape(1, -1, 1) + 1       # [b,c,1]
    col = jnp.arange(pps * page_size).reshape(1, 1, -1)
    valid = ((col < jnp.minimum(limit, kv_lens.reshape(-1, 1, 1)))
             & (jnp.arange(c).reshape(1, -1, 1) < q_lens.reshape(-1, 1, 1)))
    s = jnp.where(valid[:, None, None], s, NEG_INF)              # [b,h,g,c,s]
    p = jax.nn.softmax(s, axis=-1)
    # fully-masked rows (idle lanes / padding past q_lens): softmax is
    # uniform garbage — zero them
    p = jnp.where(valid[:, None, None], p, 0.0)
    out = jnp.einsum("bhgcs,bshd->bchgd", p, v.astype(jnp.float32),
                     precision=_MXU)
    return out.reshape(b, c, hq, d).astype(q.dtype)


def ragged_paged_attention(q, k_pages, v_pages, page_table, kv_lens, q_lens,
                           scale=None, use_kernel: bool | None = None,
                           k_scales=None, v_scales=None):
    """Ragged prefill+decode attention over the paged KV cache.

    The unified-step entry: each slot contributes ``q_lens[b]`` (0..chunk)
    query tokens this step, causal within the chunk, attending that slot's
    whole paged context (``kv_lens[b]`` tokens, chunk included — the
    chunk's K/V must already be written to the pages). ``use_kernel`` as in
    :func:`paged_attention`. Rows past ``q_lens`` are garbage the caller
    must ignore (their page writes drop; the reference zeroes them).
    ``k_scales``/``v_scales`` ([num_pages, page_size, kv_heads]) mark the
    pools int8 (round-10 quantized KV); dequantization fuses into the
    kernel's page loop (or the gathered reference) — pages stay int8
    end-to-end in HBM.
    """
    b, c, hq, d = q.shape
    hkv = k_pages.shape[2]
    assert hq % hkv == 0, f"GQA needs q heads {hq} divisible by kv {hkv}"
    assert k_pages.shape == v_pages.shape
    assert page_table.shape[0] == b
    assert kv_lens.shape == (b,) and q_lens.shape == (b,)
    assert (k_scales is None) == (v_scales is None)
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if use_kernel is None:
        use_kernel = use_kernel_default()
    if not use_kernel:
        return ragged_paged_attention_reference(
            q, k_pages, v_pages, page_table, kv_lens, q_lens, scale=scale,
            k_scales=k_scales, v_scales=v_scales)
    group = hq // hkv
    # rows = chunk-major, group-minor: [b, c, hkv, g, d] -> [b, hkv, c*g, d]
    q4 = q.reshape(b, c, hkv, group, d).transpose(0, 2, 1, 3, 4)
    q4 = q4.reshape(b, hkv, c * group, d)
    r8 = max(8, ((c * group + 7) // 8) * 8)
    if r8 != c * group:
        q4 = jnp.pad(q4, ((0, 0), (0, 0), (0, r8 - c * group), (0, 0)))
    out = _ragged_kernel_impl(q4, k_pages, v_pages, page_table, kv_lens,
                              q_lens, group, float(scale),
                              k_scales=k_scales, v_scales=v_scales)
    out = out[:, :, :c * group, :].reshape(b, hkv, c, group, d)
    out = out.transpose(0, 2, 1, 3, 4).reshape(b, c, hq, d)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# page-size autotune (rides the shared autotune cache)
# ---------------------------------------------------------------------------

PAGE_SIZE_DEFAULT = 64


def _sig(hq, hkv, d, dtype) -> str:
    return f"paged:{hq}h{hkv}x{d}:{jnp.dtype(dtype).name}:page_size"


def preferred_page_size(hq, hkv, d, dtype=jnp.bfloat16) -> int:
    """The autotuned page size for this head geometry (or the default).
    ``KVCacheManager(page_size=None)`` consults this, so a swept winner
    changes the cache layout the next time a cache is built."""
    hit = _atc.lookup(_sig(hq, hkv, d, dtype))
    return int(hit[0]) if hit else PAGE_SIZE_DEFAULT


def autotune_page_size(batch, hq, hkv, d, max_len=2048, dtype=jnp.bfloat16,
                       candidates=(16, 32, 64, 128), iters=5):
    """Sweep the cache page size on the current device and persist the
    winner (process + disk via the shared autotune cache).

    Page size is a TRACE-TIME cache-layout constant (it shapes the page
    pool and the kernel's kv block), so like the flash block sweep this is
    an explicit eager call to run once before building caches; the winner
    then flows through :func:`preferred_page_size`. Returns the page size.
    """
    from ...observability import monotonic

    if _interpret():
        return preferred_page_size(hq, hkv, d, dtype)
    _atc.load()
    sig = _sig(hq, hkv, d, dtype)
    # one subkey per operand: a shared key makes q/k/v correlated streams,
    # degenerating the softmax the sweep times
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (batch, hq, d), dtype)
    best, best_t = None, float("inf")
    for ps in candidates:
        pps = (max_len + ps - 1) // ps
        num_pages = batch * pps + 1
        kp = jax.random.normal(kk, (num_pages, ps, hkv, d), dtype)
        vp = jax.random.normal(kv, (num_pages, ps, hkv, d), dtype)
        pt = jnp.arange(batch * pps, dtype=jnp.int32).reshape(batch, pps)
        lens = jnp.full((batch,), max_len, jnp.int32)
        try:
            step = jax.jit(functools.partial(paged_attention,
                                             use_kernel=True))
            step(q, kp, vp, pt, lens).block_until_ready()  # compile+warmup
            t0 = monotonic()
            for _ in range(iters):
                out = step(q, kp, vp, pt, lens)
            out.block_until_ready()
            t = monotonic() - t0
        except Exception:
            continue
        if t < best_t:
            best, best_t = ps, t
    if best is not None:
        _atc.CACHE[sig] = [int(best)]
        _atc.save()
        return best
    return preferred_page_size(hq, hkv, d, dtype)


# ---------------------------------------------------------------------------
# chunk-size autotune (the unified step's per-slot query-chunk width)
# ---------------------------------------------------------------------------

CHUNK_DEFAULT = 16


def _chunk_sig(hq, hkv, d, dtype) -> str:
    return f"ragged:{hq}h{hkv}x{d}:{jnp.dtype(dtype).name}:chunk"


def preferred_chunk_size(hq, hkv, d, dtype=jnp.bfloat16) -> int:
    """The autotuned unified-step chunk size for this head geometry (or the
    default). Chunk is a TRACE-TIME shape constant of the unified step jit
    (its [batch, chunk] query block), so like the page size it is consulted
    once when the serving step is built."""
    hit = _atc.lookup(_chunk_sig(hq, hkv, d, dtype))
    return int(hit[0]) if hit else CHUNK_DEFAULT


def autotune_chunk_size(batch, hq, hkv, d, max_len=2048, page_size=None,
                        dtype=jnp.bfloat16, candidates=(8, 16, 32, 64),
                        iters=5):
    """Sweep the ragged kernel's chunk width on the current device and
    persist the winner on the shared autotune cache. The sweep times a
    mixed step (half the lanes decode 1 token, half prefill a full chunk —
    the steady-state unified-step shape). Returns the chunk size."""
    from ...observability import monotonic

    if _interpret():
        return preferred_chunk_size(hq, hkv, d, dtype)
    _atc.load()
    sig = _chunk_sig(hq, hkv, d, dtype)
    ps = page_size or preferred_page_size(hq, hkv, d, dtype)
    pps = (max_len + ps - 1) // ps
    num_pages = batch * pps + 1
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    kp = jax.random.normal(kk, (num_pages, ps, hkv, d), dtype)
    vp = jax.random.normal(kv, (num_pages, ps, hkv, d), dtype)
    pt = jnp.arange(batch * pps, dtype=jnp.int32).reshape(batch, pps)
    best, best_t = None, float("inf")
    for chunk in candidates:
        q = jax.random.normal(kq, (batch, chunk, hq, d), dtype)
        # mixed ragged step: even lanes decode (1 token), odd lanes carry a
        # full prefill chunk
        q_lens = jnp.where(jnp.arange(batch) % 2 == 0, 1, chunk
                           ).astype(jnp.int32)
        kv_lens = jnp.full((batch,), max_len, jnp.int32)
        try:
            step = jax.jit(functools.partial(ragged_paged_attention,
                                             use_kernel=True))
            step(q, kp, vp, pt, kv_lens, q_lens).block_until_ready()
            t0 = monotonic()
            for _ in range(iters):
                out = step(q, kp, vp, pt, kv_lens, q_lens)
            out.block_until_ready()
            # normalize per useful token: bigger chunks do more work/step
            t = (monotonic() - t0) / float(q_lens.sum())
        except Exception:
            continue
        if t < best_t:
            best, best_t = chunk, t
    if best is not None:
        _atc.CACHE[sig] = [int(best)]
        _atc.save()
        return best
    return preferred_chunk_size(hq, hkv, d, dtype)
