"""Pallas TPU kernels."""
