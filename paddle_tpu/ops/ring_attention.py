"""Context parallelism: ring (blockwise) attention + Ulysses all-to-all.

The reference has NO ring/Ulysses context parallelism (SURVEY.md §5.7 —
grep-verified absent); its long-context story is the ``sep`` mesh axis +
Megatron-SP + per-device FlashAttention. This module is the TPU-native design
that fills that gap and makes the sep axis actually scale sequence length:

- **Ring attention**: Q stays resident; K/V chunks rotate around the mesh
  axis via ``lax.ppermute`` (ICI neighbor exchange). Per-chunk attention uses
  the Pallas flash kernel (or an XLA fallback off-TPU), partial results are
  combined with the online-softmax identity ``o = Σ exp(lse_i - lse) o_i``.
  A custom VJP re-rotates K/V during backward and rotates (dK, dV)
  accumulators along with them, so per-device memory stays O(seq/n) in both
  passes — the property that makes million-token contexts possible.
- **Ulysses**: ``lax.all_to_all`` swaps the sharded dim seq<->heads, runs
  *local* flash attention over the full sequence with heads/n heads, and
  swaps back. Cheaper comm volume than ring at moderate seq, requires
  heads % n == 0.

Both are per-device (shard_map) functions plus global-view conveniences.
Causal ring uses a branch per chunk relation (full / diagonal / skip): ranks
holding future chunks skip compute entirely, matching the cost profile of
load-balanced ring schedules within one lax.cond instead of re-sharding.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .pallas.flash_attention import _flash_fwd_impl, flash_bwd_impl

_NEG_INF = -1e30


def _axis_size(axis_name) -> int:
    return jax.lax.psum(1, axis_name)


def _use_pallas(q, k) -> bool:
    if jax.default_backend() != "tpu":
        return False
    _, sq, d = q.shape
    sk = k.shape[1]
    return d % 64 == 0 and sq % 128 == 0 and sk % 128 == 0


# ---------------------------------------------------------------------------
# per-chunk fwd/bwd (kernel layout [bh, s, d]); lse/delta carried as [bh, s]
# ---------------------------------------------------------------------------


def _chunk_fwd(q, k, v, scale, causal):
    """(out fp32 [bh,sq,d], lse fp32 [bh,sq]) for one KV chunk."""
    if _use_pallas(q, k):
        out, lse = _flash_fwd_impl(q, k, v, None, None, scale, causal, 1)
        return out.astype(jnp.float32), lse[:, 0, :]
    qf = q.astype(jnp.float32) * scale
    s = jnp.einsum("bqd,bkd->bqk", qf, k.astype(jnp.float32))
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), sk - sq)
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)) / l[..., None]
    return out, m + jnp.log(l)


def _chunk_bwd(q, k, v, do, lse, delta, scale, causal):
    """Exact chunk backward from *global* lse/delta ([bh, sq] fp32).

    Identity: with p = exp(s - lse_global), ds = p * (do v^T - delta); no
    per-chunk renormalization needed. Returns fp32 (dq, dk, dv).
    """
    if _use_pallas(q, k):
        dq, dk, dv = flash_bwd_impl(
            q, k, v, do.astype(q.dtype), lse[:, None, :], delta[:, None, :],
            scale, causal,
        )
        return (
            dq.astype(jnp.float32),
            dk.astype(jnp.float32),
            dv.astype(jnp.float32),
        )
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    s = scale * jnp.einsum("bqd,bkd->bqk", qf, kf)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), sk - sq)
        s = jnp.where(mask, s, _NEG_INF)
    p = jnp.exp(s - lse[..., None])
    dv = jnp.einsum("bqk,bqd->bkd", p, dof)
    dp = jnp.einsum("bqd,bkd->bqk", dof, vf)
    ds = p * (dp - delta[..., None])
    dq = scale * jnp.einsum("bqk,bkd->bqd", ds, kf)
    dk = scale * jnp.einsum("bqk,bqd->bkd", ds, qf)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# ring loop (inside shard_map), custom VJP
# ---------------------------------------------------------------------------


def _ring_perm(n):
    return [(j, (j + 1) % n) for j in range(n)]


def _causal_branch(src, idx):
    """0 = full chunk (src strictly past), 1 = diagonal (causal), 2 = skip."""
    return jnp.where(src == idx, 1, jnp.where(src < idx, 0, 2))


def _ring_fwd_scan(q, k, v, axis_name, scale, causal):
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    perm = _ring_perm(n)
    bh, sq, d = q.shape

    def compute(kv, causal_flag):
        return _chunk_fwd(q, kv[0], kv[1], scale, causal_flag)

    def skip(kv):
        return (
            jnp.zeros((bh, sq, d), jnp.float32),
            jnp.full((bh, sq), _NEG_INF, jnp.float32),
        )

    def compute_t(k_cur, v_cur, t):
        if causal:
            branch = _causal_branch((idx - t) % n, idx)
            return lax.switch(
                branch,
                [
                    lambda kv: compute(kv, False),
                    lambda kv: compute(kv, True),
                    skip,
                ],
                (k_cur, v_cur),
            )
        return compute((k_cur, v_cur), False)

    def combine(o, lse, o_t, lse_t):
        lse_new = jnp.logaddexp(lse, lse_t)
        w_old = jnp.exp(lse - lse_new)
        w_new = jnp.exp(lse_t - lse_new)
        return o * w_old[..., None] + o_t * w_new[..., None], lse_new

    def step(carry, t):
        k_cur, v_cur, o, lse = carry
        o, lse = combine(o, lse, *compute_t(k_cur, v_cur, t))
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        return (k_cur, v_cur, o, lse), None

    o0 = jnp.zeros((bh, sq, d), jnp.float32)
    lse0 = jnp.full((bh, sq), _NEG_INF, jnp.float32)
    # last hop unrolled without the (discarded) rotation: n-1 transfers total
    (k_cur, v_cur, o, lse), _ = lax.scan(
        step, (k, v, o0, lse0), jnp.arange(n - 1)
    )
    o, lse = combine(o, lse, *compute_t(k_cur, v_cur, n - 1))
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring(q, k, v, axis_name, scale, causal):
    o, _ = _ring_fwd_scan(q, k, v, axis_name, scale, causal)
    return o


def _ring_fwd(q, k, v, axis_name, scale, causal):
    o, lse = _ring_fwd_scan(q, k, v, axis_name, scale, causal)
    return o, (q, k, v, o, lse)


def _ring_bwd(axis_name, scale, causal, res, do):
    q, k, v, o, lse = res
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    perm = _ring_perm(n)
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )  # [bh, sq]
    bh, sq, d = q.shape
    sk = k.shape[1]

    def compute(kv, causal_flag):
        return _chunk_bwd(q, kv[0], kv[1], do, lse, delta, scale, causal_flag)

    def skip(kv):
        z = jnp.zeros((bh, sq, d), jnp.float32)
        zk = jnp.zeros((bh, sk, d), jnp.float32)
        return z, zk, zk

    def compute_t(k_cur, v_cur, t):
        if causal:
            branch = _causal_branch((idx - t) % n, idx)
            return lax.switch(
                branch,
                [
                    lambda kv: compute(kv, False),
                    lambda kv: compute(kv, True),
                    skip,
                ],
                (k_cur, v_cur),
            )
        return compute((k_cur, v_cur), False)

    def step(carry, t):
        k_cur, v_cur, dk_cur, dv_cur, dq = carry
        dq_t, dk_t, dv_t = compute_t(k_cur, v_cur, t)
        dq = dq + dq_t
        dk_cur = dk_cur + dk_t
        dv_cur = dv_cur + dv_t
        # rotate KV together with its accumulated grads; after n rotations
        # each chunk's (dk, dv) lands back on the chunk's home device
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        dk_cur = lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = lax.ppermute(dv_cur, axis_name, perm)
        return (k_cur, v_cur, dk_cur, dv_cur, dq), None

    z = jnp.zeros((bh, sk, d), jnp.float32)
    dq0 = jnp.zeros((bh, sq, d), jnp.float32)
    (k_cur, v_cur, dk, dv, dq), _ = lax.scan(
        step, (k, v, z, z, dq0), jnp.arange(n - 1)
    )
    # final hop: compute, then rotate only the grad accumulators home —
    # the K/V rotation would be discarded
    dq_t, dk_t, dv_t = compute_t(k_cur, v_cur, n - 1)
    dq = dq + dq_t
    dk = lax.ppermute(dk + dk_t, axis_name, perm)
    dv = lax.ppermute(dv + dv_t, axis_name, perm)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring.defvjp(_ring_fwd, _ring_bwd)


def _to_bhsd(x):
    """[b, s, h, d] -> [b*h, s, d] (kernel layout)."""
    b, s, h, d = x.shape
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, d)


def _from_bhsd(x, b, h):
    bh, s, d = x.shape
    return jnp.transpose(x.reshape(b, h, s, d), (0, 2, 1, 3))


def ring_attention_local(q, k, v, axis_name, causal=False, scale=None):
    """Ring attention, per-device view (call inside shard_map/pjit-manual).

    q/k/v: [batch, seq_local, heads, head_dim] — the local sequence shard.
    The *global* sequence is the concatenation over ``axis_name`` in rank
    order; causal masking is applied w.r.t. global positions. Differentiable;
    backward is a second ring pass (memory O(seq/n) per device).
    """
    b, s, h, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    out = _ring(
        _to_bhsd(q), _to_bhsd(k), _to_bhsd(v), axis_name, float(scale),
        bool(causal),
    )
    return _from_bhsd(out, b, h)


def ulysses_attention_local(q, k, v, axis_name, causal=False, scale=None):
    """Ulysses (all-to-all) attention, per-device view.

    q/k/v: [batch, seq_local, heads, head_dim]; requires heads % n == 0.
    all-to-all reshards seq->heads, local attention sees the full sequence
    with heads/n heads, then reshards back. Differentiable (all_to_all has a
    transpose rule).
    """
    b, s, h, d = q.shape
    n = _axis_size(axis_name)
    if h % n != 0:
        raise ValueError(f"ulysses needs heads % axis size == 0, got {h} % {n}")
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    def swap_in(x):  # [b, s/n, h, d] -> [b, s, h/n, d]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    q, k, v = swap_in(q), swap_in(k), swap_in(v)
    qt, kt, vt = _to_bhsd(q), _to_bhsd(k), _to_bhsd(v)
    if _use_pallas(qt, kt):
        from .pallas.flash_attention import _flash

        out = _flash(qt, kt, vt, None, None, float(scale), bool(causal), 1)
    else:
        o32, _ = _chunk_fwd(qt, kt, vt, float(scale), bool(causal))
        out = o32.astype(qt.dtype)
    out = _from_bhsd(out, b, h // n)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


# ---------------------------------------------------------------------------
# global-view conveniences
# ---------------------------------------------------------------------------


def _global_cp(fn_local, q, k, v, mesh, seq_axis, causal, scale, batch_axis):
    spec = P(batch_axis, seq_axis, None, None)
    shard = jax.shard_map(
        functools.partial(fn_local, axis_name=seq_axis, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return shard(q, k, v)


def ring_attention(q, k, v, mesh: Mesh, seq_axis: str = "sep", causal=False,
                   scale=None, batch_axis: str | None = None):
    """Global-view ring attention: q/k/v [b, s, h, d] jax arrays; the s dim is
    sharded over ``mesh[seq_axis]`` (and optionally b over ``batch_axis``)."""
    return _global_cp(
        ring_attention_local, q, k, v, mesh, seq_axis, causal, scale, batch_axis
    )


def ulysses_attention(q, k, v, mesh: Mesh, seq_axis: str = "sep", causal=False,
                      scale=None, batch_axis: str | None = None):
    """Global-view Ulysses attention (see ``ulysses_attention_local``)."""
    return _global_cp(
        ulysses_attention_local, q, k, v, mesh, seq_axis, causal, scale, batch_axis
    )
