"""Host-span tracing API — the serving/training timeline half of
``paddle_tpu/observability`` (round 15).

Thin, hot-path-safe wrappers over the profiler's one in-process event
buffer (``profiler/record.py``):

- :func:`span` — a named host range (``with span("pack_dispatch"): ...``)
  recorded as a Chrome ``X`` duration event when a profiler RECORD window
  is open, and nested inside a ``jax.profiler.TraceAnnotation`` so the
  host range lines up with device activity in an xplane/TensorBoard
  capture (host/device correlation). When no window is open the call
  returns a shared no-op context manager — one flag check, no allocation.
- :func:`request_begin` / :func:`request_event` / :func:`request_end` —
  per-request ASYNC span lanes (Chrome ``b``/``n``/``e`` phases matched
  by ``(category, id, name)``): one lane per request showing its whole
  lifecycle (admit → prefill chunks → decode/spec steps → preemption /
  replay → eos) across the scheduler steps that interleave it.
- :func:`counter_event` — a Chrome counter track (``C`` phase): scalar
  series over time (the async engine's in-flight ring depth).
- :func:`monotonic` / :func:`monotonic_ns` — THE timing clock for
  ``paddle_tpu/inference`` and ``paddle_tpu/distributed`` (tpulint AL006
  flags raw ``time.perf_counter()`` there; timing belongs to this layer
  so instrumented durations and trace timestamps share one clock).

Everything exports through the existing profiler facade: run under
``profiler.Profiler`` (or anything that flips ``recorder.enabled``) and
``export_chrome_tracing`` writes one trace with the op ranges, the
serving spans and the request lanes together.
"""
from __future__ import annotations

import contextlib
import time

from ..profiler.record import now_ns, recorder

__all__ = [
    "span", "request_begin", "request_event", "request_end",
    "counter_event", "tracing_active", "monotonic", "monotonic_ns",
    "device_annotation", "set_device_tracing",
]

monotonic = time.perf_counter
monotonic_ns = time.perf_counter_ns


def tracing_active() -> bool:
    """True while a profiler RECORD window is open (spans are recorded)."""
    return recorder.enabled


#: shared no-op context manager — the disabled fast path (re-enterable;
#: no caller binds the span value)
_NULL = contextlib.nullcontext()


#: flipped by the profiler facade while a jax/PJRT xplane capture is live;
#: spans only pay the TraceAnnotation (C++ TraceMe) when a device trace
#: can actually consume it — host-only tracing stays append-cheap
_DEVICE_TRACING = [False]


def set_device_tracing(active: bool) -> None:
    _DEVICE_TRACING[0] = bool(active)


def device_annotation(name: str):
    """``jax.profiler.TraceAnnotation`` while a device (xplane) capture is
    live — host ranges then correlate with device lanes in the capture
    viewed next to the chrome trace; the shared no-op otherwise."""
    if not _DEVICE_TRACING[0]:
        return _NULL
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return _NULL


class _Span:
    __slots__ = ("name", "category", "_start", "_ann")

    def __init__(self, name, category):
        self.name = name
        self.category = category
        self._start = None
        self._ann = None

    def __enter__(self):
        self._ann = device_annotation(self.name)
        self._ann.__enter__()
        self._start = now_ns()
        return self

    def __exit__(self, *exc):
        end = now_ns()
        if self._start is not None:
            recorder.record(self.name, self._start, end,
                            category=self.category)
            self._start = None
        ann, self._ann = self._ann, None
        if ann is not None:
            ann.__exit__(*exc)
        return False


def span(name: str, category: str = "serving"):
    """A named host range. One flag check + shared no-op when no profiler
    window is open; a recorded ``X`` event (and a device-side
    TraceAnnotation) when one is."""
    if not recorder.enabled:
        return _NULL
    return _Span(name, category)


# -- per-request async lanes -------------------------------------------------

#: async lane name shared by every request span; Chrome matches b/n/e
#: phases on (category, id, name), so the id (req_id) is the lane key
REQUEST_SPAN = "request"
_REQ_CAT = "request"


def request_begin(req_id, args=None) -> bool:
    """Open the async lifecycle lane of one request. Returns whether the
    begin was recorded — the caller gates matching ``request_end`` on it
    (an ``e`` with no ``b`` renders as an unmatched phase)."""
    if not recorder.enabled:
        return False
    recorder.record_raw(REQUEST_SPAN, "b", id=req_id, category=_REQ_CAT,
                        args=args)
    return True


def request_event(req_id, name: str, args=None) -> None:
    """An instant on one request's lane (admit / prefill_chunk / decode /
    preempt / spec_accept / eos ...)."""
    if not recorder.enabled:
        return
    recorder.record_raw(name, "n", id=req_id, category=_REQ_CAT, args=args)


def request_end(req_id, args=None) -> None:
    if not recorder.enabled:
        return
    recorder.record_raw(REQUEST_SPAN, "e", id=req_id, category=_REQ_CAT,
                        args=args)


def counter_event(name: str, value) -> None:
    """One sample on a Chrome counter track (``C`` phase)."""
    if not recorder.enabled:
        return
    recorder.record_raw(name, "C", category="counter",
                        args={"value": float(value)})
