"""Fleet-level telemetry instruments (round 18; round 20 adds the
disaggregated prefill/decode transfer counters — wire frames/bytes/
tokens/retries, drop/corruption detection, fallback accounting and the
sender-side backlog gauge).

The metrics surface of the multi-replica serving fleet
(``inference/fleet_serving.py``): one :class:`FleetInstruments` bundle
declares the router's counters/gauges on a :class:`MetricsRegistry` —
submission/terminal accounting (the chaos gate's partition invariant
reads these), routing quality (affinity hits over routed admissions),
and the failure-domain counters (failovers, crashes, stalls, restarts,
sheds, deadline misses). Per-replica token emission is ONE labeled
counter family (``fleet_tokens_emitted{replica=...}``) so the bench's
per-replica tokens/s falls out of the flat snapshot without the router
keeping ad-hoc per-replica state.

Same cost contract as the serving instruments: the registry defaults to
enabled (these counters ARE the fleet bench metrics); a disabled
registry costs one flag check per mutation.
"""
from __future__ import annotations

from .metrics import MetricsRegistry

__all__ = ["FleetInstruments"]


class FleetInstruments:
    """The fleet router's instrument bundle on one registry.

    The names are the flat-snapshot schema ARCHITECTURE.md's round-18
    section documents; ``bench_serve.py``'s ``fleet-churn`` leg rides
    :meth:`snapshot_flat` as its schema-checked ``telemetry`` object.
    """

    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        m = self.registry
        # -- request accounting: submitted == finished + failed + live --
        self.submitted = m.counter(
            "fleet_requests_submitted", "requests accepted by submit()")
        self.finished = m.counter(
            "fleet_requests_finished", "fleet requests reaching FINISHED")
        self.failed = m.counter(
            "fleet_requests_failed", "fleet requests reaching FAILED")
        self.fail_reasons = m.counter(
            "fleet_fail_reasons", "terminal fleet failures by error code",
            labels=("reason",))
        self.shed = m.counter(
            "fleet_requests_shed",
            "submissions shed because every healthy replica's SLO said no")
        self.deadline_misses = m.counter(
            "fleet_deadline_misses",
            "unrouted requests failed past their deadline at the router")
        # -- routing ----------------------------------------------------
        self.routed = m.counter(
            "fleet_requests_routed", "admissions placed on a replica "
            "(initial + failover re-admits)")
        self.affinity_hits = m.counter(
            "fleet_affinity_hits",
            "admissions routed by a prefix chain-key map hit")
        # -- failure domain ---------------------------------------------
        self.failovers = m.counter(
            "fleet_failovers", "request migrations off a lost replica")
        self.crashes = m.counter(
            "fleet_replica_crashes", "replicas declared DEAD (crash or "
            "stall escalation)")
        self.stalls = m.counter(
            "fleet_replica_stalls", "replica stall events observed")
        self.restarts = m.counter(
            "fleet_replica_restarts", "fresh predictors spawned into a "
            "dead replica's slot")
        # -- round 20: disaggregated prefill/decode + KV-page transfer --
        self.prefill_routed = m.counter(
            "fleet_prefill_admissions",
            "submissions placed on a prefill-role replica first")
        self.transfers_started = m.counter(
            "fleet_kv_transfers_started",
            "KV-page streams opened prefill -> decode")
        self.transfers_completed = m.counter(
            "fleet_kv_transfers_completed",
            "KV-page streams fully acked (every page imported)")
        self.transfers_failed = m.counter(
            "fleet_kv_transfers_failed",
            "KV-page streams aborted (retries, crash, pressure)")
        self.transfer_frames = m.counter(
            "fleet_kv_transfer_frames",
            "page frames put on the wire, retransmits included")
        self.transfer_bytes = m.counter(
            "fleet_kv_transfer_bytes",
            "encoded wire bytes sent, retransmits included")
        self.transfer_tokens = m.counter(
            "fleet_kv_transfer_tokens",
            "KV tokens landed by acked frames (per-token wire-cost "
            "denominator)")
        self.transfer_retries = m.counter(
            "fleet_kv_transfer_retries",
            "frame retransmits (timeout or checksum nack)")
        self.transfer_drops = m.counter(
            "fleet_kv_transfer_frames_dropped",
            "frames lost in flight (the transfer_drop seam)")
        self.transfer_corrupt = m.counter(
            "fleet_kv_transfer_corrupt_detected",
            "frames rejected by the receiver's checksum")
        self.prefill_fallbacks = m.counter(
            "fleet_prefill_fallbacks",
            "requests degraded to colocated prefill on the decode "
            "replica (transfer failure, prefill loss, no capacity)")
        self.transfer_backlog = m.gauge(
            "fleet_kv_transfer_backlog",
            "unacked frames across in-flight transfers after a tick")
        # -- round 21: fleet-global tiered prefixes (cross-replica pulls)
        self.pulls_started = m.counter(
            "fleet_prefix_pulls_started",
            "cross-replica prefix pulls opened (a miss on the routed "
            "replica served from the owning replica's pages instead of "
            "recomputing)")
        self.pulls_completed = m.counter(
            "fleet_prefix_pulls_completed",
            "prefix pulls fully landed before the decode admission")
        self.pull_fallbacks = m.counter(
            "fleet_prefix_pull_fallbacks",
            "pulls abandoned (wire failure, pressure, deadline) — the "
            "request recomputed its prefix colocated, never failed")
        # -- per-replica emission + fleet gauges ------------------------
        self.tokens = m.counter(
            "fleet_tokens_emitted", "tokens emitted, by serving replica",
            labels=("replica",))
        self.ticks = m.counter(
            "fleet_ticks", "fleet scheduler rounds driven")
        self.live_replicas = m.gauge(
            "fleet_live_replicas", "replicas not DEAD after a tick")
        self.unrouted = m.gauge(
            "fleet_unrouted_requests", "requests queued at the router "
            "waiting for an admittable replica")

    @property
    def affinity_hit_rate(self) -> float:
        """Fraction of placements the prefix-affinity map decided."""
        routed = self.routed.value
        return self.affinity_hits.value / routed if routed else 0.0

    def snapshot_flat(self) -> dict[str, float]:
        return self.registry.snapshot_flat()
