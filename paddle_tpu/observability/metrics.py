"""Structured metrics registry — labeled Counter / Gauge / Histogram.

The runtime-telemetry half of ``paddle_tpu/observability`` (round 15): a
small, dependency-free instrument registry every hot path in the serving
and training stacks feeds (``inference/serving.py`` step/sync/TTFT
accounting, ``inference/kv_cache.py`` page-pool occupancy,
``distributed/comm_watchdog.py`` timeout/arrival events,
``models/gpt_spmd.py`` train-step + wire-byte accounting). Prometheus
client shape without the dependency:

- an **instrument family** is created once per registry
  (:meth:`MetricsRegistry.counter` / :meth:`~MetricsRegistry.gauge` /
  :meth:`~MetricsRegistry.histogram`, idempotent by name) and carries a
  label schema; :meth:`_Family.labels` returns the child for one label
  assignment (cached — the hot path never allocates);
- a **child** mutates under the registry lock (the async serving engine's
  dispatch/reconcile split and the watchdog's monitor thread may hit the
  same counter from different threads; a torn ``+=`` would silently lose
  increments);
- the **disabled path is near-zero-cost**: every mutator's first action is
  one shared-flag check and return — no lock, no allocation, no time
  lookup. ``ServingPredictor`` runs its registry always-on (its counters
  ARE the bench metrics); the module-level :data:`default_registry` that
  library-wide instruments (collectives, watchdog, train step) feed is OFF
  by default and flipped by :func:`enable_metrics`.
- :meth:`MetricsRegistry.snapshot` / :meth:`~MetricsRegistry.snapshot_flat`
  export the current values — the flat form is the schema-checked
  ``telemetry`` sub-object riding the bench JSON lines
  (``analysis/bench_schema.py``).
"""
from __future__ import annotations

import math
import threading

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "default_registry",
    "enable_metrics", "disable_metrics", "metrics_enabled",
    "merge_snapshots",
]

#: default histogram bucket upper bounds (seconds-ish scale; callers
#: measuring ms pass their own)
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0)


def _label_key(schema, kv):
    """The child cache key for one label assignment, schema order."""
    try:
        return tuple(kv[name] for name in schema)
    except KeyError as e:
        raise ValueError(
            f"missing label {e.args[0]!r}; schema is {tuple(schema)}") from e


class _Child:
    """Base of one instrument child: shares the registry's enabled flag
    (a one-element list, so enable/disable flips every instrument without
    touching them) and its mutation lock."""

    __slots__ = ("_on", "_lock")

    def __init__(self, on, lock):
        self._on = on
        self._lock = lock


class Counter(_Child):
    """Monotonically-increasing value (float-valued: duration counters
    accumulate seconds)."""

    __slots__ = ("_value",)

    def __init__(self, on, lock):
        super().__init__(on, lock)
        self._value = 0.0

    def inc(self, n=1) -> None:
        # validate BEFORE the enabled check: a negative-delta bug must
        # surface in CI (registry off) too, not first in production
        if n < 0:
            raise ValueError(f"counters only go up; inc({n})")
        if not self._on[0]:
            return
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge(_Child):
    """Point-in-time value (pool occupancy, ring depth)."""

    __slots__ = ("_value",)

    def __init__(self, on, lock):
        super().__init__(on, lock)
        self._value = 0.0

    def set(self, v) -> None:
        if not self._on[0]:
            return
        with self._lock:
            self._value = float(v)

    def inc(self, n=1) -> None:
        if not self._on[0]:
            return
        with self._lock:
            self._value += n

    def dec(self, n=1) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._value


class Histogram(_Child):
    """Bounded-bucket histogram: ``observe(v)`` increments the ONE
    bucket whose range contains v (per-bucket storage, NOT Prometheus
    cumulative le-buckets — an exporter would have to prefix-sum), plus
    count/sum. Quantile estimates interpolate across the buckets — good
    enough for the bench trend lines this feeds (exact percentiles stay
    the bench drivers' job)."""

    __slots__ = ("_bounds", "_counts", "_count", "_sum")

    def __init__(self, on, lock, bounds):
        super().__init__(on, lock)
        self._bounds = tuple(float(b) for b in bounds)
        if list(self._bounds) != sorted(self._bounds) or not self._bounds:
            raise ValueError(f"bucket bounds must be sorted, non-empty: "
                             f"{bounds}")
        self._counts = [0] * (len(self._bounds) + 1)   # +inf overflow
        self._count = 0
        self._sum = 0.0

    def observe(self, v) -> None:
        if not self._on[0]:
            return
        v = float(v)
        with self._lock:
            i = 0
            for b in self._bounds:
                if v <= b:
                    break
                i += 1
            self._counts[i] += 1
            self._count += 1
            self._sum += v

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile estimate (0 when empty)."""
        if not self._count:
            return 0.0
        q = min(max(float(q), 0.0), 1.0)
        rank = q * self._count
        seen = 0
        lo = 0.0
        for i, b in enumerate(self._bounds):
            nxt = seen + self._counts[i]
            if nxt >= rank and self._counts[i]:
                frac = (rank - seen) / self._counts[i]
                return lo + frac * (b - lo)
            seen = nxt
            lo = b
        return self._bounds[-1]     # overflow bucket: clamp to last bound


class _Family:
    """One named instrument family with a label schema; ``labels(**kv)``
    returns (and caches) the child for a concrete assignment. A family
    declared with no labels proxies straight to its single default child,
    so ``reg.counter("steps").inc()`` works without a ``labels()`` hop."""

    def __init__(self, registry, name, kind, help, labelnames, make):
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        self._registry = registry
        self._make = make
        self._children: dict[tuple, _Child] = {}
        self._default = None if self.labelnames else self._bind(())

    def _bind(self, key):
        child = self._children.get(key)
        if child is None:
            with self._registry._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._make()
                    self._children[key] = child
        return child

    def labels(self, **kv) -> _Child:
        if not self.labelnames:
            raise ValueError(f"{self.name} declares no labels")
        return self._bind(_label_key(self.labelnames, kv))

    # -- no-label proxying --------------------------------------------------
    def _only(self):
        if self._default is None:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; call .labels()")
        return self._default

    def inc(self, n=1):
        self._only().inc(n)

    def set(self, v):
        self._only().set(v)

    def dec(self, n=1):
        self._only().dec(n)

    def observe(self, v):
        self._only().observe(v)

    #: child reads that pass through an unlabeled family
    _CHILD_ATTRS = ("value", "count", "sum", "quantile")

    def __getattr__(self, attr):
        # only the known child reads delegate, and only for unlabeled
        # families; everything else is a plain AttributeError so
        # hasattr()/getattr(..., default) keep their protocol (dunder
        # guard: __getattr__ must not touch self during __init__)
        if not attr.startswith("_") and attr in self._CHILD_ATTRS \
                and self._default is not None:
            return getattr(self._default, attr)
        raise AttributeError(
            f"family {self.name!r} has no attribute {attr!r}"
            + (f" (labeled {self.labelnames}; call .labels())"
               if attr in self._CHILD_ATTRS else ""))

    def items(self):
        """(label_suffix, child) pairs; '' for the unlabeled default.
        Snapshots the child table under the registry lock — a concurrent
        first-seen ``labels()`` insert (watchdog monitor thread) must not
        blow up a snapshot iteration."""
        with self._registry._lock:
            children = sorted(self._children.items())
        for key, child in children:
            if self.labelnames:
                suffix = "{" + ",".join(
                    f"{n}={v}" for n, v in zip(self.labelnames, key)) + "}"
            else:
                suffix = ""
            yield suffix, child


class MetricsRegistry:
    """Owns instrument families + the shared enabled flag and lock.

    ``enabled=False`` builds the registry in the near-zero-cost disabled
    state: instruments exist (callers keep unconditional references) but
    every mutation is one flag check. ``enable()``/``disable()`` flip all
    of them at once.
    """

    def __init__(self, enabled: bool = True):
        self._on = [bool(enabled)]
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    # -- lifecycle ----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._on[0]

    def enable(self) -> None:
        self._on[0] = True

    def disable(self) -> None:
        self._on[0] = False

    def reset(self) -> None:
        """Zero every child in place (references stay valid). The lock is
        taken per snapshot/mutation, never held across ``items()`` (which
        locks internally)."""
        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            for _, child in fam.items():
                with self._lock:
                    if isinstance(child, Histogram):
                        child._counts = [0] * (len(child._bounds) + 1)
                        child._count = 0
                        child._sum = 0.0
                    else:
                        child._value = 0.0

    # -- families -----------------------------------------------------------
    def _family(self, name, kind, help, labels, make):
        fam = self._families.get(name)
        if fam is None:
            # construct OUTSIDE the lock (an unlabeled family binds its
            # default child, which takes the registry lock) and publish
            # with setdefault — a racing thread's duplicate is dropped
            fam = _Family(self, name, kind, help, labels, make)
            with self._lock:
                fam = self._families.setdefault(name, fam)
        if fam.kind != kind or fam.labelnames != tuple(labels):
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind}"
                f"{fam.labelnames}, not {kind}{tuple(labels)}")
        return fam

    def counter(self, name, help="", labels=()) -> _Family:
        return self._family(name, "counter", help, labels,
                            lambda: Counter(self._on, self._lock))

    def gauge(self, name, help="", labels=()) -> _Family:
        return self._family(name, "gauge", help, labels,
                            lambda: Gauge(self._on, self._lock))

    def histogram(self, name, help="", labels=(),
                  buckets=DEFAULT_BUCKETS) -> _Family:
        return self._family(
            name, "histogram", help, labels,
            lambda: Histogram(self._on, self._lock, buckets))

    # -- export -------------------------------------------------------------
    def snapshot(self) -> dict:
        """Structured export: ``{"counters": {...}, "gauges": {...},
        "histograms": {name: {"count", "sum", "p50", "p99"}}}`` with
        labeled children keyed ``name{a=b}``."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        # snapshot the family table under the lock — a poller thread must
        # not crash on a concurrent first-seen registration (lazy
        # counter() calls in collective.py / watchdog __init__)
        with self._lock:
            families = sorted(self._families.items())
        for name, fam in families:
            for suffix, child in fam.items():
                key = name + suffix
                if fam.kind == "counter":
                    out["counters"][key] = child.value
                elif fam.kind == "gauge":
                    out["gauges"][key] = child.value
                else:
                    out["histograms"][key] = {
                        "count": child.count, "sum": child.sum,
                        "p50": child.quantile(0.5),
                        "p99": child.quantile(0.99),
                    }
        return out

    def snapshot_flat(self, prefix: str = "") -> dict[str, float]:
        """Flat ``{key: finite number}`` export — the shape
        ``bench_schema.validate_line`` checks for the ``telemetry``
        sub-object on bench JSON lines (histograms expand to
        ``_count``/``_sum``/``_p50``/``_p99``)."""
        flat: dict[str, float] = {}
        snap = self.snapshot()
        for key, v in snap["counters"].items():
            flat[prefix + key] = v
        for key, v in snap["gauges"].items():
            flat[prefix + key] = v
        for key, h in snap["histograms"].items():
            flat[prefix + key + "_count"] = h["count"]
            flat[prefix + key + "_sum"] = h["sum"]
            flat[prefix + key + "_p50"] = h["p50"]
            flat[prefix + key + "_p99"] = h["p99"]
        # the schema contract is finite numbers; a NaN observed into a
        # histogram sum must fail HERE, not two rounds later in a diff
        for k, v in flat.items():
            if not math.isfinite(v):
                raise ValueError(f"non-finite telemetry value {k}={v!r}")
        return flat


def merge_snapshots(*flats: dict) -> dict[str, float]:
    """Merge flat snapshots; duplicate keys must agree (two registries
    exporting the same key with different values is a wiring bug)."""
    out: dict[str, float] = {}
    for flat in flats:
        for k, v in flat.items():
            if k in out and out[k] != v:
                raise ValueError(f"conflicting telemetry key {k!r}: "
                                 f"{out[k]!r} vs {v!r}")
            out[k] = v
    return out


#: library-wide instruments (collectives, watchdog, train step) feed this
#: registry; OFF by default so an uninstrumented run pays one flag check
default_registry = MetricsRegistry(enabled=False)


def enable_metrics() -> None:
    default_registry.enable()


def disable_metrics() -> None:
    default_registry.disable()


def metrics_enabled() -> bool:
    return default_registry.enabled
