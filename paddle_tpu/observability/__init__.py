"""paddle_tpu.observability — runtime telemetry (round 15).

Two halves, one import surface:

- :mod:`.metrics` — the structured metrics registry: labeled
  Counter/Gauge/Histogram families with a near-zero-cost disabled path,
  thread-safe mutation (the async serving engine's dispatch/reconcile
  split, the watchdog monitor thread), and ``snapshot()`` /
  ``snapshot_flat()`` export — the schema-checked ``telemetry``
  sub-object riding the bench JSON lines.
- :mod:`.tracing` — host spans + per-request async lanes + counter
  tracks recorded into the profiler's event buffer and exported through
  ``profiler.export_chrome_tracing``; ``monotonic()``/``monotonic_ns()``
  are THE timing clock for ``inference/`` and ``distributed/`` (tpulint
  AL006 fences raw ``time.perf_counter()`` there to this layer).

Cost contract: with observability disabled (no profiler window open,
``default_registry`` off) every instrument call is one flag check and an
immediate return — the churn-smoke bench gates the end-to-end overhead
(see ARCHITECTURE.md round 15).
"""
from .fleet import FleetInstruments
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      default_registry, disable_metrics, enable_metrics,
                      merge_snapshots, metrics_enabled)
from .tracing import (REQUEST_SPAN, counter_event, device_annotation,
                      monotonic, monotonic_ns, request_begin, request_end,
                      request_event, span, tracing_active)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "default_registry",
    "enable_metrics", "disable_metrics", "metrics_enabled",
    "merge_snapshots", "span", "request_begin", "request_event",
    "request_end", "counter_event", "tracing_active", "monotonic",
    "monotonic_ns", "device_annotation", "REQUEST_SPAN",
    "FleetInstruments",
]
