"""paddle.onnx parity surface.

Reference: python/paddle/onnx/export.py — a thin wrapper delegating to the
external ``paddle2onnx`` package. This environment ships no onnx package
(and has no egress to fetch one), so ``export`` hard-errors by default.
Passing ``fallback_format="stablehlo"`` opts in to the portable serving
artifact this framework DOES ship — serialized StableHLO via
``paddle_tpu.jit.save`` (consumed by ``paddle_tpu.inference.Predictor``
and any StableHLO-speaking runtime).
"""
from __future__ import annotations

import warnings


def export(layer, path, input_spec=None, opset_version=9,
           fallback_format=None, **configs):
    """Export ``layer`` for serving.

    With the ``onnx`` package absent (this build), raises by default — a
    downstream ONNX consumer handed .pdmodel/.pdiparams.npz files would
    fail much later with a worse error. Pass
    ``fallback_format="stablehlo"`` to opt in to writing the StableHLO
    program + weights at ``path`` (same artifact as ``jit.save``); the
    produced files load with ``paddle_tpu.jit.load`` /
    ``inference.Predictor``.
    """
    try:
        import onnx  # noqa: F401
        have_onnx = True
    except ImportError:
        have_onnx = False
    if have_onnx:
        raise NotImplementedError(
            "ONNX graph emission is not implemented; export via jit.save "
            "(StableHLO) for deployment.")
    if fallback_format != "stablehlo":
        raise RuntimeError(
            "paddle_tpu.onnx.export requires the 'onnx' package, which is "
            "not available in this build. Pass fallback_format='stablehlo' "
            "to write the serialized-StableHLO serving artifact instead, "
            "or use paddle_tpu.jit.save directly.")
    warnings.warn(
        "onnx package unavailable: paddle_tpu.onnx.export is writing the "
        "portable serialized-StableHLO artifact instead (load with "
        "paddle_tpu.jit.load / inference.Predictor)", stacklevel=2)
    from .jit import save as jit_save

    if path.endswith(".onnx"):
        path = path[: -len(".onnx")]
    jit_save(layer, path, input_spec=input_spec)
    return path


__all__ = ["export"]
