"""paddle.onnx parity surface.

Reference: python/paddle/onnx/export.py — a thin wrapper delegating to the
external ``paddle2onnx`` package. This environment ships no onnx package
(and has no egress to fetch one), so ``export`` produces the portable
serving artifact this framework DOES ship — serialized StableHLO via
``paddle_tpu.jit.save`` (consumed by ``paddle_tpu.inference.Predictor``
and any StableHLO-speaking runtime) — and says so loudly. Pass
``fallback_format=None`` to get a hard error instead of the fallback.
"""
from __future__ import annotations

import warnings


def export(layer, path, input_spec=None, opset_version=9,
           fallback_format="stablehlo", **configs):
    """Export ``layer`` for serving.

    With the ``onnx`` package absent (this build), writes the StableHLO
    program + weights at ``path`` (same artifact as ``jit.save``) and
    returns the path prefix; the produced files load with
    ``paddle_tpu.jit.load`` / ``inference.Predictor``.
    """
    try:
        import onnx  # noqa: F401
        have_onnx = True
    except ImportError:
        have_onnx = False
    if have_onnx:
        raise NotImplementedError(
            "ONNX graph emission is not implemented; export via jit.save "
            "(StableHLO) for deployment.")
    if fallback_format != "stablehlo":
        raise RuntimeError(
            "paddle_tpu.onnx.export requires the 'onnx' package, which is "
            "not available in this build, and fallback_format=None disabled "
            "the StableHLO fallback. Use paddle_tpu.jit.save directly.")
    warnings.warn(
        "onnx package unavailable: paddle_tpu.onnx.export is writing the "
        "portable serialized-StableHLO artifact instead (load with "
        "paddle_tpu.jit.load / inference.Predictor)", stacklevel=2)
    from .jit import save as jit_save

    if path.endswith(".onnx"):
        path = path[: -len(".onnx")]
    jit_save(layer, path, input_spec=input_spec)
    return path


__all__ = ["export"]
