"""paddle.onnx parity surface.

Reference: python/paddle/onnx/export.py — a thin wrapper delegating to the
external ``paddle2onnx`` package. This environment ships no onnx runtime or
exporter (and has no network egress to fetch one), so ``export`` gates with
a clear error pointing at the portable serving format this framework does
ship: serialized StableHLO via ``paddle_tpu.jit.save`` /
``paddle_tpu.static.save_inference_model`` (consumed by
``paddle_tpu.inference.Predictor`` and any StableHLO-speaking runtime).
"""
from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, **configs):
    try:
        import onnx  # noqa: F401
    except ImportError:
        raise RuntimeError(
            "paddle_tpu.onnx.export requires the 'onnx' package, which is "
            "not available in this build. Use paddle_tpu.jit.save(layer, "
            "path, input_spec=...) to produce a portable serialized-"
            "StableHLO program instead (loadable by paddle_tpu.inference."
            "Predictor or any StableHLO runtime).")
    raise NotImplementedError(
        "ONNX graph emission is not implemented; export via jit.save "
        "(StableHLO) for deployment.")


__all__ = ["export"]
