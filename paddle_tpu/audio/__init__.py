"""paddle.audio parity (SURVEY.md §2.8): features + functional + wav IO.

Reference: python/paddle/audio (features/layers.py, functional/, backends/
— soundfile-backed load/save). The backend here is the stdlib ``wave``
module (PCM16/PCM32), keeping the build dependency-free.
"""
from . import backends, datasets, features, functional
from .backends import info, load, save

__all__ = ["features", "functional", "backends", "datasets",
           "load", "info", "save"]
