"""Audio classification datasets (reference python/paddle/audio/datasets/:
AudioClassificationDataset base + TESS + ESC50).

Zero-egress build: the reference downloads its archives; here the data
directory must already exist locally (``data_dir=``) — construction raises a
pointed error otherwise, the file-walk/fold-split/label contracts match.
"""
from __future__ import annotations

import os

import numpy as np

from ..io import Dataset
from .backends import load as _load


class AudioClassificationDataset(Dataset):
    """Base class: (audio-or-feature, label) pairs over a file list
    (reference datasets/dataset.py:29)."""

    def __init__(self, files, labels, feat_type="raw", sample_rate=None,
                 **feat_kwargs):
        super().__init__()
        if feat_type not in ("raw", "melspectrogram", "mfcc",
                             "logmelspectrogram", "spectrogram"):
            raise RuntimeError(f"Unknown feat_type: {feat_type}")
        self.files = list(files)
        self.labels = list(labels)
        self.feat_type = feat_type
        self.sample_rate = sample_rate
        self.feat_kwargs = feat_kwargs

    def _extract(self, waveform, sr):
        from ..tensor.tensor import Tensor

        if self.feat_type == "raw":
            return waveform
        from . import features

        x = Tensor(waveform[None, :])
        if self.feat_type == "melspectrogram":
            out = features.MelSpectrogram(sr=sr, **self.feat_kwargs)(x)
        elif self.feat_type == "logmelspectrogram":
            out = features.LogMelSpectrogram(sr=sr, **self.feat_kwargs)(x)
        elif self.feat_type == "spectrogram":
            out = features.Spectrogram(**self.feat_kwargs)(x)
        else:
            out = features.MFCC(sr=sr, **self.feat_kwargs)(x)
        return np.asarray(out.numpy())[0]

    def __getitem__(self, idx):
        wav, sr = _load(self.files[idx])
        waveform = np.asarray(wav.numpy())[0]  # mono channel 0
        return self._extract(waveform, sr), self.labels[idx]

    def __len__(self):
        return len(self.files)


def _require_dir(data_dir, cls, url):
    if data_dir is None or not os.path.isdir(data_dir):
        raise RuntimeError(
            f"{cls} needs a local data_dir (this build has no network "
            f"egress; the reference downloads {url}). Pass "
            f"data_dir=<extracted archive path>.")


class TESS(AudioClassificationDataset):
    """Toronto emotional speech set (reference datasets/tess.py:26): 2800
    wavs over 7 emotions; n-fold split by file order."""

    label_list = ["angry", "disgust", "fear", "happy", "neutral", "ps",
                  "sad"]
    archive_url = "TESS_Toronto_emotional_speech_set.zip"

    def __init__(self, mode="train", n_folds=5, split=1, feat_type="raw",
                 data_dir=None, **kwargs):
        if not 1 <= split <= n_folds:
            raise ValueError(f"split must be in [1, {n_folds}], got {split}")
        _require_dir(data_dir, "TESS", self.archive_url)
        files, labels = [], []
        for root, _, names in sorted(os.walk(data_dir)):
            for name in sorted(names):
                if not name.lower().endswith(".wav"):
                    continue
                emo = name.rsplit("_", 1)[-1][:-4].lower()
                if emo not in self.label_list:
                    continue
                files.append(os.path.join(root, name))
                labels.append(self.label_list.index(emo))
        folds = [i % n_folds + 1 for i in range(len(files))]
        keep = [(f != split) if mode == "train" else (f == split)
                for f in folds]
        files = [f for f, k in zip(files, keep) if k]
        labels = [l for l, k in zip(labels, keep) if k]
        super().__init__(files, labels, feat_type, **kwargs)


class ESC50(AudioClassificationDataset):
    """ESC-50 environmental sound dataset (reference datasets/esc50.py:26):
    2000 wavs, 50 classes, official 5-fold split encoded in filenames
    (fold-target: ``{fold}-{clip}-{take}-{target}.wav``)."""

    archive_url = "ESC-50-master.zip"

    def __init__(self, mode="train", split=1, feat_type="raw",
                 data_dir=None, **kwargs):
        _require_dir(data_dir, "ESC50", self.archive_url)
        files, labels = [], []
        for root, _, names in sorted(os.walk(data_dir)):
            for name in sorted(names):
                if not name.lower().endswith(".wav"):
                    continue
                parts = name[:-4].split("-")
                if len(parts) != 4:
                    continue
                fold, target = int(parts[0]), int(parts[3])
                if (fold != split) if mode == "train" else (fold == split):
                    files.append(os.path.join(root, name))
                    labels.append(target)
        super().__init__(files, labels, feat_type, **kwargs)


__all__ = ["AudioClassificationDataset", "TESS", "ESC50"]
