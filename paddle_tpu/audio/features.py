"""paddle.audio.features parity: Spectrogram / MelSpectrogram /
LogMelSpectrogram / MFCC Layers.

Reference: python/paddle/audio/features/layers.py. STFT framed as an XLA
conv-free gather + rfft: frames are gathered with a strided window, the
windowed frames go through jnp.fft.rfft — everything jits onto TPU.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..autograd.engine import apply_op
from ..nn.layer.layers import Layer
from ..tensor.tensor import Tensor
from .functional import (
    compute_fbank_matrix,
    create_dct,
    get_window,
    power_to_db,
)


def _stft(x, n_fft, hop_length, win_length, window, center, pad_mode):
    """x: [..., T] -> complex [..., n_fft//2+1, frames]."""
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad, mode=pad_mode)
    T = x.shape[-1]
    n_frames = 1 + (T - n_fft) // hop_length
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(n_fft)[None, :])
    frames = x[..., idx]  # [..., frames, n_fft]
    w = window
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        w = jnp.pad(window, (lpad, n_fft - win_length - lpad))
    spec = jnp.fft.rfft(frames * w, n=n_fft, axis=-1)
    return jnp.moveaxis(spec, -1, -2)  # [..., freq, frames]


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length: int | None = None,
                 win_length: int | None = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = get_window(window, self.win_length)._data

    def forward(self, x: Tensor) -> Tensor:
        def fn(v):
            spec = _stft(v, self.n_fft, self.hop_length, self.win_length,
                         self.window, self.center, self.pad_mode)
            return jnp.abs(spec) ** self.power

        return apply_op("spectrogram", fn, x)


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: int | None = None, win_length: int | None = None,
                 window: str = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0,
                 f_max: float | None = None, htk: bool = False,
                 norm: str = "slaney", dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode)
        self.fbank = compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm)._data

    def forward(self, x: Tensor) -> Tensor:
        spec = self._spectrogram(x)

        def fn(s):
            return jnp.einsum("mf,...ft->...mt", self.fbank, s)

        return apply_op("mel_spectrogram", fn, spec)


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: int | None = None, win_length: int | None = None,
                 window: str = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0,
                 f_max: float | None = None, htk: bool = False,
                 norm: str = "slaney", ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: float | None = None,
                 dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x: Tensor) -> Tensor:
        mel = self._melspectrogram(x)
        return power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 512,
                 hop_length: int | None = None, win_length: int | None = None,
                 window: str = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0,
                 f_max: float | None = None, htk: bool = False,
                 norm: str = "slaney", ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: float | None = None,
                 dtype="float32"):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db)
        self.dct = create_dct(n_mfcc, n_mels)._data

    def forward(self, x: Tensor) -> Tensor:
        logmel = self._log_melspectrogram(x)

        def fn(m):
            return jnp.einsum("mk,...mt->...kt", self.dct, m)

        return apply_op("mfcc", fn, logmel)
