"""paddle.audio.functional parity: windows, mel filterbanks, DCT, dB.

Reference: python/paddle/audio/functional/{window.py,functional.py}. All
pure jnp — these feed the feature Layers which run under jit on TPU.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from ..autograd.engine import apply_op
from ..tensor.tensor import Tensor


def _window_array(window: str, win_length: int, fftbins: bool = True,
                  **kwargs):
    N = win_length if fftbins else win_length - 1
    n = jnp.arange(win_length, dtype=jnp.float32)
    if window in ("hann", "hanning"):
        return 0.5 - 0.5 * jnp.cos(2 * math.pi * n / N)
    if window in ("hamming",):
        return 0.54 - 0.46 * jnp.cos(2 * math.pi * n / N)
    if window in ("blackman",):
        return (0.42 - 0.5 * jnp.cos(2 * math.pi * n / N)
                + 0.08 * jnp.cos(4 * math.pi * n / N))
    if window in ("bartlett", "triang"):
        return 1 - jnp.abs(2 * n / N - 1)
    if window in ("rect", "ones", "boxcar"):
        return jnp.ones(win_length, jnp.float32)
    if window == "gaussian":
        std = kwargs.get("std", 7.0)
        return jnp.exp(-0.5 * ((n - N / 2) / std) ** 2)
    if window == "exponential":
        tau = kwargs.get("tau", 1.0)
        return jnp.exp(-jnp.abs(n - N / 2) / tau)
    if window == "taylor":
        # 4-term Taylor window, 30 dB sidelobe (reference default)
        nbar, sll = 4, 30.0
        B = 10 ** (sll / 20)
        A = jnp.arccosh(B) / math.pi
        s2 = nbar ** 2 / (A ** 2 + (nbar - 0.5) ** 2)
        ma = jnp.arange(1, nbar, dtype=jnp.float32)
        Fm = []
        for mi in range(1, nbar):
            numer = (-1) ** (mi + 1) * jnp.prod(
                1 - mi ** 2 / s2 / (A ** 2 + (ma - 0.5) ** 2))
            denom = 2 * jnp.prod(
                jnp.where(ma != mi, 1 - mi ** 2 / ma ** 2, 1.0))
            Fm.append(numer / denom)
        Fm = jnp.stack(Fm)
        x = (n - (win_length - 1) / 2) / win_length
        w = jnp.ones(win_length)
        for mi in range(1, nbar):
            w = w + 2 * Fm[mi - 1] * jnp.cos(2 * math.pi * mi * x)
        return w / w.max()
    raise ValueError(f"unsupported window: {window}")


def get_window(window, win_length: int, fftbins: bool = True) -> Tensor:
    if isinstance(window, tuple):
        name, param = window[0], window[1]
        kw = ({"std": param} if name == "gaussian"
              else {"tau": param} if name == "exponential" else {})
        return Tensor(_window_array(name, win_length, fftbins, **kw))
    return Tensor(_window_array(window, win_length, fftbins))


def hz_to_mel(freq, htk: bool = False):
    scalar = not isinstance(freq, Tensor)
    f = jnp.asarray(freq._data if isinstance(freq, Tensor) else freq,
                    jnp.float32)
    if htk:
        mel = 2595.0 * jnp.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mel = jnp.where(f >= min_log_hz,
                        min_log_mel + jnp.log(jnp.maximum(f, 1e-10) / min_log_hz) / logstep,
                        mel)
    return float(mel) if scalar else Tensor(mel)


def mel_to_hz(mel, htk: bool = False):
    scalar = not isinstance(mel, Tensor)
    m = jnp.asarray(mel._data if isinstance(mel, Tensor) else mel,
                    jnp.float32)
    if htk:
        hz = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        hz = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        hz = jnp.where(m >= min_log_mel,
                       min_log_hz * jnp.exp(logstep * (m - min_log_mel)), hz)
    return float(hz) if scalar else Tensor(hz)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: float | None = None,
                         htk: bool = False, norm: str = "slaney",
                         dtype="float32") -> Tensor:
    """[n_mels, n_fft//2+1] triangular mel filterbank (reference:
    audio/functional/functional.py compute_fbank_matrix)."""
    f_max = f_max or sr / 2.0
    fft_freqs = jnp.linspace(0, sr / 2, n_fft // 2 + 1)
    mel_min = hz_to_mel(f_min, htk)
    mel_max = hz_to_mel(f_max, htk)
    mel_pts = jnp.linspace(mel_min, mel_max, n_mels + 2)
    hz_pts = jnp.asarray([mel_to_hz(float(m), htk) for m in mel_pts])
    fdiff = jnp.diff(hz_pts)
    ramps = hz_pts[:, None] - fft_freqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    fb = jnp.maximum(0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (hz_pts[2: n_mels + 2] - hz_pts[:n_mels])
        fb = fb * enorm[:, None]
    return Tensor(fb.astype(dtype))


def create_dct(n_mfcc: int, n_mels: int, norm: str | None = "ortho",
               dtype="float32") -> Tensor:
    """[n_mels, n_mfcc] DCT-II basis (reference: create_dct)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)[None, :]
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct = dct * math.sqrt(2.0 / n_mels)
        dct = dct.at[:, 0].set(dct[:, 0] / math.sqrt(2.0))
    else:
        dct = dct * 2.0
    return Tensor(dct.astype(dtype))


def power_to_db(spect: Tensor, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: float | None = 80.0) -> Tensor:
    def fn(x):
        db = 10.0 * jnp.log10(jnp.maximum(amin, x))
        db = db - 10.0 * jnp.log10(jnp.maximum(amin, ref_value))
        if top_db is not None:
            db = jnp.maximum(db, db.max() - top_db)
        return db

    return apply_op("power_to_db", fn, spect)


def fft_frequencies(sr: int, n_fft: int, dtype="float32") -> Tensor:
    return Tensor(jnp.linspace(0, sr / 2, n_fft // 2 + 1).astype(dtype))


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False,
                    dtype="float32") -> Tensor:
    mel_pts = jnp.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                           n_mels)
    return Tensor(jnp.asarray([mel_to_hz(float(m), htk)
                               for m in mel_pts]).astype(dtype))
