"""WAV load/save (reference: paddle.audio.backends wave_backend.py —
stdlib-wave PCM IO with normalize semantics)."""
from __future__ import annotations

import wave

import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import Tensor


class AudioInfo:
    def __init__(self, sample_rate, num_samples, num_channels, bits_per_sample):
        self.sample_rate = sample_rate
        self.num_frames = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample


def info(filepath: str) -> AudioInfo:
    with wave.open(filepath, "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                         f.getsampwidth() * 8)


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True):
    """Returns (Tensor [C, T] (or [T, C]), sample_rate)."""
    with wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        n_channels = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    dtype = {1: np.uint8, 2: np.int16, 4: np.int32}[width]
    data = np.frombuffer(raw, dtype=dtype).reshape(-1, n_channels)
    if width == 1:
        data = data.astype(np.int16) - 128  # 8-bit wav is unsigned
    if normalize:
        full_scale = {1: 128.0, 2: 32768.0, 4: 2147483648.0}[width]
        data = data.astype(np.float32) / full_scale
    arr = data.T if channels_first else data
    return Tensor(jnp.asarray(arr)), sr


def save(filepath: str, src: Tensor, sample_rate: int,
         channels_first: bool = True, encoding: str = "PCM_16",
         bits_per_sample: int = 16):
    data = np.asarray(src._data if isinstance(src, Tensor) else src)
    if channels_first:
        data = data.T  # -> [T, C]
    if data.ndim == 1:
        data = data[:, None]
    if bits_per_sample not in (16, 32):
        raise ValueError("bits_per_sample must be 16 or 32")
    width = bits_per_sample // 8
    if data.dtype in (np.float32, np.float64):
        data = np.clip(data, -1.0, 1.0)
        full = 32767 if width == 2 else 2147483647
        data = (data * full).astype(f"<i{width}")
    elif data.dtype == np.int16:
        data = (data.astype(np.int32) << 16).astype("<i4") if width == 4 \
            else data.astype("<i2")
    elif data.dtype == np.int32:
        # rescale, don't wrap: int32 samples to 16-bit drop the low bits
        data = (data >> 16).astype("<i2") if width == 2 else data.astype("<i4")
    else:
        raise ValueError(f"unsupported sample dtype {data.dtype}")
    with wave.open(filepath, "wb") as f:
        f.setnchannels(data.shape[1])
        f.setsampwidth(width)
        f.setframerate(sample_rate)
        f.writeframes(data.tobytes())
