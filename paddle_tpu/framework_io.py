"""paddle.save / paddle.load.

Parity: python/paddle/framework/io.py:721/:960 — pickle-based state_dict
serialization for Tensor / Layer / Optimizer state dicts, nested containers.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .tensor.tensor import Tensor


def _to_serializable(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": obj.numpy(), "name": obj.name,
                "stop_gradient": obj.stop_gradient}
    if isinstance(obj, dict):
        return {k: _to_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_serializable(v) for v in obj)
    return obj


def _from_serializable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            if return_numpy:
                return obj["data"]
            t = Tensor(obj["data"], stop_gradient=obj.get("stop_gradient", True))
            t.name = obj.get("name", t.name)
            return t
        return {k: _from_serializable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_serializable(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    if isinstance(path, str):
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        with open(path, "wb") as f:
            pickle.dump(_to_serializable(obj), f, protocol=protocol)
    else:  # file-like
        pickle.dump(_to_serializable(obj), path, protocol=protocol)


def load(path, **configs):
    return_numpy = configs.get("return_numpy", False)
    if isinstance(path, str):
        with open(path, "rb") as f:
            raw = pickle.load(f)
    else:
        raw = pickle.load(path)
    return _from_serializable(raw, return_numpy)
