"""Static-graph auxiliary surface (reference python/paddle/static/*):
scopes, guards, program state, serialization helpers, static metrics,
EMA. The record-replay Program design collapses most of these to thin
shims — documented per item.
"""
from __future__ import annotations

import contextlib
import pickle

import numpy as np

import jax
import jax.numpy as jnp

from ..tensor.tensor import Parameter, Tensor
from .program import Program, default_main_program, default_startup_program

# Variable: the reference's static-graph tensor handle; the one-IR design
# uses Tensor everywhere (SURVEY §2.3), so the name is an alias.
Variable = Tensor


@contextlib.contextmanager
def name_scope(prefix=None):
    """Name prefix scope (reference static/nn/common.py name_scope): names
    generated inside carry the prefix (a fresh prefixed unique_name
    generator, the same mechanism the reference pushes)."""
    from ..utils import unique_name as _un

    with _un.guard(prefix or ""):
        yield


@contextlib.contextmanager
def device_guard(device=None):
    """Reference device_guard pins ops to a device inside a program; XLA
    owns placement under the one-IR design, so this is a documented no-op
    scope (kept so reference programs run unchanged)."""
    yield


class _Scope:
    """Reference Scope: a variable name -> value store. The record-replay
    Executor keeps state on the Program itself; this scope view exposes
    the same lookup surface."""

    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, _ScopeVar())

    def find_var(self, name):
        return self._vars.get(name)


class _ScopeVar:
    def __init__(self):
        self._value = None

    def get_tensor(self):
        return self._value

    def set(self, value, place=None):
        self._value = value


_GLOBAL_SCOPE = _Scope()


def global_scope():
    return _GLOBAL_SCOPE


@contextlib.contextmanager
def scope_guard(scope):
    global _GLOBAL_SCOPE
    old, _GLOBAL_SCOPE = _GLOBAL_SCOPE, scope
    try:
        yield
    finally:
        _GLOBAL_SCOPE = old


def cpu_places(device_count=None):
    from ..device import CPUPlace

    n = device_count or 1
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    # CUDA does not exist here; the accelerator places are the TPU chips
    from ..device import CustomPlace

    ids = device_ids if device_ids is not None else range(
        len(jax.devices()))
    return [CustomPlace("tpu", int(i)) for i in ids]


xpu_places = cuda_places


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """A persistable var in the program (reference creates a var in the
    global block; here: a Parameter-like persistent Tensor)."""
    from ..framework.dtype import to_jax_dtype

    t = Parameter(jnp.full(list(shape), value, to_jax_dtype(dtype)),
                  trainable=False, name=name)
    t.persistable = bool(persistable)
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """Reference static.create_parameter — a trainable parameter outside
    any Layer."""
    from ..nn.initializer import XavierUniform

    init = default_initializer or (attr.initializer if attr is not None
                                   and getattr(attr, "initializer", None)
                                   else XavierUniform())
    from ..framework.dtype import to_jax_dtype

    data = init(list(shape), to_jax_dtype(dtype))
    param = Parameter(data, name=name or (attr.name if attr else None))
    # register with the recording program (reference: parameters live in
    # the program's global block) so Program.parameters()/save see it
    from .program import default_main_program, is_recording

    if is_recording():
        default_main_program()._params[param.name] = param
    return param


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Reference static.Print op: passthrough + host-side debug print via
    jax.debug.print (works inside jit, matching the op semantics)."""
    from ..autograd.engine import apply_op

    msg = message or ""

    def fn(v):
        # message passed as DATA, not format string: user braces are safe
        jax.debug.print("{m} {v}", m=msg, v=v)
        return v

    return apply_op("print", fn, input)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Reference static.py_func: run host python inside the graph. Under
    jax this is pure_callback (forward) with an optional custom backward."""
    from ..autograd.engine import apply_op

    if backward_func is not None:
        raise NotImplementedError(
            "py_func: backward_func is not supported — wrap the host "
            "function with autograd.PyLayer for a custom gradient")
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    shapes = [jax.ShapeDtypeStruct(tuple(o.shape), o._data.dtype)
              for o in outs]

    def fn(*vals):
        res = jax.pure_callback(
            lambda *a: func(*[np.asarray(v) for v in a]), shapes, *vals)
        return res if len(shapes) > 1 else res[0]

    return apply_op("py_func", fn, *xs)


def serialize_program(feed_vars, fetch_vars, program=None):
    """Program -> bytes (reference serialize_program pickles the
    ProgramDesc proto; the record-replay Program serializes through
    jit.save's StableHLO path for real deployment — this byte form covers
    the reference's in-memory round-trip use)."""
    prog = program or default_main_program()
    return pickle.dumps({
        "num_ops": prog.num_ops(),
        "feeds": [getattr(v, "name", str(i))
                  for i, v in enumerate(feed_vars or [])],
        "fetches": [getattr(v, "name", str(i))
                    for i, v in enumerate(fetch_vars or [])],
    })


def deserialize_program(data):
    meta = pickle.loads(data)
    prog = Program()
    prog._serialized_meta = meta
    return prog


def serialize_persistables(feed_vars, fetch_vars, program=None):
    prog = program or default_main_program()
    state = {p.name: np.asarray(p.numpy()) for p in prog.parameters()}
    return pickle.dumps(state)


def deserialize_persistables(program, data, executor=None):
    state = pickle.loads(data)
    for p in program.parameters():
        if p.name in state:
            p._data = jnp.asarray(state[p.name])
    return state


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def load_program_state(model_path, var_list=None):
    """Reference load_program_state: {name: ndarray} from a static.save
    artifact (io.save writes <path>.pdparams pickle)."""
    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)


def set_program_state(program, state_dict):
    for p in program.parameters():
        if p.name in state_dict:
            p._data = jnp.asarray(state_dict[p.name])


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """Reference: prune + inline feed/fetch for export. Record-replay
    programs are already minimal per (feed, fetch) signature."""
    return program


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Static metric op (reference static/nn/metric.py accuracy)."""
    from ..autograd.engine import apply_op

    def fn(logits, y):
        topk = jnp.argsort(-logits, axis=-1)[..., :k]
        hit = (topk == y.reshape(-1, 1)).any(axis=-1)
        return jnp.mean(hit.astype(jnp.float32))

    return apply_op("accuracy", fn, input, label)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, ins_tag_weight=None):
    """Static AUC op (reference static/nn/metric.py auc): histogram
    approximation with num_thresholds bins. Only the ROC curve is
    implemented (PR would silently return the wrong metric)."""
    if curve != "ROC":
        raise NotImplementedError(
            f"auc: curve={curve!r} is not supported (ROC only)")
    from ..autograd.engine import apply_op

    def fn(probs, y):
        pos_prob = probs[:, 1] if probs.ndim == 2 else probs.reshape(-1)
        yb = y.reshape(-1).astype(jnp.float32)
        bins = jnp.clip((pos_prob * num_thresholds).astype(jnp.int32), 0,
                        num_thresholds)
        pos_hist = jnp.zeros(num_thresholds + 1).at[bins].add(yb)
        neg_hist = jnp.zeros(num_thresholds + 1).at[bins].add(1.0 - yb)
        # sweep thresholds high->low accumulating TP/FP
        tp = jnp.cumsum(pos_hist[::-1])
        fp = jnp.cumsum(neg_hist[::-1])
        tot_p = tp[-1]
        tot_n = fp[-1]
        tpr = tp / jnp.maximum(tot_p, 1.0)
        fpr = fp / jnp.maximum(tot_n, 1.0)
        return jnp.trapezoid(tpr, fpr)

    return apply_op("auc", fn, input, label)


class ExponentialMovingAverage:
    """EMA of parameters (reference static/ema.py): update() folds the
    current parameter values in; apply()/restore() swap the averages into
    the parameters around evaluation."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._thres_steps = thres_steps  # truthy: ramp the decay in
        self._tracked: dict = {}  # name -> (param ref, ema array)
        self._backup: dict = {}
        self._step = 0

    def _decay_t(self):
        # reference ramp: min(decay, (1+step)/(10+step)) when thres_steps
        if self._thres_steps is None:
            return self._decay
        return min(self._decay, (1.0 + self._step) / (10.0 + self._step))

    def update(self, parameters=None):
        params = parameters or default_main_program().parameters()
        self._step += 1
        d = self._decay_t()
        for p in params:
            prev = self._tracked.get(p.name)
            cur = p._data
            ema = (cur if prev is None else
                   d * prev[1] + (1.0 - d) * cur)
            self._tracked[p.name] = (p, ema)

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        for name, (p, ema) in self._tracked.items():
            self._backup[name] = p._data
            p._data = ema
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for name, (p, _e) in self._tracked.items():
            if name in self._backup:
                p._data = self._backup.pop(name)


class BuildStrategy:
    """Reference BuildStrategy: fusion/memory-pass toggles consumed by the
    ParallelExecutor. XLA owns those passes under the one-IR design; the
    class keeps the attribute surface so reference configs parse."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = True
        self.fuse_bn_act_ops = True
        self.memory_optimize = True
        self.reduce_strategy = 0
        self.build_cinn_pass = False


class ExecutionStrategy:
    """Reference ExecutionStrategy (thread pools, iteration drop): the
    Executor compiles one XLA program — attributes kept for config
    parity."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10
        self.num_iteration_per_run = 1


class WeightNormParamAttr:
    """Reference WeightNormParamAttr — weight-norm reparameterization via
    ParamAttr. The dygraph path uses nn.utils.weight_norm; this attr
    carries (dim, name/initializer) so static builders accept it."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.trainable = trainable
