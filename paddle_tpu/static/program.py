"""Static graph Program: record-and-replay over the eager engine.

Reference: ProgramDesc / Block / Operator protobuf graphs plus the python
mirror (framework/framework.proto:267, base/framework.py) that
``paddle.static`` users build under ``program_guard`` and run with an
Executor (SURVEY.md §2.3).

TPU-native design ("one IR", SURVEY.md §7.1): there is no separate op-desc
IR. Graph construction *executes eagerly once* (define-by-run), and while a
Program is recording, every op that flows through the autograd engine's
``apply_op`` appends a replayable statement ``(pure_fn, input refs, output
ids)``. The Executor replays the statement list as a pure JAX function of
(feeds, parameters) and hands it to ``jax.jit`` — the compiled XLA
executable is the static graph. Benefits over a translated ProgramDesc:
construction-time python control flow is baked exactly like the reference's
static mode, shapes stay polymorphic until compile, and dead statements
(e.g. initializer ops that belong in the reference's startup program) are
pruned by the backward slice from the fetch targets.
"""
from __future__ import annotations

import itertools
import threading
from typing import Any, Callable

import jax.numpy as jnp
import numpy as np

from ..autograd import engine
from ..framework import dtype as dtype_mod
from ..framework.random import RngKey
from ..tensor.tensor import Parameter, Tensor

_vid_counter = itertools.count(1)
_program_uid = itertools.count(1)


class Statement:
    """One recorded op: replayable pure function + argument references.

    ``leaf_refs`` mirrors the flattened (args, kwargs) pytree; each entry is
    ``("v", vid)`` for a produced-in-program variable, ``("p", name)`` for a
    Parameter (lives in the scope, updatable between runs), ``("rng", slot)``
    for a PRNG key the Executor re-derives per run (so dropout/random ops
    re-randomize on replay instead of baking the recorded mask), or
    ``("c", value)`` for a captured constant / python literal.
    """

    __slots__ = ("name", "fn", "treedef", "leaf_refs", "out_vids")

    def __init__(self, name, fn, treedef, leaf_refs, out_vids):
        self.name = name
        self.fn = fn
        self.treedef = treedef
        self.leaf_refs = leaf_refs
        self.out_vids = out_vids


class Program:
    """A recorded computation: feed placeholders -> statements -> variables.

    API parity: ``paddle.static.Program`` (global_block/parameters/clone);
    the op container role of Block collapses into the flat statement list
    (control flow is baked at construction, like reference static mode with
    the AST transformer resolved).
    """

    def __init__(self):
        self._origin = self  # clones share identity for var ownership checks
        self._uid = next(_program_uid)  # unique even across GC'd id() reuse
        self._statements: list[Statement] = []
        self._feeds: dict[str, int] = {}
        self._feed_specs: dict[str, tuple] = {}
        self._feed_tensors: dict[str, Tensor] = {}
        self._params: dict[str, Parameter] = {}
        self._optimizer = None
        self._loss_vid: int | None = None
        # Shared mutable cells so clones see recordings into the origin, the
        # Executor cache can't serve a stale compiled entry, and rng slot
        # numbers stay unique across the shared statement list.
        self._version_cell = [0]
        self._rng_cell = [0]
        self._var_names: dict[int, str] = {}
        self.random_seed = None

    @property
    def _version(self) -> int:
        return self._version_cell[0]

    def _bump_version(self):
        self._version_cell[0] += 1

    # -- recording ---------------------------------------------------------
    def _record(self, name, fn, treedef, leaves, out_tensors):
        leaf_refs = []
        for leaf in leaves:
            if isinstance(leaf, Parameter):
                pname = leaf.name
                self._params[pname] = leaf
                leaf_refs.append(("p", pname))
            elif isinstance(leaf, Tensor):
                vid = getattr(leaf, "_static_vid", None)
                if vid is not None and vid[0] is self._origin:
                    leaf_refs.append(("v", vid[1]))
                else:
                    leaf_refs.append(("c", leaf._data))
            elif isinstance(leaf, RngKey):
                leaf_refs.append(("rng", self._rng_cell[0]))
                self._rng_cell[0] += 1
            else:
                leaf_refs.append(("c", leaf))
        out_vids = []
        for t in out_tensors:
            vid = next(_vid_counter)
            t._static_vid = (self, vid)
            out_vids.append(vid)
        self._statements.append(
            Statement(name, fn, treedef, leaf_refs, out_vids))
        self._bump_version()

    def _add_feed(self, name, tensor, shape, dtype):
        vid = next(_vid_counter)
        tensor._static_vid = (self, vid)
        self._feeds[name] = vid
        self._feed_specs[name] = (tuple(shape), dtype)
        self._var_names[vid] = name
        self._feed_tensors[name] = tensor  # for gradients()/append_backward
        self._bump_version()

    def _set_optimizer(self, optimizer, loss):
        vid = getattr(loss, "_static_vid", None)
        if vid is None or vid[0] is not self._origin:
            raise ValueError(
                "minimize(loss): loss was not produced inside this Program")
        self._optimizer = optimizer
        self._loss_vid = vid[1]
        self._bump_version()

    # -- introspection -----------------------------------------------------
    def parameters(self):
        return list(self._params.values())

    def all_parameters(self):
        return self.parameters()

    def global_block(self):
        return self  # Block/Program collapse; `vars` access via feeds

    def list_vars(self):
        return list(self._feeds)

    def num_ops(self):
        return len(self._statements)

    def clone(self, for_test: bool = False):
        """Share the recorded graph (reference Program.clone shares params).

        ``for_test=True`` parity note: the reference strips optimizer ops;
        here the Executor only replays the slice needed for the requested
        fetches and skips the optimizer unless it was attached AND the run
        asks for training, so the clone can share everything.
        """
        p = Program.__new__(Program)
        p.__dict__.update(self.__dict__)
        p._uid = next(_program_uid)  # own cache identity; version cell shared
        if for_test:
            p._optimizer = None
            p._loss_vid = None
        return p

    # -- slicing for execution --------------------------------------------
    def slice_for(self, target_vids: set[int]) -> list[Statement]:
        """Backward slice: the statements (in order) needed to compute the
        targets from feeds/params/constants. Prunes initializer ops and any
        construction-time side computation (startup-program parity)."""
        needed: set[int] = set(target_vids)
        keep: list[Statement] = []
        for stmt in reversed(self._statements):
            if any(v in needed for v in stmt.out_vids):
                keep.append(stmt)
                for kind, ref in stmt.leaf_refs:
                    if kind == "v":
                        needed.add(ref)
        keep.reverse()
        return keep

    def __repr__(self):
        return (f"Program(feeds={list(self._feeds)}, "
                f"ops={len(self._statements)}, params={len(self._params)})")


# ---------------------------------------------------------------------------
# default programs + guards (reference: base/framework.py program stack)
# ---------------------------------------------------------------------------

_state = threading.local()


def _tls():
    if not hasattr(_state, "main"):
        _state.main = Program()
        _state.startup = Program()
        _state.recording = False
    return _state


def default_main_program() -> Program:
    return _tls().main


def default_startup_program() -> Program:
    return _tls().startup


def _install_hook():
    tls = _tls()
    engine.static_record_hook = tls.main._record
    tls.recording = True


def _uninstall_hook():
    engine.static_record_hook = None
    _tls().recording = False


def is_recording() -> bool:
    return getattr(_state, "recording", False)


class program_guard:
    """``with program_guard(main, startup):`` — ops record into ``main``.

    The startup program is accepted for API parity; parameter initialization
    runs eagerly at layer construction (its ops are pruned from the main
    slice), so startup replay is a no-op.
    """

    def __init__(self, main_program: Program, startup_program: Program | None = None):
        self._main = main_program
        self._startup = startup_program

    def __enter__(self):
        tls = _tls()
        self._saved = (tls.main, tls.startup, engine.static_record_hook,
                       tls.recording)
        tls.main = self._main
        if self._startup is not None:
            tls.startup = self._startup
        _install_hook()
        return self

    def __exit__(self, *exc):
        tls = _tls()
        tls.main, tls.startup, engine.static_record_hook, tls.recording = (
            self._saved)
        return False


def data(name: str, shape, dtype="float32", lod_level=0) -> Tensor:
    """Feed placeholder (reference: paddle.static.data). Dynamic dims
    (None/-1) are concretized to 1 for the construction pass; the Executor
    re-traces per concrete feed shape (guard-keyed jit cache), so any batch
    size can be fed at run time."""
    del lod_level
    if not is_recording():
        raise RuntimeError(
            "paddle.static.data() must be called under paddle.enable_static()"
            " or program_guard")
    concrete = tuple(1 if (d is None or d == -1) else int(d) for d in shape)
    jdt = dtype_mod.to_jax_dtype(dtype)
    t = Tensor(jnp.zeros(concrete, jdt), stop_gradient=True)
    t.name = name
    default_main_program()._add_feed(name, t, shape, dtype)
    return t


def enable_static():
    """Switch to static graph mode: subsequent ops record into the default
    main program (reference: paddle.enable_static — idempotent; the default
    programs persist across enable/disable cycles like the reference's
    module-level program stack)."""
    _install_hook()


def disable_static():
    _uninstall_hook()


def in_static_mode() -> bool:
    return is_recording()
