"""paddle.static.nn — thin functional wrappers over the nn layers.

Reference: python/paddle/static/nn/common.py (fc, embedding, batch_norm…)
which append ops + parameters to the current program. Here the nn.Layer
machinery already records through the engine hook while a Program is
recording, so these wrappers just construct a layer once and apply it.
"""
from __future__ import annotations

from .. import nn
from ..tensor.tensor import Tensor


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    in_features = 1
    for d in x.shape[num_flatten_dims:]:
        in_features *= int(d)
    layer = nn.Linear(in_features, size,
                      weight_attr=weight_attr, bias_attr=bias_attr)
    # -1 keeps the leading (batch) extent symbolic so the recorded reshape
    # replays at any feed batch size
    if num_flatten_dims == 1:
        flat = x.reshape([-1, in_features])
    else:
        flat = x.reshape(list(x.shape[:num_flatten_dims]) + [in_features])
    out = layer(flat)
    if activation:
        out = getattr(nn.functional, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    layer = nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                         weight_attr=param_attr)
    return layer(input)


def batch_norm(input, is_test=False, momentum=0.9, epsilon=1e-05,
               param_attr=None, bias_attr=None, data_layout="NCHW", **kw):
    num = int(input.shape[1 if data_layout == "NCHW" else -1])
    layer = nn.BatchNorm2D(num, momentum=momentum, epsilon=epsilon,
                           weight_attr=param_attr, bias_attr=bias_attr,
                           data_format=data_layout)
    if is_test:
        layer.eval()
    return layer(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, data_format="NCHW"):
    in_channels = int(input.shape[1 if data_format == "NCHW" else -1])
    layer = nn.Conv2D(in_channels, num_filters, filter_size, stride=stride,
                      padding=padding, dilation=dilation, groups=groups,
                      weight_attr=param_attr, bias_attr=bias_attr,
                      data_format=data_format)
    return layer(input)


def _as_py_bool(v) -> bool:
    import numpy as np

    from ..tensor.tensor import Tensor

    return bool(np.asarray(v._data)) if isinstance(v, Tensor) else bool(v)


def _as_py_int(v) -> int:
    import numpy as np

    from ..tensor.tensor import Tensor

    return int(np.asarray(v._data)) if isinstance(v, Tensor) else int(v)


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """Conditional execution (reference: paddle.static.nn.cond).

    Dygraph semantics: the predicate is evaluated and the chosen branch
    runs. Under Program recording the same applies — construction-time
    control flow is baked into the recorded graph (see static/program.py
    design notes); a feed-dependent predicate should instead be expressed
    with tensor ops (paddle.where) or traced via jit.to_static, where
    lax.cond handles it.
    """
    if _as_py_bool(pred):
        return true_fn() if true_fn is not None else None
    return false_fn() if false_fn is not None else None


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """paddle.static.nn.while_loop parity with dygraph semantics: iterate
    body_fn while cond_fn holds (concrete evaluation per iteration; under
    jit.to_static the python loop unrolls at trace time on concrete
    shapes)."""
    vars_ = list(loop_vars)
    while True:
        if not _as_py_bool(cond_fn(*vars_)):
            break
        out = body_fn(*vars_)
        vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
    return vars_


def case(pred_fn_pairs, default=None, name=None):
    """First-match conditional chain (reference: paddle.static.nn.case —
    with no default, the LAST pair's fn is the implicit fallback)."""
    for pred, fn_ in pred_fn_pairs:
        if _as_py_bool(pred):
            return fn_()
    if default is not None:
        return default()
    return pred_fn_pairs[-1][1]()  # reference implicit-default contract


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Index-dispatched branch (reference: paddle.static.nn.switch_case —
    with no default, the fn of the LARGEST key is the implicit fallback)."""
    idx = _as_py_int(branch_index)
    fns = dict(branch_fns) if not isinstance(branch_fns, dict) else branch_fns
    if idx in fns:
        return fns[idx]()
    if default is not None:
        return default()
    return fns[max(fns)]()  # reference implicit-default contract
