"""paddle.static.nn — thin functional wrappers over the nn layers.

Reference: python/paddle/static/nn/common.py (fc, embedding, batch_norm…)
which append ops + parameters to the current program. Here the nn.Layer
machinery already records through the engine hook while a Program is
recording, so these wrappers just construct a layer once and apply it.
"""
from __future__ import annotations

from .. import nn
from ..tensor.tensor import Tensor


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    in_features = 1
    for d in x.shape[num_flatten_dims:]:
        in_features *= int(d)
    layer = nn.Linear(in_features, size,
                      weight_attr=weight_attr, bias_attr=bias_attr)
    # -1 keeps the leading (batch) extent symbolic so the recorded reshape
    # replays at any feed batch size
    if num_flatten_dims == 1:
        flat = x.reshape([-1, in_features])
    else:
        flat = x.reshape(list(x.shape[:num_flatten_dims]) + [in_features])
    out = layer(flat)
    if activation:
        out = getattr(nn.functional, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    layer = nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                         weight_attr=param_attr)
    return layer(input)


def batch_norm(input, is_test=False, momentum=0.9, epsilon=1e-05,
               param_attr=None, bias_attr=None, data_layout="NCHW", **kw):
    num = int(input.shape[1 if data_layout == "NCHW" else -1])
    layer = nn.BatchNorm2D(num, momentum=momentum, epsilon=epsilon,
                           weight_attr=param_attr, bias_attr=bias_attr,
                           data_format=data_layout)
    if is_test:
        layer.eval()
    return layer(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, data_format="NCHW"):
    in_channels = int(input.shape[1 if data_format == "NCHW" else -1])
    layer = nn.Conv2D(in_channels, num_filters, filter_size, stride=stride,
                      padding=padding, dilation=dilation, groups=groups,
                      weight_attr=param_attr, bias_attr=bias_attr,
                      data_format=data_format)
    return layer(input)
