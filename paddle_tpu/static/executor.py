"""Static graph Executor: replay a Program as one compiled XLA step.

Reference: paddle.static.Executor.run (base/executor.py:1608 →
_StandaloneExecutor:816) over the C++ StandaloneExecutor/PirInterpreter
instruction scheduler (SURVEY.md §3.4). The TPU-native executor has no
instruction-level scheduler to write: the whole program (forward + backward +
optimizer update when attached) is replayed into one pure JAX function and
``jax.jit``-compiled — XLA's scheduler is the interpreter, its fusion is the
pass pipeline, and the executable cache keyed on (program version, feed
shapes, fetch set) is the `_ExecutorCache` (executor.py:854) equivalent.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.grad_mode import no_grad
from ..framework import dtype as dtype_mod
from ..tensor.tensor import Tensor
from .program import Program, default_main_program


class CompiledProgram:
    """API-parity wrapper (reference: paddle.static.CompiledProgram). XLA
    compiles every program; this just tags build options."""

    def __init__(self, program: Program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy


def _program_of(p) -> Program:
    return p._program if isinstance(p, CompiledProgram) else p


class Executor:
    def __init__(self, place=None):
        self.place = place
        self._cache: dict[tuple, Any] = {}

    # -- public API --------------------------------------------------------
    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, **kwargs):
        program = _program_of(program) if program is not None else (
            default_main_program())
        feed = dict(feed or {})
        fetch_list = list(fetch_list or [])

        fetch_vids = []
        for f in fetch_list:
            vid = getattr(f, "_static_vid", None)
            if vid is None or vid[0] is not program._origin:
                raise ValueError(
                    f"fetch target {f!r} was not produced by this Program")
            fetch_vids.append(vid[1])

        if not fetch_vids and program._optimizer is None:
            return []  # startup-program run: params initialized eagerly

        feed_arrays = {}
        for name, value in feed.items():
            if isinstance(value, Tensor):
                value = value._data
            spec = program._feed_specs.get(name)
            jdt = dtype_mod.to_jax_dtype(spec[1]) if spec else None
            feed_arrays[name] = jnp.asarray(value, jdt)

        key = (
            program._uid, program._version, tuple(fetch_vids),
            tuple(sorted((n, a.shape, str(a.dtype))
                         for n, a in feed_arrays.items())),
        )
        entry = self._cache.get(key)
        if entry is None:
            entry = self._build(program, fetch_vids)
            self._cache[key] = entry
        return entry(feed_arrays, return_numpy)

    # -- compilation -------------------------------------------------------
    def _build(self, program: Program, fetch_vids: list[int]):
        opt = program._optimizer
        with_opt = opt is not None
        targets = set(fetch_vids)
        if with_opt:
            targets.add(program._loss_vid)
        stmts = program.slice_for(targets)

        pnames = sorted({ref for st in stmts
                         for kind, ref in st.leaf_refs if kind == "p"})
        params = {n: program._params[n] for n in pnames}
        # feed vids the slice actually consumes
        produced = {v for st in stmts for v in st.out_vids}
        consumed = {ref for st in stmts
                    for kind, ref in st.leaf_refs if kind == "v"}
        needed_feeds = {name: vid for name, vid in program._feeds.items()
                        if vid in (consumed | targets) and vid not in produced}

        def replay(env, pvals, rng_key):
            for st in stmts:
                leaf_vals = []
                for kind, ref in st.leaf_refs:
                    if kind == "v":
                        leaf_vals.append(env[ref])
                    elif kind == "p":
                        leaf_vals.append(pvals[ref])
                    elif kind == "rng":
                        # fresh per-run key per rng slot: replays re-randomize
                        leaf_vals.append(jax.random.fold_in(rng_key, ref))
                    else:
                        leaf_vals.append(ref)
                a, kw = jax.tree.unflatten(st.treedef, leaf_vals)
                out = st.fn(*a, **kw)
                for vid, val in zip(st.out_vids, jax.tree.flatten(out)[0]):
                    env[vid] = val
            return env

        def seed_env(feed_arrays):
            env = {}
            for name, vid in needed_feeds.items():
                if name not in feed_arrays:
                    raise KeyError(
                        f"Executor.run: program needs feed '{name}'")
                env[vid] = feed_arrays[name]
            return env

        has_rng = any(kind == "rng" for st in stmts
                      for kind, _ in st.leaf_refs)

        def run_key():
            """Per-run base key. program.random_seed pins it (reference: a
            seeded program replays identical masks); otherwise draw from the
            global generator so paddle.seed reproducibility holds. Programs
            without random ops must not consume a generator tick (it would
            perturb eager sampling sequences interleaved with runs)."""
            if not has_rng:
                return jax.random.key(0)
            if program.random_seed is not None:
                return jax.random.key(int(program.random_seed))
            from ..framework.random import default_generator

            return default_generator.next_key()

        if not with_opt:
            @jax.jit
            def fwd(feed_arrays, pvals, rng_key):
                env = replay(seed_env(feed_arrays), pvals, rng_key)
                return [env[v] for v in fetch_vids]

            def entry(feed_arrays, return_numpy):
                pvals = {n: p._data for n, p in params.items()}
                outs = fwd(feed_arrays, pvals, run_key())
                return [np.asarray(o) if return_numpy else Tensor(o)
                        for o in outs]

            return entry

        # training step: forward + grad + optimizer update, one executable
        loss_vid = program._loss_vid
        train_names = [n for n in pnames if not params[n].stop_gradient]
        frozen_names = [n for n in pnames if params[n].stop_gradient]
        train_params = [params[n] for n in train_names]
        for p in train_params:
            opt._ensure_state(p)
        wds = [jnp.asarray(opt._param_decay_coeff(p), jnp.float32)
               for p in train_params]
        lr_scales = [jnp.asarray(opt._param_lr_scale(p), jnp.float32)
                     for p in train_params]
        grad_clip = opt._grad_clip

        @jax.jit
        def step(feed_arrays, train_arrays, frozen_arrays, lr, states,
                 masters, rng_key):
            def loss_fn(train_arrays):
                pvals = {**frozen_arrays, **train_arrays}
                env = replay(seed_env(feed_arrays), pvals, rng_key)
                return env[loss_vid], [env[v] for v in fetch_vids]

            (_, fetches), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(train_arrays)
            plist = [train_arrays[n] for n in train_names]
            glist = [grads[n] for n in train_names]
            if grad_clip is not None:
                with no_grad():
                    pairs = [(Tensor(p), Tensor(g))
                             for p, g in zip(plist, glist)]
                    glist = [g._data for _, g in grad_clip(pairs)]
            new_p, new_st, new_m = opt._batch_update(
                lr, plist, glist, states, masters, wds, lr_scales)
            return fetches, new_p, new_st, new_m

        def entry(feed_arrays, return_numpy):
            train_arrays = {n: params[n]._data for n in train_names}
            frozen_arrays = {n: params[n]._data for n in frozen_names}
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            states = [opt._accumulators[id(p)] for p in train_params]
            masters = [opt._master_weights.get(id(p)) for p in train_params]
            fetches, new_p, new_st, new_m = step(
                feed_arrays, train_arrays, frozen_arrays, lr, states, masters,
                run_key())
            for p, pa, st, mw in zip(train_params, new_p, new_st, new_m):
                p._data = pa
                opt._accumulators[id(p)] = st
                if mw is not None:
                    opt._master_weights[id(p)] = mw
            opt._after_step()
            return [np.asarray(o) if return_numpy else Tensor(o)
                    for o in fetches]

        return entry
