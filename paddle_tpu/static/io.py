"""Static graph save/load: parameters and inference-model export.

Reference: paddle.static.save/load (static/io.py state of a Program) and
save_inference_model/load_inference_model producing deployable artifacts.
TPU-native artifact = serialized StableHLO via ``jax.export`` (parameters
baked or sided as .npz), the same format as paddle_tpu.jit.save, so the
inference Predictor consumes both.
"""
from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import Tensor
from .executor import Executor
from .program import Program


def save(program: Program, path: str) -> None:
    """Save all parameters (and nothing else — the statement list is code,
    re-created by re-running the construction; reference static.save saves
    the param scope the same way)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    arrays = {n: np.asarray(p._data) for n, p in program._params.items()}
    with open(path + ".pdparams", "wb") as f:
        pickle.dump(arrays, f)


def load(program: Program, path: str, executor=None, var_list=None) -> None:
    with open(path + ".pdparams", "rb") as f:
        arrays = pickle.load(f)
    for n, p in program._params.items():
        if n in arrays:
            p._data = jnp.asarray(arrays[n], p._data.dtype)


def save_inference_model(path_prefix: str, feed_vars, fetch_vars,
                         executor=None, program=None, **kwargs) -> None:
    """Export the fetch-slice of a Program as StableHLO + weights.

    Reference: paddle.static.save_inference_model prunes the program to the
    feed→fetch slice and saves model+params; here the slice is replayed into
    a pure function of the feeds (parameters passed as inputs so the .npz
    stays separate and editable) and exported with dynamic leading dims.
    """
    from jax import export as jax_export

    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) else [fetch_vars]
    if program is None:
        vid = getattr(fetch_vars[0], "_static_vid", None)
        if vid is None:
            raise ValueError("fetch_vars must come from a static Program")
        program = vid[0]

    fetch_vids = [f._static_vid[1] for f in fetch_vars]
    stmts = program.slice_for(set(fetch_vids))
    pnames = sorted({ref for st in stmts
                     for kind, ref in st.leaf_refs if kind == "p"})
    param_arrays = {n: program._params[n]._data for n in pnames}

    feed_names, feed_vids = [], []
    for v in feed_vars:
        vid = v._static_vid[1]
        name = next((n for n, fv in program._feeds.items() if fv == vid), None)
        if name is None:
            raise ValueError(f"feed var {v!r} is not a static.data placeholder")
        feed_names.append(name)
        feed_vids.append(vid)

    def pure(params, *feed_datas):
        env = dict(zip(feed_vids, feed_datas))
        for st in stmts:
            leaf_vals = []
            for kind, ref in st.leaf_refs:
                if kind == "v":
                    leaf_vals.append(env[ref])
                elif kind == "p":
                    leaf_vals.append(params[ref])
                else:
                    leaf_vals.append(ref)
            a, kw = jax.tree.unflatten(st.treedef, leaf_vals)
            out = st.fn(*a, **kw)
            for vid_, val in zip(st.out_vids, jax.tree.flatten(out)[0]):
                env[vid_] = val
        return tuple(env[v] for v in fetch_vids)

    # Export with a shared symbolic batch dim wherever the declared spec had
    # a dynamic dim; other dims use the declared static sizes.
    scope = jax_export.SymbolicScope()
    counter = [0]
    arg_shapes = []
    for name in feed_names:
        shape, dtype = program._feed_specs[name]
        dims = []
        for d in shape:
            if d is None or d == -1:
                counter[0] += 1
                dims.append(f"_dyn{counter[0]}")
            else:
                dims.append(str(int(d)))
        from ..framework import dtype as dtype_mod

        if any(d.startswith("_dyn") for d in dims):
            sym = jax_export.symbolic_shape(", ".join(dims), scope=scope)
            arg_shapes.append(
                jax.ShapeDtypeStruct(sym, dtype_mod.to_jax_dtype(dtype)))
        else:
            arg_shapes.append(jax.ShapeDtypeStruct(
                tuple(int(d) for d in shape), dtype_mod.to_jax_dtype(dtype)))

    param_shapes = {n: jax.ShapeDtypeStruct(a.shape, a.dtype)
                    for n, a in param_arrays.items()}
    exported = jax_export.export(jax.jit(pure))(param_shapes, *arg_shapes)

    d = os.path.dirname(path_prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    np.savez(path_prefix + ".pdiparams.npz",
             **{n: np.asarray(a) for n, a in param_arrays.items()})
    with open(path_prefix + ".pdmeta", "wb") as f:
        pickle.dump({"feed_names": feed_names,
                     "param_names": pnames,
                     "format": "stablehlo-static-v1"}, f)


class _LoadedInferenceProgram:
    """Stands in for the pruned inference Program after load; Executor.run
    accepts it via duck typing in load_inference_model's returned closure."""

    def __init__(self, exported, params, feed_names):
        self._exported = exported
        self._params = params
        self._feed_names = feed_names

    def run(self, feed: dict):
        datas = [jnp.asarray(feed[n]) for n in self._feed_names]
        return [np.asarray(o) for o in self._exported.call(self._params, *datas)]


def load_inference_model(path_prefix: str, executor=None):
    """Returns [program, feed_target_names, fetch_targets] like the
    reference; fetch_targets are opaque handles — pass them (or not) to
    ``executor.run``-style calls on the returned program."""
    from jax import export as jax_export

    with open(path_prefix + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    params = {n: jnp.asarray(a)
              for n, a in np.load(path_prefix + ".pdiparams.npz").items()}
    with open(path_prefix + ".pdmeta", "rb") as f:
        meta = pickle.load(f)
    prog = _LoadedInferenceProgram(exported, params, meta["feed_names"])
    fetch_targets = list(range(len(exported.out_avals)))
    return [prog, meta["feed_names"], fetch_targets]
