"""Static-mode gradients: paddle.static.gradients / append_backward.

Reference: paddle.static.append_backward (base/backward.py — appends grad
ops to the main program) and paddle.static.gradients. In the
record-and-replay design the "appended backward" is ONE recorded statement
whose pure function replays the loss slice and takes jax.grad — the
Executor then compiles it like any other op, so fetching a gradient
variable costs one fused XLA program, not a hand-built grad-op graph.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd.engine import apply_op
from ..tensor.tensor import Parameter, Tensor
from .program import Program


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """d targets / d inputs as new program variables.

    ``inputs`` must be feed placeholders (static.data) or Parameters —
    gradients w.r.t. intermediate activations are not part of the v1
    surface (the reference's main uses are these two).
    """
    if no_grad_set:
        raise NotImplementedError(
            "gradients(no_grad_set=...) is not supported; mark tensors with "
            "stop_gradient=True before recording instead")
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    target_vids = []
    for t in targets:
        tv = getattr(t, "_static_vid", None)
        if tv is None:
            raise ValueError(
                "gradients(): targets must be static Program vars")
        target_vids.append(tv[1])
    prog: Program = getattr(targets[0], "_static_vid")[0]
    stmts = prog.slice_for(set(target_vids))

    produced = {v for st in stmts for v in st.out_vids}
    consumed = {ref for st in stmts
                for kind, ref in st.leaf_refs if kind == "v"}
    pnames = sorted({ref for st in stmts
                     for kind, ref in st.leaf_refs if kind == "p"})
    feed_names = [n for n, fv in prog._feeds.items()
                  if fv in (consumed | set(target_vids))
                  and fv not in produced]
    feed_vids = [prog._feeds[n] for n in feed_names]

    # fixed argument order: feeds then params
    arg_tensors = [prog._feed_tensors[n] for n in feed_names] + [
        prog._params[n] for n in pnames]

    diff_idx = []
    for x in inputs:
        xv = getattr(x, "_static_vid", None)
        if isinstance(x, Parameter) and x.name in pnames:
            diff_idx.append(len(feed_names) + pnames.index(x.name))
        elif xv is not None and xv[1] in feed_vids:
            diff_idx.append(feed_vids.index(xv[1]))
        else:
            raise ValueError(
                f"gradients(): input {x!r} is neither a feed placeholder "
                "nor a Parameter used by the targets")

    tgs = [None] * len(targets)
    if target_gradients is not None:
        tgl = (target_gradients
               if isinstance(target_gradients, (list, tuple))
               else [target_gradients])
        if len(tgl) != len(targets):
            raise ValueError(
                "target_gradients must match targets in length")
        tgs = [t._data if isinstance(t, Tensor)
               else (jnp.asarray(t) if t is not None else None)
               for t in tgl]

    def fn(*arrays):
        feeds = dict(zip(feed_vids, arrays[: len(feed_names)]))
        pvals = dict(zip(pnames, arrays[len(feed_names):]))

        def scalar_loss(diff_arrays):
            local_feeds = dict(feeds)
            local_p = dict(pvals)
            for pos, a in zip(diff_idx, diff_arrays):
                if pos < len(feed_names):
                    local_feeds[feed_vids[pos]] = a
                else:
                    local_p[pnames[pos - len(feed_names)]] = a
            env = dict(local_feeds)
            for st in stmts:
                leaf_vals = []
                for kind, ref in st.leaf_refs:
                    if kind == "v":
                        leaf_vals.append(env[ref])
                    elif kind == "p":
                        leaf_vals.append(local_p[ref])
                    else:
                        leaf_vals.append(ref)
                a_, kw = jax.tree.unflatten(st.treedef, leaf_vals)
                out = st.fn(*a_, **kw)
                for v, val in zip(st.out_vids, jax.tree.flatten(out)[0]):
                    env[v] = val
            # reference semantics: grads sum over all targets, each with an
            # implicit all-ones cotangent unless target_gradients given
            total = 0.0
            for tvid, tg in zip(target_vids, tgs):
                out = env[tvid]
                total = total + (jnp.sum(out * tg) if tg is not None
                                 else jnp.sum(out))
            return total

        diff_arrays = [arrays[i] for i in diff_idx]
        return tuple(jax.grad(scalar_loss)(diff_arrays))

    grads = apply_op("gradients", fn, *arg_tensors)
    return list(grads) if isinstance(grads, (tuple, list)) else [grads]


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Returns [(param, grad_var)] (reference: paddle.static.append_backward
    return contract)."""
    vid = getattr(loss, "_static_vid", None)
    if vid is None:
        raise ValueError("append_backward(): loss must be a static var")
    prog: Program = vid[0]
    stmts = prog.slice_for({vid[1]})
    pnames = sorted({ref for st in stmts
                     for kind, ref in st.leaf_refs if kind == "p"})
    params = [prog._params[n] for n in pnames
              if not prog._params[n].stop_gradient]
    if parameter_list is not None:
        wanted = {p.name if isinstance(p, Tensor) else p
                  for p in parameter_list}
        params = [p for p in params if p.name in wanted]
    grads = gradients(loss, params)
    return list(zip(params, grads))
