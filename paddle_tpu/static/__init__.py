"""paddle.static parity package (SURVEY.md §2.3): Program, Executor,
program_guard, data, save/load + inference-model export. Design notes in
``program.py`` — static graph = record once, replay under jax.jit.
"""
from ..jit.api import InputSpec
from . import nn
from .backward import append_backward, gradients
from .executor import CompiledProgram, Executor
from .io import (
    load,
    load_inference_model,
    save,
    save_inference_model,
)
from .program import (
    Program,
    data,
    default_main_program,
    default_startup_program,
    disable_static,
    enable_static,
    in_static_mode,
    program_guard,
)
from .misc import (
    BuildStrategy,
    ExecutionStrategy,
    ExponentialMovingAverage,
    Print,
    Variable,
    WeightNormParamAttr,
    accuracy,
    auc,
    cpu_places,
    create_global_var,
    create_parameter,
    cuda_places,
    deserialize_persistables,
    deserialize_program,
    device_guard,
    global_scope,
    load_from_file,
    load_program_state,
    name_scope,
    normalize_program,
    py_func,
    save_to_file,
    scope_guard,
    serialize_persistables,
    serialize_program,
    set_program_state,
    xpu_places,
)

__all__ = [
    "InputSpec", "nn", "CompiledProgram", "Executor", "Program", "data",
    "default_main_program", "default_startup_program", "disable_static",
    "enable_static", "in_static_mode", "program_guard", "load",
    "load_inference_model", "save", "save_inference_model",
    "gradients", "append_backward",
    "BuildStrategy", "ExecutionStrategy", "ExponentialMovingAverage",
    "Print", "Variable", "WeightNormParamAttr", "accuracy", "auc",
    "cpu_places", "create_global_var", "create_parameter", "cuda_places",
    "deserialize_persistables", "deserialize_program", "device_guard",
    "global_scope", "load_from_file", "load_program_state", "name_scope",
    "normalize_program", "py_func", "save_to_file", "scope_guard",
    "serialize_persistables", "serialize_program", "set_program_state",
    "xpu_places",
]
