"""paddle.static parity package (SURVEY.md §2.3): Program, Executor,
program_guard, data, save/load + inference-model export. Design notes in
``program.py`` — static graph = record once, replay under jax.jit.
"""
from ..jit.api import InputSpec
from . import nn
from .backward import append_backward, gradients
from .executor import CompiledProgram, Executor
from .io import (
    load,
    load_inference_model,
    save,
    save_inference_model,
)
from .program import (
    Program,
    data,
    default_main_program,
    default_startup_program,
    disable_static,
    enable_static,
    in_static_mode,
    program_guard,
)
from .misc import (
    BuildStrategy,
    ExecutionStrategy,
    ExponentialMovingAverage,
    Print,
    Variable,
    WeightNormParamAttr,
    accuracy,
    auc,
    cpu_places,
    create_global_var,
    create_parameter,
    cuda_places,
    deserialize_persistables,
    deserialize_program,
    device_guard,
    global_scope,
    load_from_file,
    load_program_state,
    name_scope,
    normalize_program,
    py_func,
    save_to_file,
    scope_guard,
    serialize_persistables,
    serialize_program,
    set_program_state,
    xpu_places,
)

__all__ = [
    "InputSpec", "nn", "CompiledProgram", "Executor", "Program", "data",
    "default_main_program", "default_startup_program", "disable_static",
    "enable_static", "in_static_mode", "program_guard", "load",
    "load_inference_model", "save", "save_inference_model",
    "gradients", "append_backward",
    "BuildStrategy", "ExecutionStrategy", "ExponentialMovingAverage",
    "Print", "Variable", "WeightNormParamAttr", "accuracy", "auc",
    "cpu_places", "create_global_var", "create_parameter", "cuda_places",
    "deserialize_persistables", "deserialize_program", "device_guard",
    "global_scope", "load_from_file", "load_program_state", "name_scope",
    "normalize_program", "py_func", "save_to_file", "scope_guard",
    "serialize_persistables", "serialize_program", "set_program_state",
    "xpu_places",
]


# --- IPU surface (reference static/__init__.py exports these; a reference
# build without IPU support raises on use — identical behavior here, where
# the accelerator is the TPU) ------------------------------------------------
def _no_ipu(name):
    raise RuntimeError(
        f"paddle.static.{name} requires the IPU backend; this build targets "
        "TPU (XLA). Same behavior as a reference build compiled without "
        "IPU support.")


def ipu_shard_guard(index=-1, stage=-1):
    _no_ipu("ipu_shard_guard")


def set_ipu_shard(call_func, index=-1, stage=-1):
    _no_ipu("set_ipu_shard")


class IpuStrategy:
    def __init__(self, *a, **k):
        _no_ipu("IpuStrategy")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        _no_ipu("IpuCompiledProgram")


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """CTR metric bundle (reference static/__init__ export; the metric
    itself is parameter-server infra — SURVEY §7.4 exclusion). The
    streaming AUC it feeds is served by paddle_tpu.metric.Auc."""
    raise NotImplementedError(
        "ctr_metric_bundle is parameter-server infrastructure (out of the "
        "TPU build's scope; SURVEY §7.4). Use paddle_tpu.metric.Auc for "
        "streaming AUC.")


__all__ += ["ipu_shard_guard", "set_ipu_shard", "IpuStrategy",
            "IpuCompiledProgram", "ctr_metric_bundle"]
