"""paddle.static parity package (SURVEY.md §2.3): Program, Executor,
program_guard, data, save/load + inference-model export. Design notes in
``program.py`` — static graph = record once, replay under jax.jit.
"""
from ..jit.api import InputSpec
from . import nn
from .backward import append_backward, gradients
from .executor import CompiledProgram, Executor
from .io import (
    load,
    load_inference_model,
    save,
    save_inference_model,
)
from .program import (
    Program,
    data,
    default_main_program,
    default_startup_program,
    disable_static,
    enable_static,
    in_static_mode,
    program_guard,
)

__all__ = [
    "InputSpec", "nn", "CompiledProgram", "Executor", "Program", "data",
    "default_main_program", "default_startup_program", "disable_static",
    "enable_static", "in_static_mode", "program_guard", "load",
    "load_inference_model", "save", "save_inference_model",
    "gradients", "append_backward",
]
