"""paddle.signal parity: frame / overlap_add / stft / istft.

Reference: python/paddle/signal.py (stft returns complex
[..., n_fft//2+1, num_frames] with center padding; istft inverts with
window-envelope normalization). All jnp — jits onto TPU; the framing is a
strided gather like audio.features, shared contract with the reference.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .autograd.engine import apply_op
from .tensor.tensor import Tensor


def frame(x: Tensor, frame_length: int, hop_length: int, axis: int = -1):
    """Slice into overlapping frames: [..., T] -> [..., frame_length,
    num_frames] (axis=-1, reference default)."""

    def fn(v):
        T = v.shape[-1]
        n = 1 + (T - frame_length) // hop_length
        idx = (jnp.arange(n)[:, None] * hop_length
               + jnp.arange(frame_length)[None, :])
        frames = v[..., idx]  # [..., n, frame_length]
        return jnp.swapaxes(frames, -1, -2)  # [..., frame_length, n]

    return apply_op("frame", fn, x)


def overlap_add(x: Tensor, hop_length: int, axis: int = -1):
    """Inverse of frame: [..., frame_length, n] -> [..., T]."""

    def fn(v):
        fl, n = v.shape[-2], v.shape[-1]
        T = (n - 1) * hop_length + fl
        out_shape = v.shape[:-2] + (T,)
        out = jnp.zeros(out_shape, v.dtype)
        idx = (jnp.arange(n)[:, None] * hop_length
               + jnp.arange(fl)[None, :])  # [n, fl]
        return out.at[..., idx].add(jnp.swapaxes(v, -1, -2))

    return apply_op("overlap_add", fn, x)


def stft(x: Tensor, n_fft: int, hop_length: int | None = None,
         win_length: int | None = None, window: Tensor | None = None,
         center: bool = True, pad_mode: str = "reflect",
         normalized: bool = False, onesided: bool = True, name=None):
    """[..., T] -> complex [..., freq, frames] (reference signal.stft)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    wdata = window._data if isinstance(window, Tensor) else window

    def fn(v, w):
        if center:
            pad = [(0, 0)] * (v.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            v = jnp.pad(v, pad, mode=pad_mode)
        T = v.shape[-1]
        n = 1 + (T - n_fft) // hop_length
        idx = (jnp.arange(n)[:, None] * hop_length
               + jnp.arange(n_fft)[None, :])
        frames = v[..., idx]  # [..., n, n_fft]
        if w is None:
            w = jnp.ones(win_length, v.dtype)
        if win_length < n_fft:
            lpad = (n_fft - win_length) // 2
            w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
        spec = (jnp.fft.rfft(frames * w, n=n_fft, axis=-1) if onesided
                else jnp.fft.fft(frames * w, n=n_fft, axis=-1))
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.moveaxis(spec, -1, -2)  # [..., freq, frames]

    return apply_op("stft", fn, x, wdata)


def istft(x: Tensor, n_fft: int, hop_length: int | None = None,
          win_length: int | None = None, window: Tensor | None = None,
          center: bool = True, normalized: bool = False,
          onesided: bool = True, length: int | None = None,
          return_complex: bool = False, name=None):
    """Inverse STFT with window-envelope normalization (reference
    signal.istft)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    wdata = window._data if isinstance(window, Tensor) else window

    def fn(spec, w):
        spec = jnp.moveaxis(spec, -2, -1)  # [..., frames, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        frames = (jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided
                  else jnp.fft.ifft(spec, n=n_fft, axis=-1).real)
        if w is None:
            w = jnp.ones(win_length, frames.dtype)
        if win_length < n_fft:
            lpad = (n_fft - win_length) // 2
            w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
        frames = frames * w
        n = frames.shape[-2]
        T = (n - 1) * hop_length + n_fft
        idx = (jnp.arange(n)[:, None] * hop_length
               + jnp.arange(n_fft)[None, :])
        out = jnp.zeros(frames.shape[:-2] + (T,), frames.dtype)
        out = out.at[..., idx].add(frames)
        # window-envelope normalization (COLA division)
        env = jnp.zeros((T,), frames.dtype)
        env = env.at[idx.reshape(-1)].add(jnp.tile(w * w, n))
        out = out / jnp.maximum(env, 1e-11)
        if center:
            out = out[..., n_fft // 2: T - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        return out

    return apply_op("istft", fn, x, wdata)


__all__ = ["frame", "overlap_add", "stft", "istft"]
