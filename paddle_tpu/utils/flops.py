"""Per-op FLOPs calculator (parity: python/paddle/utils/flops.py:27 `flops`).

Registry of `op_type -> fn(input_shapes, attrs) -> int`. Used by the profiler
summary and the bench MFU calculation. Shapes are plain lists; everything is
host-side arithmetic.
"""
from __future__ import annotations

import math

from ..framework.op_registry import attach_flops, flops_fn


def prod(s) -> int:
    out = 1
    for v in s:
        out *= int(v)
    return out


def register_flops(op_type: str):
    """Attach an analytic FLOPs fn to the op's registry row
    (framework/op_registry.py — the single source of truth)."""

    def decorator(fn):
        attach_flops(op_type, fn)
        return fn

    return decorator


def flops(op_type: str, input_shapes: dict, attrs: dict | None = None) -> int:
    """FLOPs of one op call. Returns 0 for unregistered ops (parity behavior)."""
    fn = flops_fn(op_type)
    if fn is None:
        return 0
    return int(fn(input_shapes, attrs or {}))


def _first(input_shapes, *keys):
    for k in keys:
        v = input_shapes.get(k)
        if v:
            return v[0] if isinstance(v[0], (list, tuple)) else v
    return []


@register_flops("matmul")
@register_flops("matmul_v2")
def _matmul_flops(input_shapes, attrs):
    x = list(_first(input_shapes, "X", "x"))
    y = list(_first(input_shapes, "Y", "y"))
    if not x or not y:
        return 0
    if attrs.get("transpose_X") or attrs.get("trans_x"):
        x[-1], x[-2] = x[-2], x[-1]
    if attrs.get("transpose_Y") or attrs.get("trans_y"):
        y[-1], y[-2] = y[-2], y[-1]
    # batched [..., M, K] @ [..., K, N]: 2*M*K*N per batch element
    batch = prod(x[:-2]) if len(x) > 2 else (prod(y[:-2]) if len(y) > 2 else 1)
    m = x[-2] if len(x) >= 2 else 1
    k = x[-1]
    n = y[-1] if len(y) >= 2 else 1
    return 2 * batch * m * k * n


@register_flops("conv2d")
def _conv2d_flops(input_shapes, attrs):
    x = _first(input_shapes, "Input", "x")  # NCHW
    w = _first(input_shapes, "Filter", "weight")  # OIHW
    if len(x) != 4 or len(w) != 4:
        return 0
    strides = attrs.get("strides", [1, 1])
    paddings = attrs.get("paddings", [0, 0])
    dilations = attrs.get("dilations", [1, 1])
    groups = attrs.get("groups", 1)
    n, _, h, wd = x
    co, ci_g, kh, kw = w
    ho = (h + 2 * paddings[0] - dilations[0] * (kh - 1) - 1) // strides[0] + 1
    wo = (wd + 2 * paddings[-1] - dilations[-1] * (kw - 1) - 1) // strides[-1] + 1
    return 2 * n * co * ho * wo * ci_g * kh * kw // max(groups // groups, 1)


@register_flops("c_embedding")
@register_flops("embedding")
def _embedding_flops(input_shapes, attrs):
    return 0  # gather: no MACs


@register_flops("layer_norm")
def _layer_norm_flops(input_shapes, attrs):
    x = _first(input_shapes, "X", "x")
    return 8 * prod(x) if x else 0


@register_flops("softmax")
def _softmax_flops(input_shapes, attrs):
    x = _first(input_shapes, "X", "x")
    return 5 * prod(x) if x else 0


@register_flops("gelu")
def _gelu_flops(input_shapes, attrs):
    x = _first(input_shapes, "X", "x")
    return 8 * prod(x) if x else 0


def _elementwise(input_shapes, attrs):
    x = _first(input_shapes, "X", "x")
    y = _first(input_shapes, "Y", "y")
    if not x:
        return prod(y) if y else 0
    if not y:
        return prod(x)
    out = [max(a, b) for a, b in zip(
        [1] * (max(len(x), len(y)) - len(x)) + list(x),
        [1] * (max(len(x), len(y)) - len(y)) + list(y))]
    return prod(out)


for _name in ("elementwise_add", "elementwise_mul", "elementwise_div",
              "elementwise_sub", "relu", "relu6", "elu", "leaky_relu",
              "prelu", "silu", "sigmoid", "tanh", "dropout"):
    register_flops(_name)(_elementwise)


@register_flops("flash_attention")
def _flash_attention_flops(input_shapes, attrs):
    q = _first(input_shapes, "q", "Q")
    if len(q) != 4:
        return 0
    b, s, h, d = q
    causal = attrs.get("causal", False)
    f = 4 * b * h * s * s * d  # QK^T + PV
    return f // 2 if causal else f


def attention_flops(batch: int, seq: int, heads: int, head_dim: int,
                    causal: bool = True) -> int:
    """Helper for MFU math in bench/profiler."""
    f = 4 * batch * heads * seq * seq * head_dim
    return f // 2 if causal else f


def transformer_flops(batch: int, seq: int, hidden: int, layers: int,
                      vocab: int, ffn_mult: int = 4, causal: bool = True) -> int:
    """Approximate fwd FLOPs of a GPT block stack + LM head (6ND-style)."""
    per_layer = 2 * seq * (4 * hidden * hidden + 2 * ffn_mult * hidden * hidden)
    attn = 4 * seq * seq * hidden * (0.5 if causal else 1.0)
    head = 2 * seq * hidden * vocab
    return int(batch * (layers * (per_layer + attn) + head))
