"""Per-op FLOPs calculator (parity: python/paddle/utils/flops.py:27 `flops`).

Registry of `op_type -> fn(input_shapes, attrs) -> int`. Used by the profiler
summary and the bench MFU calculation. Shapes are plain lists; everything is
host-side arithmetic.
"""
from __future__ import annotations

import math

from ..framework.op_registry import attach_flops, flops_fn


def prod(s) -> int:
    out = 1
    for v in s:
        out *= int(v)
    return out


def register_flops(op_type: str):
    """Attach an analytic FLOPs fn to the op's registry row
    (framework/op_registry.py — the single source of truth)."""

    def decorator(fn):
        attach_flops(op_type, fn)
        return fn

    return decorator


def flops(op_type: str, input_shapes: dict, attrs: dict | None = None) -> int:
    """FLOPs of one op call. Returns 0 for unregistered ops (parity behavior)."""
    fn = flops_fn(op_type)
    if fn is None:
        return 0
    return int(fn(input_shapes, attrs or {}))


def _first(input_shapes, *keys):
    for k in keys:
        v = input_shapes.get(k)
        if v:
            return v[0] if isinstance(v[0], (list, tuple)) else v
    return []


@register_flops("matmul")
@register_flops("matmul_v2")
def _matmul_flops(input_shapes, attrs):
    x = list(_first(input_shapes, "X", "x"))
    y = list(_first(input_shapes, "Y", "y"))
    if not x or not y:
        return 0
    if attrs.get("transpose_X") or attrs.get("trans_x"):
        x[-1], x[-2] = x[-2], x[-1]
    if attrs.get("transpose_Y") or attrs.get("trans_y"):
        y[-1], y[-2] = y[-2], y[-1]
    # batched [..., M, K] @ [..., K, N]: 2*M*K*N per batch element
    batch = prod(x[:-2]) if len(x) > 2 else (prod(y[:-2]) if len(y) > 2 else 1)
    m = x[-2] if len(x) >= 2 else 1
    k = x[-1]
    n = y[-1] if len(y) >= 2 else 1
    return 2 * batch * m * k * n


@register_flops("mm")
@register_flops("bmm")
def _mm_flops(input_shapes, attrs):
    return _matmul_flops(input_shapes, attrs)


@register_flops("addmm")
def _addmm_flops(input_shapes, attrs):
    # input + alpha * (x @ y): the GEMM dominates; + out adds per element
    x = list(_first(input_shapes, "X", "x"))
    y = list(_first(input_shapes, "Y", "y"))
    if len(x) < 2 or len(y) < 2:
        return 0
    return 2 * x[-2] * x[-1] * y[-1] + x[-2] * y[-1]


@register_flops("mv")
def _mv_flops(input_shapes, attrs):
    x = _first(input_shapes, "X", "x")
    return 2 * prod(x) if x else 0  # [m, k] @ [k] = 2mk


@register_flops("linear")
@register_flops("fused_linear")
def _linear_flops(input_shapes, attrs):
    # x [..., k] @ w [k, n] (+ bias)
    x = _first(input_shapes, "Input", "x", "X")
    w = _first(input_shapes, "W", "weight", "Y", "y")
    if not x or len(w) < 2:
        return 0
    return 2 * prod(x[:-1]) * x[-1] * w[-1] + prod(x[:-1]) * w[-1]


@register_flops("weight_only_linear")
def _weight_only_linear_flops(input_shapes, attrs):
    # dequant epilogue rides the GEMM: count the GEMM MACs
    return _linear_flops(input_shapes, attrs)


@register_flops("quant_matmul")
def _quant_matmul_flops(input_shapes, attrs):
    # fused weight-only GEMM: x [..., K] @ dequant(q [K|K/2, N]) — the
    # in-kernel dequant rides the GEMM MACs; K comes from x (the weight
    # may be nibble-packed int4, so its own in-dim can be K/2)
    x = _first(input_shapes, "Input", "x", "X")
    w = _first(input_shapes, "W", "weight", "qweight", "Y", "y")
    if not x or len(w) < 2:
        return 0
    return 2 * prod(x[:-1]) * x[-1] * w[-1]


@register_flops("grouped_matmul")
def _grouped_matmul_flops(input_shapes, attrs):
    # ragged grouped GEMM: every row of x [M, K] hits exactly one
    # expert's [K, N] tile — MACs are group-size-independent (2*M*K*N);
    # the weight stack is [E, K|K/2, N] (int4 may be nibble-packed)
    x = _first(input_shapes, "Input", "x", "X")
    w = _first(input_shapes, "W", "weights", "qweight", "Y", "y")
    if not x or len(w) < 3:
        return 0
    return 2 * prod(x[:-1]) * x[-1] * w[-1]


@register_flops("weight_quantize")
def _weight_quantize_flops(input_shapes, attrs):
    # absmax reduce + scale divide + round/clip: ~4 passes over [K, N]
    w = _first(input_shapes, "X", "x", "w")
    return 4 * prod(w) if w else 0


@register_flops("weight_dequantize")
def _weight_dequantize_flops(input_shapes, attrs):
    # one widen-and-scale pass over the [K, N] weight
    w = _first(input_shapes, "X", "x", "w")
    return 2 * prod(w) if w else 0


def _conv_flops_nd(input_shapes, attrs, nd):
    """MACs of an N-d convolution (NC<spatial> x, OI<spatial> filter)."""
    x = _first(input_shapes, "Input", "x")
    w = _first(input_shapes, "Filter", "weight")
    if len(x) != nd + 2 or len(w) != nd + 2:
        return 0
    strides = list(attrs.get("strides", [1] * nd)) or [1] * nd
    paddings = list(attrs.get("paddings", [0] * nd)) or [0] * nd
    dilations = list(attrs.get("dilations", [1] * nd)) or [1] * nd
    if len(strides) < nd:
        strides = strides * nd
    if len(paddings) < nd:
        paddings = paddings * nd
    if len(dilations) < nd:
        dilations = dilations * nd
    n = x[0]
    co, ci_g = w[0], w[1]
    out_spatial = 1
    for i in range(nd):
        size = (x[2 + i] + 2 * paddings[i]
                - dilations[i] * (w[2 + i] - 1) - 1) // strides[i] + 1
        out_spatial *= max(size, 0)
    return 2 * n * co * out_spatial * ci_g * prod(w[2:])


@register_flops("conv1d")
def _conv1d_flops(input_shapes, attrs):
    return _conv_flops_nd(input_shapes, attrs, 1)


@register_flops("conv2d")
def _conv2d_flops(input_shapes, attrs):
    return _conv_flops_nd(input_shapes, attrs, 2)


@register_flops("conv3d")
def _conv3d_flops(input_shapes, attrs):
    return _conv_flops_nd(input_shapes, attrs, 3)


def _conv_transpose_flops(input_shapes, attrs):
    """Transposed conv: one MAC per input position per filter tap — the
    gradient-of-conv identity, independent of output padding arithmetic."""
    x = _first(input_shapes, "Input", "x")
    w = _first(input_shapes, "Filter", "weight")
    if not x or len(w) < 3:
        return 0
    # x [n, ci, *sp], w [ci, co_g, *k]
    return 2 * prod(x) * w[1] * prod(w[2:])


for _name in ("conv1d_transpose", "conv2d_transpose", "conv3d_transpose"):
    register_flops(_name)(_conv_transpose_flops)


@register_flops("einsum")
def _einsum_flops(input_shapes, attrs):
    """2 * prod(distinct dim sizes) of the contraction — exact for any
    single-contraction einsum (matmul, attention scores), an upper bound
    for multi-operand chains. An equation/shape mismatch (broadcast
    ellipsis, rank drift) returns 0: a partial product would silently skew
    MFU numbers, an exact-0 reads as "unaccounted"."""
    eq = attrs.get("equation", "")
    operands = input_shapes.get("Operands") or input_shapes.get("operands") \
        or [v[0] if v and isinstance(v[0], (list, tuple)) else v
            for v in input_shapes.values()]
    if not eq or not operands:
        return 0
    lhs = eq.replace(" ", "").split("->")[0].split(",")
    if len(lhs) != len(operands):
        return 0
    sizes = {}
    for labels, shape in zip(lhs, operands):
        labels = labels.replace("...", "")
        if len(labels) != len(shape):
            return 0  # ellipsis/rank mismatch: no partial products
        for ch, sz in zip(labels, shape):
            sizes[ch] = max(sizes.get(ch, 1), int(sz))
    if not sizes:
        return 0
    return 2 * prod(sizes.values())


def _attn_flops(b, heads, s_q, s_k, d, causal):
    f = 4 * b * heads * s_q * s_k * d  # QK^T + PV
    return f // 2 if causal else f


@register_flops("scaled_dot_product_attention")
def _sdpa_flops(input_shapes, attrs):
    # shares the analytic core with flash_attention/flash_attn_unpadded so
    # the three attention spellings cannot drift apart
    q = _first(input_shapes, "q", "Q", "query", "x")
    k = _first(input_shapes, "k", "K", "key")
    if len(q) != 4:
        return 0
    b, s_q, h, d = q
    s_k = k[1] if len(k) == 4 else s_q
    causal = attrs.get("causal", attrs.get("is_causal", False))
    return _attn_flops(b, h, s_q, s_k, d, causal)


@register_flops("flash_attn_unpadded")
def _flash_unpadded_flops(input_shapes, attrs):
    """Varlen (packed) flash attention: q is [total_tokens, H, D]. With
    ``max_seqlen_k`` in attrs this is the padded-layout upper bound
    (total_tokens rows each attending <= max_seqlen_k keys); without it the
    packed batch is treated as one sequence (k total_tokens long)."""
    q = _first(input_shapes, "q", "Q", "query", "x")
    k = _first(input_shapes, "k", "K", "key")
    causal = attrs.get("causal", attrs.get("is_causal", False))
    if len(q) == 4:  # already-padded spelling
        b, s_q, h, d = q
        s_k = k[1] if len(k) == 4 else s_q
        return _attn_flops(b, h, s_q, s_k, d, causal)
    if len(q) != 3:
        return 0
    total, h, d = q
    s_k = int(attrs.get("max_seqlen_k", 0)) or (
        k[0] if len(k) == 3 else total)
    return _attn_flops(1, h, total, s_k, d, causal)


@register_flops("c_embedding")
@register_flops("embedding")
def _embedding_flops(input_shapes, attrs):
    return 0  # gather: no MACs


@register_flops("layer_norm")
def _layer_norm_flops(input_shapes, attrs):
    x = _first(input_shapes, "X", "x")
    return 8 * prod(x) if x else 0


@register_flops("softmax")
def _softmax_flops(input_shapes, attrs):
    x = _first(input_shapes, "X", "x")
    return 5 * prod(x) if x else 0


@register_flops("gelu")
def _gelu_flops(input_shapes, attrs):
    x = _first(input_shapes, "X", "x")
    return 8 * prod(x) if x else 0


def _elementwise(input_shapes, attrs):
    x = _first(input_shapes, "X", "x")
    y = _first(input_shapes, "Y", "y")
    if not x:
        return prod(y) if y else 0
    if not y:
        return prod(x)
    out = [max(a, b) for a, b in zip(
        [1] * (max(len(x), len(y)) - len(x)) + list(x),
        [1] * (max(len(x), len(y)) - len(y)) + list(y))]
    return prod(out)


for _name in ("elementwise_add", "elementwise_mul", "elementwise_div",
              "elementwise_sub", "relu", "relu6", "elu", "leaky_relu",
              "prelu", "silu", "sigmoid", "tanh", "dropout"):
    register_flops(_name)(_elementwise)


@register_flops("flash_attention")
def _flash_attention_flops(input_shapes, attrs):
    q = _first(input_shapes, "q", "Q")
    if len(q) != 4:
        return 0
    b, s, h, d = q
    causal = attrs.get("causal", False)
    f = 4 * b * h * s * s * d  # QK^T + PV
    return f // 2 if causal else f


def attention_flops(batch: int, seq: int, heads: int, head_dim: int,
                    causal: bool = True) -> int:
    """Helper for MFU math in bench/profiler."""
    f = 4 * batch * heads * seq * seq * head_dim
    return f // 2 if causal else f


def transformer_flops(batch: int, seq: int, hidden: int, layers: int,
                      vocab: int, ffn_mult: int = 4, causal: bool = True) -> int:
    """Approximate fwd FLOPs of a GPT block stack + LM head (6ND-style)."""
    per_layer = 2 * seq * (4 * hidden * hidden + 2 * ffn_mult * hidden * hidden)
    attn = 4 * seq * seq * hidden * (0.5 if causal else 1.0)
    head = 2 * seq * hidden * vocab
    return int(batch * (layers * (per_layer + attn) + head))
