"""paddle.utils parity: small host-side helpers.

Parity target: python/paddle/utils/ (reference: deprecated.py, flops.py,
unique_name.py, dlpack.py, install_check.py, lazy_import.py). TPU-native
notes: dlpack rides jax's zero-copy dlpack exchange; install_check runs a
tiny matmul+grad on the default device.
"""
from __future__ import annotations

import functools
import importlib
import warnings

from . import flops as _flops_mod
from . import unique_name
from .flops import flops

__all__ = ["deprecated", "try_import", "unique_name", "flops", "run_check",
           "to_dlpack", "from_dlpack"]


def deprecated(update_to: str = "", since: str = "", reason: str = "", level: int = 0):
    """Decorator emitting a DeprecationWarning on first call.

    Parity: paddle.utils.deprecated (reference python/paddle/utils/deprecated.py).
    """

    def decorator(func):
        msg = f"API '{func.__module__}.{func.__name__}' is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f", use '{update_to}' instead"
        if reason:
            msg += f". Reason: {reason}"
        if level == 2:
            raise RuntimeError(msg)

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        wrapper.__deprecated_message__ = msg
        return wrapper

    return decorator


def try_import(module_name: str, err_msg: str | None = None):
    """Import an optional dependency with a friendly error (lazy_import.py parity)."""
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"Optional dependency {module_name!r} is required for this API"
        ) from e


def to_dlpack(tensor):
    """Export a Tensor as a DLPack-capable object (dlpack.py parity; zero-copy).

    Returns the underlying buffer exposing ``__dlpack__``/``__dlpack_device__``
    (the modern DLPack exchange protocol) rather than a bare capsule, so any
    consumer (numpy, torch, jax) can import it.
    """
    from ..tensor.tensor import Tensor

    return tensor._data if isinstance(tensor, Tensor) else tensor


def from_dlpack(capsule):
    """Import a DLPack capsule as a Tensor."""
    import jax.numpy as jnp

    from ..tensor.tensor import Tensor

    return Tensor(jnp.from_dlpack(capsule))


def run_check():
    """Install check: run a tiny matmul + backward on the default device.

    Parity: paddle.utils.run_check (reference install_check.py) — prints the
    device it verified.
    """
    import jax

    import paddle_tpu as paddle

    x = paddle.randn([4, 8])
    x.stop_gradient = False
    w = paddle.randn([8, 8])
    w.stop_gradient = False
    y = paddle.matmul(x, w).sum()
    y.backward()
    assert w.grad is not None and tuple(w.grad.shape) == (8, 8)
    dev = jax.devices()[0]
    print(f"paddle_tpu is installed successfully! Verified on {dev.platform}:{dev.id}.")
    return True
