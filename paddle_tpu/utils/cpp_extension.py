"""Custom C++ op extension (reference: paddle.utils.cpp_extension — JIT
`load(sources)` building a custom-op .so; SURVEY.md §2.1 custom-op C API,
test/custom_op, test/cpp_extension).

TPU-native contract: a custom C++ op is a HOST op. It plugs into the
framework through ``jax.pure_callback`` so it composes with jit/vmap-free
tracing, and into autograd through the engine's custom-vjp machinery when
the library exports a ``<name>_backward``. Device-side custom kernels are
Pallas (python), not C++ — this API covers the reference's CPU custom-op
surface (IO codecs, samplers, CPU reference kernels).

ABI: see native/pd_custom_op.h.
"""
from __future__ import annotations

import ctypes
import os

import threading

import jax
import jax.numpy as jnp
import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_HEADER_DIR = os.path.join(os.path.dirname(_HERE), "native")
_lock = threading.Lock()

_DTYPE_CODES = {
    np.dtype(np.float32): 0, np.dtype(np.float64): 1,
    np.dtype(np.int32): 2, np.dtype(np.int64): 3,
    np.dtype(np.bool_): 4, np.dtype(np.uint8): 5,
}


class _CTensor(ctypes.Structure):
    _fields_ = [
        ("data", ctypes.c_void_p),
        ("ndim", ctypes.c_int64),
        ("shape", ctypes.POINTER(ctypes.c_int64)),
        ("dtype", ctypes.c_int32),
    ]


def _as_ctensor(arr: np.ndarray, holders: list) -> _CTensor:
    arr = np.ascontiguousarray(arr)
    shape = (ctypes.c_int64 * arr.ndim)(*arr.shape)
    holders.append((arr, shape))  # keep alive for the call
    return _CTensor(
        arr.ctypes.data_as(ctypes.c_void_p), arr.ndim, shape,
        _DTYPE_CODES[arr.dtype])


def get_build_directory() -> str:
    d = os.environ.get("PADDLE_EXTENSION_DIR",
                       os.path.expanduser("~/.cache/paddle_tpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


class CppExtensionLibrary:
    """A loaded custom-op library; ``get_op`` returns framework ops."""

    def __init__(self, name: str, so_path: str):
        self.name = name
        self._lib = ctypes.CDLL(so_path)

    def _fn(self, symbol: str):
        fn = getattr(self._lib, symbol)
        fn.restype = None
        fn.argtypes = [ctypes.POINTER(_CTensor), ctypes.c_int,
                       ctypes.POINTER(_CTensor), ctypes.c_int]
        return fn

    def has(self, symbol: str) -> bool:
        try:
            getattr(self._lib, symbol)
            return True
        except AttributeError:
            return False

    def _invoke(self, symbol, in_arrays, out_specs):
        """Run the C function on host numpy buffers; returns outputs."""
        fn = self._fn(symbol)
        holders: list = []
        ins = (_CTensor * len(in_arrays))(
            *[_as_ctensor(np.asarray(a), holders) for a in in_arrays])
        outs_np = [np.zeros(s.shape, dtype=s.dtype) for s in out_specs]
        outs = (_CTensor * len(outs_np))(
            *[_as_ctensor(o, holders) for o in outs_np])
        fn(ins, len(in_arrays), outs, len(outs_np))
        # _as_ctensor may copy for contiguity; read back via the holders
        return tuple(h[0] for h in holders[len(in_arrays):])

    def get_op(self, op_name: str, infer_shape, infer_dtype=None):
        """Build a framework op from ``<op_name>_forward`` (+ optional
        ``_backward``).

        infer_shape(*input_shapes) -> list of output shapes;
        infer_dtype(*input_dtypes) -> list of output dtypes (defaults to
        the first input's dtype for every output) — exactly the
        reference's InferShapeFn/InferDtypeFn registration contract.
        """
        from ..autograd.engine import apply_op
        from ..framework.op_registry import register_op

        # Custom-op names are user-defined at load time — register the row
        # here (the creation site) so the strict dispatch gate stays sound.
        register_op(op_name, notes="custom C++ op (utils.cpp_extension)")

        fwd_symbol = f"{op_name}_forward"
        bwd_symbol = f"{op_name}_backward"
        has_bwd = self.has(bwd_symbol)

        def _check_dtypes(args):
            for a in args:
                if np.dtype(a.dtype) not in _DTYPE_CODES:
                    raise TypeError(
                        f"custom op '{op_name}': dtype {a.dtype} is not "
                        "supported by the custom-op C ABI (supported: "
                        "float32/float64/int32/int64/bool/uint8; cast "
                        "bf16/fp16 tensors at the boundary)")

        def out_specs_for(args):
            shapes = infer_shape(*[tuple(a.shape) for a in args])
            if infer_dtype is not None:
                dtypes = infer_dtype(*[a.dtype for a in args])
            else:
                dtypes = [args[0].dtype] * len(shapes)
            _check_dtypes([jnp.zeros((), jnp.dtype(d)) for d in dtypes])
            return [jax.ShapeDtypeStruct(tuple(s), jnp.dtype(d))
                    for s, d in zip(shapes, dtypes)]

        def host_forward(*arrays):
            # validated HERE (trace time, after AMP's cast hook has run on
            # the inputs) so an O2 bf16 auto-cast fails with the clear
            # TypeError, not a KeyError inside the XLA callback
            _check_dtypes(arrays)
            specs = out_specs_for(arrays)
            return jax.pure_callback(
                lambda *a: self._invoke(fwd_symbol, a, specs),
                tuple(specs), *arrays)

        # Always a custom_vjp: apply_op eagerly linearizes through jax.vjp
        # when any input requires grad, and a bare pure_callback has no JVP
        # rule — without custom rules even the FORWARD pass of a
        # grad-enabled input would crash.
        @jax.custom_vjp
        def fn(*arrays):
            out = host_forward(*arrays)
            return out if len(out) > 1 else out[0]

        def fwd(*arrays):
            out = host_forward(*arrays)
            return (out if len(out) > 1 else out[0]), (arrays, out)

        def bwd(res, g):
            if not has_bwd:
                raise RuntimeError(
                    f"custom op '{op_name}' has no backward registered "
                    f"(export {bwd_symbol} from the extension library)")
            arrays, outs = res
            gs = g if isinstance(g, tuple) else (g,)
            # C backward fills grads for FLOATING inputs only (in input
            # order); integer/bool primals get symbolic float0 cotangents
            diff = [jnp.issubdtype(jnp.dtype(a.dtype), jnp.floating)
                    for a in arrays]
            grad_specs = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                          for a, d in zip(arrays, diff) if d]
            all_ins = tuple(arrays) + tuple(outs) + tuple(gs)
            grads = jax.pure_callback(
                lambda *a: self._invoke(bwd_symbol, a, grad_specs),
                tuple(grad_specs), *all_ins)
            out_grads, gi = [], 0
            for a, d in zip(arrays, diff):
                if d:
                    out_grads.append(grads[gi])
                    gi += 1
                else:
                    out_grads.append(
                        np.zeros(a.shape, dtype=jax.dtypes.float0))
            return tuple(out_grads)

        fn.defvjp(fwd, bwd)

        def op(*tensors):
            return apply_op(op_name, fn, *tensors)

        return op


def load(name: str, sources, extra_cxx_flags=None, extra_ldflags=None,
         build_directory=None, verbose: bool = False) -> CppExtensionLibrary:
    """Compile ``sources`` into lib<name>.so and load it (reference:
    cpp_extension.load — the JIT build path).

    The cache filename includes a digest of the absolute source paths and
    flags, so two extensions sharing a ``name`` but built from different
    sources never collide in the shared cache dir; mtimes of sources AND
    the ABI header govern rebuilds.
    """
    import hashlib

    from ..native import compile_shared_lib

    sources = [sources] if isinstance(sources, str) else list(sources)
    cxx = [f"-I{_HEADER_DIR}", *(extra_cxx_flags or [])]
    ld = list(extra_ldflags or [])
    digest = hashlib.sha1("\x00".join(
        [os.path.abspath(s) for s in sources] + cxx + ld
    ).encode()).hexdigest()[:10]
    build_dir = build_directory or get_build_directory()
    so = os.path.join(build_dir, f"lib{name}-{digest}.so")
    header = os.path.join(_HEADER_DIR, "pd_custom_op.h")
    with _lock:
        compile_shared_lib(sources, so, extra_flags=cxx, ldflags=ld,
                           deps=[header], verbose=verbose)
    return CppExtensionLibrary(name, so)


__all__ = ["load", "CppExtensionLibrary", "get_build_directory"]
