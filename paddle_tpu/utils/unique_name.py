"""Unique name generator (parity: python/paddle/utils/unique_name.py).

Host-side only: names label parameters/layers; they never enter compiled
programs, so a plain counter map is the whole design.
"""
from __future__ import annotations

import contextlib


class UniqueNameGenerator:
    def __init__(self, prefix: str = ""):
        self._prefix = prefix
        self._ids: dict[str, int] = {}

    def __call__(self, key: str) -> str:
        n = self._ids.get(key, 0)
        self._ids[key] = n + 1
        return "_".join(filter(None, [self._prefix, key, str(n)]))


_generator = UniqueNameGenerator()


def generate(key: str) -> str:
    return _generator(key)


def switch(new_generator: UniqueNameGenerator | None = None) -> UniqueNameGenerator:
    global _generator
    old = _generator
    _generator = new_generator or UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    if isinstance(new_generator, str):
        new_generator = UniqueNameGenerator(new_generator)
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
