"""QAT fake quanters: quantize-dequantize in forward, straight-through
estimator in backward.

Reference: python/paddle/quantization/quanters/abs_max.py
(FakeQuanterWithAbsMaxObserver — moving-average scale learned during QAT,
quant-dequant with STE gradient so training sees quantization error but
gradients flow as identity inside the clip range).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd.engine import apply_op
from ..nn.layer.layers import Layer
from ..tensor.tensor import Tensor


def fake_quant_dequant(x: Tensor, scale, quant_bits: int = 8) -> Tensor:
    """q = round(clip(x)/step); out = q*step. Gradient: identity where
    |x| <= scale, 0 outside (clipped STE)."""
    qmax = float(2 ** (quant_bits - 1) - 1)

    def fn(x_, scale_):
        s = jnp.maximum(scale_, 1e-9)
        step = s / qmax
        clipped = jnp.clip(x_, -s, s)
        qdq = jnp.round(clipped / step) * step
        # STE: forward value is qdq, gradient is d(clipped)/dx
        return clipped + jax.lax.stop_gradient(qdq - clipped)

    return apply_op("fake_quant_dequant", fn, x, scale)


class BaseQuanter(Layer):
    pass


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """Moving-average abs-max scale + fake quant-dequant (QAT training
    collects the scale; eval uses the frozen moving average)."""

    def __init__(self, moving_rate: float = 0.9, quant_bits: int = 8,
                 dtype="float32", name=None):
        super().__init__()
        self._rate = moving_rate
        self._quant_bits = quant_bits
        self._scale = 1e-9

    def bit_length(self):
        return self._quant_bits

    def quant_axis(self):
        return -1

    def scales(self) -> Tensor:
        return Tensor(jnp.asarray(self._scale, jnp.float32))

    def zero_points(self):
        return Tensor(jnp.zeros((), jnp.float32))

    def forward(self, x):
        if self.training:
            cur = float(jnp.abs(x._data).max())
            self._scale = (self._rate * self._scale
                           + (1 - self._rate) * cur) if self._scale > 1e-9 else cur
        return fake_quant_dequant(x, self.scales(), self._quant_bits)


class FakeQuanterChannelWiseAbsMaxObserver(BaseQuanter):
    """Per-output-channel abs-max fake quant for weights (reference:
    channel-wise weight quanter; axis 0 = output channels)."""

    def __init__(self, quant_bits: int = 8, quant_axis: int = 0, **kw):
        super().__init__()
        self._quant_bits = quant_bits
        self._axis = quant_axis

    def bit_length(self):
        return self._quant_bits

    def quant_axis(self):
        return self._axis

    def forward(self, x):
        qmax = float(2 ** (self._quant_bits - 1) - 1)
        axis = self._axis

        def fn(x_):
            reduce_axes = tuple(i for i in range(x_.ndim) if i != axis)
            s = jnp.maximum(jnp.abs(x_).max(axis=reduce_axes, keepdims=True),
                            1e-9)
            step = s / qmax
            clipped = jnp.clip(x_, -s, s)
            qdq = jnp.round(clipped / step) * step
            return clipped + jax.lax.stop_gradient(qdq - clipped)

        return apply_op("fake_channel_quant_dequant", fn, x)
