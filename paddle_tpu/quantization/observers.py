"""PTQ observers: watch activations/weights during calibration and derive
quantization scales.

Reference: python/paddle/quantization/observers (AbsmaxObserver etc.) — an
observer is a Layer inserted into the model that records statistics on
forward and later reports scales()/zero_points().
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..nn.layer.layers import Layer
from ..tensor.tensor import Tensor


class BaseObserver(Layer):
    """Records statistics on every forward; forward is identity."""

    def __init__(self, quant_bits: int = 8):
        super().__init__()
        self._quant_bits = quant_bits

    def bit_length(self):
        return self._quant_bits

    def quant_axis(self):
        return -1

    def scales(self) -> Tensor:
        raise NotImplementedError

    def zero_points(self):
        return Tensor(jnp.zeros((), jnp.float32))

    def _observe(self, x: Tensor):
        raise NotImplementedError

    def forward(self, x):
        self._observe(x)
        return x


class AbsmaxObserver(BaseObserver):
    """Per-tensor abs-max scale (reference:
    quantization/observers/abs_max.py)."""

    def __init__(self, quant_bits: int = 8):
        super().__init__(quant_bits)
        self._max = 1e-9

    def _observe(self, x: Tensor):
        self._max = max(self._max, float(jnp.abs(x._data).max()))

    def scales(self) -> Tensor:
        return Tensor(jnp.asarray(self._max, jnp.float32))


class EMAObserver(BaseObserver):
    """Moving-average abs-max (reference: mse/ema observers family)."""

    def __init__(self, quant_bits: int = 8, moving_rate: float = 0.9):
        super().__init__(quant_bits)
        self._rate = moving_rate
        self._state = None

    def _observe(self, x: Tensor):
        cur = float(jnp.abs(x._data).max())
        self._state = cur if self._state is None else (
            self._rate * self._state + (1 - self._rate) * cur)

    def scales(self) -> Tensor:
        return Tensor(jnp.asarray(self._state or 1e-9, jnp.float32))


class HistObserver(BaseObserver):
    """Histogram percentile observer (reference:
    quantization/observers/hist.py): scale = the percentile of |x| so
    outliers don't blow up the range."""

    def __init__(self, quant_bits: int = 8, percent: float = 0.999,
                 bins_count: int = 2048):
        super().__init__(quant_bits)
        self._percent = percent
        self._bins = bins_count
        self._hist = None
        self._range = 1e-9

    def _observe(self, x: Tensor):
        a = np.abs(np.asarray(x._data)).ravel()
        mx = float(a.max()) if a.size else 0.0
        if self._hist is None:
            self._range = max(mx, self._range)
            self._hist = np.histogram(a, bins=self._bins,
                                      range=(0, self._range))[0].astype(np.float64)
            return
        if mx > self._range:
            # widen: redistribute accumulated counts into the new bin grid
            # (uniform within each old bin), preserving history
            old_edges = np.linspace(0, self._range, self._bins + 1)
            self._range = mx
            new_hist = np.zeros(self._bins, np.float64)
            new_width = self._range / self._bins
            for i, cnt in enumerate(self._hist):
                if cnt == 0:
                    continue
                lo, hi = old_edges[i], old_edges[i + 1]
                lo_bin = int(lo / new_width)
                hi_bin = min(int(np.ceil(hi / new_width)), self._bins)
                span = max(hi_bin - lo_bin, 1)
                new_hist[lo_bin:lo_bin + span] += cnt / span
            self._hist = new_hist
        self._hist += np.histogram(a, bins=self._bins,
                                   range=(0, self._range))[0]

    def scales(self) -> Tensor:
        if self._hist is None:
            return Tensor(jnp.asarray(1e-9, jnp.float32))
        cdf = np.cumsum(self._hist) / max(self._hist.sum(), 1)
        idx = int(np.searchsorted(cdf, self._percent))
        scale = (idx + 1) / self._bins * self._range
        return Tensor(jnp.asarray(max(scale, 1e-9), jnp.float32))
