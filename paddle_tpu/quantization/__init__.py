"""paddle.quantization parity (SURVEY.md §2.8): QuantConfig + QAT/PTQ
drivers, observers, quanters, and quanted layer wrappers.

Reference layout: python/paddle/quantization/{config.py,qat.py,ptq.py,
observers/,quanters/} + nn/quant layers. Workflow parity:

    q_config = QuantConfig(activation=quanter, weight=quanter)
    qat = QAT(q_config); q_model = qat.quantize(model)   # train with fake quant
    ptq = PTQ(q_config); q_model = ptq.quantize(model)   # run calibration data
    final = qat.convert(q_model)                          # freeze scales

TPU stance: "int8 inference" on TPU = XLA int8 dot with dequant epilogue;
the QAT/PTQ phase is numerically identical to the reference (fake
quant-dequant in fp), so convert() freezes scales into the layer for the
serving path rather than rewriting to a separate int8 op set.
"""
from __future__ import annotations

import copy

import jax.numpy as jnp

from ..nn.layer.layers import Layer
from ..tensor.tensor import Tensor
from . import observers, quanters
from .observers import AbsmaxObserver, BaseObserver, EMAObserver, HistObserver
from .quanters import (
    BaseQuanter,
    FakeQuanterChannelWiseAbsMaxObserver,
    FakeQuanterWithAbsMaxObserver,
    fake_quant_dequant,
)


class QuantConfig:
    """Maps layers/types to (activation, weight) quanter factories
    (reference: quantization/config.py — add_layer_config/add_type_config/
    add_name_config with global default)."""

    def __init__(self, activation=None, weight=None):
        self._global = (activation, weight)
        self._type_cfg: dict[type, tuple] = {}
        self._layer_cfg: dict[int, tuple] = {}
        self._name_cfg: dict[str, tuple] = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) else [layer_type]
        for t in types:
            self._type_cfg[t] = (activation, weight)

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_cfg[id(l)] = (activation, weight)

    def add_name_config(self, layer_name, activation=None, weight=None):
        names = layer_name if isinstance(layer_name, (list, tuple)) else [layer_name]
        for n in names:
            self._name_cfg[n] = (activation, weight)

    def _config_for(self, name: str, layer: Layer, path_cfg=None):
        # id() matches only un-copied layers; quantize() resolves ids to
        # dotted paths BEFORE its deepcopy and passes them as path_cfg
        if id(layer) in self._layer_cfg:
            return self._layer_cfg[id(layer)]
        if path_cfg and name in path_cfg:
            return path_cfg[name]
        if name in self._name_cfg or name.split(".")[-1] in self._name_cfg:
            return self._name_cfg.get(name) or self._name_cfg[
                name.split(".")[-1]]
        for t, cfg in self._type_cfg.items():
            if isinstance(layer, t):
                return cfg
        return self._global

    def _resolve_layer_paths(self, model: Layer) -> dict:
        """Map dotted sublayer paths to their add_layer_config entries so
        the config survives the deepcopy in quantize()."""
        out = {}

        def visit(layer, prefix):
            if id(layer) in self._layer_cfg:
                out[prefix] = self._layer_cfg[id(layer)]
            for n, child in layer._sub_layers.items():
                visit(child, f"{prefix}.{n}" if prefix else n)

        visit(model, "")
        return out

    def _make(self, factory):
        if factory is None:
            return None
        if isinstance(factory, type):
            return factory()
        if isinstance(factory, Layer):
            return copy.deepcopy(factory)
        return factory()  # callable factory


class QuantedLinear(Layer):
    """Linear with fake-quanted weight + activation (reference:
    nn/quant/qat/linear.py QuantedLinear)."""

    def __init__(self, linear, activation_quanter, weight_quanter):
        super().__init__()
        self._linear = linear
        self.weight = linear.weight
        self.bias = linear.bias
        self.activation_quanter = activation_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        from ..nn import functional as F

        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return F.linear(x, w, self.bias)


class QuantedConv2D(Layer):
    def __init__(self, conv, activation_quanter, weight_quanter):
        super().__init__()
        self._conv = conv
        self.weight = conv.weight
        self.bias = conv.bias
        self.activation_quanter = activation_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        from ..nn import functional as F

        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        c = self._conv
        return F.conv2d(x, w, self.bias, stride=c._stride,
                        padding=c._padding, dilation=c._dilation,
                        groups=c._groups, data_format=c._data_format)


def _swap(model: Layer, config: QuantConfig, observer_mode: bool,
          path_cfg=None):
    """Replace quantizable sublayers with quanted wrappers, in place on a
    deep copy (reference QAT.quantize walks full_name->layer). path_cfg
    carries add_layer_config entries resolved to dotted paths on the
    pre-copy model."""
    from ..nn import Conv2D, Linear

    # the root itself may be a bare quantizable layer
    a_factory, w_factory = config._config_for("", model, path_cfg)
    if isinstance(model, Linear) and (a_factory or w_factory):
        return QuantedLinear(model, config._make(a_factory),
                             config._make(w_factory))
    if isinstance(model, Conv2D) and (a_factory or w_factory):
        return QuantedConv2D(model, config._make(a_factory),
                             config._make(w_factory))

    def visit(parent, prefix):
        for attr_name, child in list(parent._sub_layers.items()):
            path = f"{prefix}.{attr_name}" if prefix else attr_name
            a_factory, w_factory = config._config_for(path, child, path_cfg)
            if isinstance(child, Linear) and (a_factory or w_factory):
                parent._sub_layers[attr_name] = QuantedLinear(
                    child, config._make(a_factory), config._make(w_factory))
            elif isinstance(child, Conv2D) and (a_factory or w_factory):
                parent._sub_layers[attr_name] = QuantedConv2D(
                    child, config._make(a_factory), config._make(w_factory))
            else:
                visit(child, path)

    visit(model, "")
    return model


class QAT:
    """Quantization-aware training driver (reference: quantization/qat.py)."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        path_cfg = self._config._resolve_layer_paths(model)
        target = model if inplace else copy.deepcopy(model)
        target.train()
        return _swap(target, self._config, observer_mode=False,
                     path_cfg=path_cfg)

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        """Freeze: quanters stop updating (eval mode) and scales become
        attributes for export."""
        target = model if inplace else copy.deepcopy(model)
        target.eval()
        return target


class PTQ:
    """Post-training quantization driver (reference: quantization/ptq.py):
    insert observers, run calibration batches, then convert to frozen
    fake-quant using observed scales."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        path_cfg = self._config._resolve_layer_paths(model)
        target = model if inplace else copy.deepcopy(model)
        target.eval()
        return _swap(target, self._config, observer_mode=True,
                     path_cfg=path_cfg)

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        """Replace observers with frozen fake quant-dequant at the observed
        scale."""
        target = model if inplace else copy.deepcopy(model)

        def freeze_one(child):
            obs = child.activation_quanter
            if isinstance(obs, BaseObserver):
                child.activation_quanter = _FrozenQuant(
                    obs.scales(), obs.bit_length())
            wobs = child.weight_quanter
            if isinstance(wobs, BaseObserver):
                child.weight_quanter = _FrozenQuant(
                    wobs.scales(), wobs.bit_length())

        def freeze(parent):
            for child in parent._sub_layers.values():
                if isinstance(child, (QuantedLinear, QuantedConv2D)):
                    freeze_one(child)
                else:
                    freeze(child)

        if isinstance(target, (QuantedLinear, QuantedConv2D)):
            freeze_one(target)  # root-level bare layer case
        else:
            freeze(target)
        return target


class _FrozenQuant(Layer):
    def __init__(self, scale: Tensor, bits: int):
        super().__init__()
        self._scale = scale
        self._bits = bits

    def scales(self):
        return self._scale

    def forward(self, x):
        return fake_quant_dequant(x, self._scale, self._bits)


__all__ = [
    "QuantConfig", "QAT", "PTQ", "QuantedLinear", "QuantedConv2D",
    "observers", "quanters", "BaseObserver", "AbsmaxObserver", "EMAObserver",
    "HistObserver", "BaseQuanter", "FakeQuanterWithAbsMaxObserver",
    "FakeQuanterChannelWiseAbsMaxObserver", "fake_quant_dequant",
]
