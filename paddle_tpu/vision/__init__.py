"""paddle_tpu.vision (parity: python/paddle/vision/)."""
from . import datasets, models, transforms
from .models import LeNet, ResNet, resnet18, resnet34, resnet50, resnet101, resnet152

__all__ = [
    "datasets",
    "models",
    "transforms",
    "LeNet",
    "ResNet",
    "resnet18",
    "resnet34",
    "resnet50",
    "resnet101",
    "resnet152",
]
