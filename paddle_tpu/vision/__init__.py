"""paddle_tpu.vision (parity: python/paddle/vision/)."""
from . import datasets, models, transforms
from .models import LeNet, ResNet, resnet18, resnet34, resnet50, resnet101, resnet152

__all__ = [
    "datasets",
    "models",
    "transforms",
    "LeNet",
    "ResNet",
    "resnet18",
    "resnet34",
    "resnet50",
    "resnet101",
    "resnet152",
]


# --- image backend knobs (reference vision/image.py) ------------------------
_IMAGE_BACKEND = ["pil"]


def set_image_backend(backend):
    """Select the dataset image-decoding backend (reference vision/image.py
    set_image_backend). This build decodes through numpy ('cv2'-style HWC
    arrays); both names are accepted, PIL objects are coerced on use."""
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(
            f"Invalid backend: {backend!r}. Expected 'pil', 'cv2' or 'tensor'")
    _IMAGE_BACKEND[0] = backend


def get_image_backend():
    return _IMAGE_BACKEND[0]


def image_load(path, backend=None):
    """Load an image file (reference vision/image.py image_load): a PIL
    Image for the 'pil' backend, an HWC uint8 ndarray for 'cv2', a CHW
    uint8 Tensor for 'tensor'."""
    import numpy as np

    from PIL import Image

    backend = backend or _IMAGE_BACKEND[0]
    img = Image.open(path)
    if backend == "pil":
        return img
    arr = np.asarray(img)
    if backend == "cv2":
        return arr if arr.ndim == 3 else arr[:, :, None]
    from .ops import Tensor  # tensor backend: CHW like decode_jpeg
    import jax.numpy as jnp

    chw = arr.transpose(2, 0, 1) if arr.ndim == 3 else arr[None]
    return Tensor(jnp.asarray(chw))
