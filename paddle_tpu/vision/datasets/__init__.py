"""Vision datasets (parity: python/paddle/vision/datasets/).

Zero-egress build: dataset classes read local archives when present
(``download=False``-style); ``FakeData`` provides the in-repo synthetic
fixture used by tests and benchmarks (the reference tests likewise run on
small locally generated data rather than real downloads in CI).
"""
from __future__ import annotations

import gzip
import os
import pickle
import tarfile

import numpy as np

from ...io.dataset import Dataset


class FakeData(Dataset):
    """Synthetic labeled images, deterministic per index."""

    def __init__(self, num_samples=1000, shape=(3, 32, 32), num_classes=10, transform=None, seed=0):
        self.num_samples = num_samples
        self.shape = tuple(shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.rand(*self.shape).astype("float32")
        label = rng.randint(0, self.num_classes)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(label)

    def __len__(self):
        return self.num_samples


class Cifar10(Dataset):
    """CIFAR-10 from a local ``cifar-10-python.tar.gz`` (no download)."""

    def __init__(self, data_file=None, mode="train", transform=None, download=False, backend="cv2"):
        if download:
            raise RuntimeError(
                "this build has no network egress; place cifar-10-python.tar.gz "
                "locally and pass data_file="
            )
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(f"CIFAR archive not found: {data_file}")
        self.transform = transform
        self.mode = mode
        names = (
            [f"data_batch_{i}" for i in range(1, 6)] if mode == "train" else ["test_batch"]
        )
        xs, ys = [], []
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                base = os.path.basename(m.name)
                if base in names:
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    xs.append(d[b"data"])
                    ys.extend(d[b"labels"])
        self.data = np.concatenate(xs).reshape(-1, 3, 32, 32).astype("float32") / 255.0
        self.labels = np.asarray(ys, "int64")

    def __getitem__(self, idx):
        img = self.data[idx]
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0)).astype("float32")
        return img, self.labels[idx]

    def __len__(self):
        return len(self.data)


class MNIST(Dataset):
    """MNIST from local idx-gz files (no download)."""

    def __init__(self, image_path=None, label_path=None, mode="train", transform=None, download=False, backend="cv2"):
        if download:
            raise RuntimeError("no network egress; provide image_path/label_path")
        for p in (image_path, label_path):
            if p is None or not os.path.exists(p):
                raise FileNotFoundError(f"MNIST file not found: {p}")
        with gzip.open(image_path, "rb") as f:
            buf = f.read()
            self.images = (
                np.frombuffer(buf, np.uint8, offset=16).reshape(-1, 1, 28, 28).astype("float32") / 255.0
            )
        with gzip.open(label_path, "rb") as f:
            self.labels = np.frombuffer(f.read(), np.uint8, offset=8).astype("int64")
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0)).astype("float32")
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    """CIFAR-100 from a local ``cifar-100-python.tar.gz``: same layout with
    'train'/'test' members and b'fine_labels'."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend="cv2"):
        if download:
            raise RuntimeError(
                "this build has no network egress; place "
                "cifar-100-python.tar.gz locally and pass data_file=")
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(f"CIFAR archive not found: {data_file}")
        self.transform = transform
        self.mode = mode
        want = "train" if mode == "train" else "test"
        xs, ys = [], []
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                if os.path.basename(m.name) == want:
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    xs.append(d[b"data"])
                    ys.extend(d[b"fine_labels"])
        self.data = np.concatenate(xs).reshape(-1, 3, 32, 32).astype(
            "float32") / 255.0
        self.labels = np.asarray(ys, "int64")


class FashionMNIST(MNIST):
    """Same idx-gz format as MNIST (reference: vision/datasets/mnist.py
    FashionMNIST subclass)."""


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp", ".npy")


def _load_image(path: str):
    if path.endswith(".npy"):
        return np.load(path)
    from PIL import Image  # pillow ships with the baked environment

    with Image.open(path) as img:
        return np.asarray(img.convert("RGB"))


def _scan_files(root, extensions, is_valid_file):
    """Sorted recursive walk filtered by extension/predicate (shared by
    DatasetFolder and ImageFolder)."""
    for dirpath, _, files in sorted(os.walk(root)):
        for fname in sorted(files):
            path = os.path.join(dirpath, fname)
            ok = (is_valid_file(path) if is_valid_file
                  else fname.lower().endswith(extensions))
            if ok:
                yield path


class DatasetFolder(Dataset):
    """class-per-subdirectory image tree (reference:
    vision/datasets/folder.py DatasetFolder): root/<class_x>/xxx.png."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _load_image
        self.transform = transform
        extensions = extensions or IMG_EXTENSIONS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class folders under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            for path in _scan_files(os.path.join(root, c), extensions,
                                    is_valid_file):
                self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid files under {root}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, np.int64(target)

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Unlabeled flat/recursive image list (reference: folder.py
    ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _load_image
        self.transform = transform
        extensions = extensions or IMG_EXTENSIONS
        self.samples = list(_scan_files(root, extensions, is_valid_file))
        if not self.samples:
            raise RuntimeError(f"no valid files under {root}")

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)


__all__ = ["FakeData", "Cifar10", "Cifar100", "MNIST", "FashionMNIST",
           "DatasetFolder", "ImageFolder", "Flowers", "VOC2012"]


class Flowers(Dataset):
    """Flowers102 (reference vision/datasets/flowers.py:41): images tarball
    + imagelabels.mat + setid.mat; train/valid/test index splits.

    Zero-egress build: all three files must be given locally (the reference
    downloads them)."""

    _SPLIT_KEY = {"train": "trnid", "valid": "valid", "test": "tstid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False, backend=None):
        import tarfile

        for path, what in ((data_file, "102flowers.tgz"),
                           (label_file, "imagelabels.mat"),
                           (setid_file, "setid.mat")):
            if path is None or not os.path.exists(path):
                raise RuntimeError(
                    f"Flowers needs a local {what} (no network egress in "
                    "this build; the reference downloads it)")
        from scipy.io import loadmat

        self.labels = loadmat(label_file)["labels"][0]  # 1-based per image
        setid = loadmat(setid_file)
        self.indexes = setid[self._SPLIT_KEY[mode]][0]  # 1-based image ids
        self.transform = transform
        self.backend = backend or "cv2"
        self._tar = tarfile.open(data_file)
        self._members = {os.path.basename(n): n
                         for n in self._tar.getnames()
                         if n.endswith(".jpg")}

    def __getitem__(self, idx):
        import io as _io

        from PIL import Image

        img_id = int(self.indexes[idx])
        name = f"image_{img_id:05d}.jpg"
        with self._tar.extractfile(self._members[name]) as f:
            img = Image.open(_io.BytesIO(f.read()))
            img.load()
        label = np.array([int(self.labels[img_id - 1])], np.int64)
        out = img if self.backend == "pil" else np.asarray(img)
        if self.transform is not None:
            out = self.transform(out)
        return out, label

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation pairs (reference
    vision/datasets/voc2012.py:39): (image, label-mask) over the
    ImageSets/Segmentation split lists inside the VOCtrainval tarball."""

    _SPLIT_FILE = {"train": "train.txt", "valid": "val.txt",
                   "test": "val.txt"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        import tarfile

        if data_file is None or not os.path.exists(data_file):
            raise RuntimeError(
                "VOC2012 needs a local VOCtrainval tarball (no network "
                "egress in this build; the reference downloads it)")
        self.transform = transform
        self.backend = backend or "cv2"
        self._tar = tarfile.open(data_file)
        names = self._tar.getnames()
        split_suffix = ("ImageSets/Segmentation/"
                        + self._SPLIT_FILE[mode])
        split_member = next((n for n in names if n.endswith(split_suffix)),
                            None)
        if split_member is None:
            raise ValueError(f"archive lacks {split_suffix}")
        with self._tar.extractfile(split_member) as f:
            ids = [l.strip() for l in f.read().decode().splitlines()
                   if l.strip()]
        self._jpeg = {os.path.basename(n)[:-4]: n for n in names
                      if n.endswith(".jpg")}
        self._png = {os.path.basename(n)[:-4]: n for n in names
                     if n.endswith(".png") and "SegmentationClass" in n}
        self.ids = [i for i in ids if i in self._jpeg and i in self._png]

    def _read(self, member):
        import io as _io

        from PIL import Image

        with self._tar.extractfile(member) as f:
            img = Image.open(_io.BytesIO(f.read()))
            img.load()
        return img

    def __getitem__(self, idx):
        img = self._read(self._jpeg[self.ids[idx]])
        mask = self._read(self._png[self.ids[idx]])
        if self.backend != "pil":
            img, mask = np.asarray(img), np.asarray(mask)
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return len(self.ids)
