"""Geometric + photometric transforms over numpy HWC images — the tail of
the reference's transform set (python/paddle/vision/transforms/transforms.py:
RandomResizedCrop, ColorJitter family, affine/rotate/perspective, Grayscale,
RandomErasing; functional.py: hflip/vflip/crop/center_crop/pad/adjust_*/
rotate/affine/perspective/to_grayscale/erase)."""
from __future__ import annotations

import math
import numbers
import random

import numpy as np

from .transforms import BaseTransform, _as_hwc, resize
from .transforms import Pad as _PadTransform

_LUMA = np.array([0.299, 0.587, 0.114], np.float32)


# --- functional: flips / crops / pad ---------------------------------------
def hflip(img):
    """Horizontal flip (reference functional.py hflip)."""
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    """Vertical flip (reference functional.py vflip)."""
    return _as_hwc(img)[::-1]


def crop(img, top, left, height, width):
    return _as_hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _as_hwc(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    oh, ow = output_size
    h, w = arr.shape[:2]
    return crop(arr, max((h - oh) // 2, 0), max((w - ow) // 2, 0), oh, ow)


def pad(img, padding, fill=0, padding_mode="constant"):
    """Functional spelling of the Pad transform (reference functional.py
    pad)."""
    return _PadTransform(padding, fill, padding_mode)(img)


# --- functional: photometric ------------------------------------------------
def _blend(a, b, factor):
    out = a.astype(np.float32) * factor + b.astype(np.float32) * (1 - factor)
    return _like(out, a)


def _like(out, ref):
    if np.issubdtype(np.asarray(ref).dtype, np.integer):
        return np.clip(out, 0, 255).astype(np.asarray(ref).dtype)
    return out


def adjust_brightness(img, brightness_factor):
    arr = _as_hwc(img)
    return _blend(arr, np.zeros_like(arr), brightness_factor)


def adjust_contrast(img, contrast_factor):
    arr = _as_hwc(img)
    mean = (arr.astype(np.float32) @ _LUMA[: arr.shape[2]]).mean() \
        if arr.shape[2] == 3 else arr.astype(np.float32).mean()
    return _blend(arr, np.full_like(arr, mean, dtype=np.float32), contrast_factor)


def adjust_saturation(img, saturation_factor):
    arr = _as_hwc(img)
    gray = (arr.astype(np.float32) @ _LUMA[: arr.shape[2]])[:, :, None]
    return _blend(arr, np.broadcast_to(gray, arr.shape), saturation_factor)


def adjust_hue(img, hue_factor):
    """Shift hue by ``hue_factor`` (in [-0.5, 0.5] turns) via RGB->HSV->RGB
    (reference functional.py adjust_hue)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError(f"hue_factor {hue_factor} is not in [-0.5, 0.5]")
    arr = _as_hwc(img)
    scale = 255.0 if np.issubdtype(arr.dtype, np.integer) else 1.0
    rgb = arr.astype(np.float32) / scale
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    maxc = rgb.max(-1)
    minc = rgb.min(-1)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0.0)
    dz = np.maximum(delta, 1e-12)
    h = np.where(maxc == r, (g - b) / dz % 6,
                 np.where(maxc == g, (b - r) / dz + 2, (r - g) / dz + 4)) / 6
    h = np.where(delta == 0, 0.0, h)
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6)
    f = h * 6 - i
    p, q, t = v * (1 - s), v * (1 - s * f), v * (1 - s * (1 - f))
    i = (i.astype(np.int32) % 6)[..., None]
    out = np.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    return _like(out * scale, arr)


def to_grayscale(img, num_output_channels=1):
    arr = _as_hwc(img)
    gray = arr.astype(np.float32) @ _LUMA[: arr.shape[2]] \
        if arr.shape[2] == 3 else arr.astype(np.float32)[..., 0]
    out = np.repeat(gray[:, :, None], num_output_channels, axis=2)
    return _like(out, arr)


# --- functional: geometric (inverse-mapped affine sampling) ----------------
def _inverse_sample(arr, inv, out_h, out_w, interpolation, fill):
    """Sample arr at inv @ [x_out, y_out, 1] (pixel-center coords)."""
    ys, xs = np.meshgrid(np.arange(out_h), np.arange(out_w), indexing="ij")
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], 0).reshape(3, -1).astype(np.float64)
    src = inv @ coords
    sx = src[0] / src[2]
    sy = src[1] / src[2]
    h, w = arr.shape[:2]
    a = arr.astype(np.float32)
    fill_px = np.broadcast_to(
        np.asarray(fill, np.float32), (arr.shape[2],))

    def sample_nearest(sx, sy):
        xi = np.round(sx).astype(np.int64)
        yi = np.round(sy).astype(np.int64)
        valid = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
        out = np.empty((sx.size, arr.shape[2]), np.float32)
        out[:] = fill_px
        out[valid] = a[yi[valid], xi[valid]]
        return out

    if interpolation == "nearest":
        out = sample_nearest(sx, sy)
    else:  # bilinear with fill outside
        x0 = np.floor(sx).astype(np.int64)
        y0 = np.floor(sy).astype(np.int64)
        wx = (sx - x0).astype(np.float32)[:, None]
        wy = (sy - y0).astype(np.float32)[:, None]

        def at(yi, xi):
            valid = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
            px = np.empty((xi.size, arr.shape[2]), np.float32)
            px[:] = fill_px
            px[valid] = a[yi[valid], xi[valid]]
            return px

        out = (at(y0, x0) * (1 - wx) * (1 - wy) + at(y0, x0 + 1) * wx * (1 - wy)
               + at(y0 + 1, x0) * (1 - wx) * wy + at(y0 + 1, x0 + 1) * wx * wy)
    out = out.reshape(out_h, out_w, arr.shape[2])
    return _like(out, arr)


def _affine_matrix(angle, translate, scale, shear, center):
    rot = math.radians(angle)
    sx, sy = (math.radians(s) for s in shear)
    cx, cy = center
    # M = T(center) @ T(translate) @ R(angle) @ Shear @ Scale @ T(-center)
    a = math.cos(rot - sy) / math.cos(sy)
    b = -math.cos(rot - sy) * math.tan(sx) / math.cos(sy) - math.sin(rot)
    c = math.sin(rot - sy) / math.cos(sy)
    d = -math.sin(rot - sy) * math.tan(sx) / math.cos(sy) + math.cos(rot)
    m = np.array([[a * scale, b * scale, 0.0],
                  [c * scale, d * scale, 0.0],
                  [0.0, 0.0, 1.0]], np.float64)
    m[0, 2] = cx + translate[0] - (m[0, 0] * cx + m[0, 1] * cy)
    m[1, 2] = cy + translate[1] - (m[1, 0] * cx + m[1, 1] * cy)
    return m


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """Affine transform (reference functional.py affine)."""
    arr = _as_hwc(img)
    h, w = arr.shape[:2]
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    if center is None:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    # positive angle = counter-clockwise (PIL/reference convention); in
    # y-down image coordinates that is a negative math-convention rotation
    m = _affine_matrix(-angle, translate, scale, shear, center)
    return _inverse_sample(arr, np.linalg.inv(m), h, w, interpolation, fill)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate counter-clockwise by ``angle`` degrees (reference
    functional.py rotate)."""
    arr = _as_hwc(img)
    h, w = arr.shape[:2]
    if center is None:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    m = _affine_matrix(-angle, (0, 0), 1.0, (0.0, 0.0), center)  # CCW, see affine
    out_h, out_w = h, w
    if expand:
        corners = np.array([[0, 0, 1], [w - 1, 0, 1], [0, h - 1, 1],
                            [w - 1, h - 1, 1]], np.float64).T
        mapped = m @ corners
        xs, ys = mapped[0], mapped[1]
        out_w = int(math.ceil(xs.max() - xs.min() + 1))
        out_h = int(math.ceil(ys.max() - ys.min() + 1))
        shift = np.eye(3)
        shift[0, 2] = -xs.min()
        shift[1, 2] = -ys.min()
        m = shift @ m
    return _inverse_sample(arr, np.linalg.inv(m), out_h, out_w,
                           interpolation, fill)


def _homography(src_pts, dst_pts):
    """3x3 perspective matrix mapping src -> dst (4 point pairs)."""
    A, b = [], []
    for (x, y), (u, v) in zip(src_pts, dst_pts):
        A.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
        A.append([0, 0, 0, x, y, 1, -v * x, -v * y])
        b += [u, v]
    coef = np.linalg.solve(np.asarray(A, np.float64),
                           np.asarray(b, np.float64))
    return np.append(coef, 1.0).reshape(3, 3)


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    """Perspective transform taking ``startpoints`` to ``endpoints``
    (reference functional.py perspective)."""
    arr = _as_hwc(img)
    h, w = arr.shape[:2]
    m = _homography(startpoints, endpoints)
    return _inverse_sample(arr, np.linalg.inv(m), h, w, interpolation, fill)


def erase(img, i, j, h, w, v, inplace=False):
    """Erase the region at (i, j, h, w) with value ``v`` (reference
    functional.py erase). Accepts HWC arrays or CHW tensors-as-arrays
    (channel-count heuristic matches ToTensor's output)."""
    from ...tensor.tensor import Tensor

    is_tensor = isinstance(img, Tensor)
    arr = np.array(img, copy=not inplace)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[2] not in (1, 3)
    if chw:
        arr[:, i:i + h, j:j + w] = v
    else:
        arr[i:i + h, j:j + w] = v
    if is_tensor:
        return Tensor(arr)
    return arr


# --- class transforms -------------------------------------------------------
class RandomResizedCrop(BaseTransform):
    """Random area/aspect crop then resize (reference transforms.py
    RandomResizedCrop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4, 4.0 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def __call__(self, img):
        arr = _as_hwc(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            log_ratio = (math.log(self.ratio[0]), math.log(self.ratio[1]))
            ar = math.exp(random.uniform(*log_ratio))
            cw = int(round(math.sqrt(target * ar)))
            ch = int(round(math.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                return resize(crop(arr, top, left, ch, cw), self.size,
                              self.interpolation)
        return resize(center_crop(arr, min(h, w)), self.size,
                      self.interpolation)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return img
        return adjust_brightness(img, random.uniform(max(0, 1 - self.value),
                                                     1 + self.value))


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        if value < 0:
            raise ValueError("contrast value should be non-negative")
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return img
        return adjust_contrast(img, random.uniform(max(0, 1 - self.value),
                                                   1 + self.value))


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return img
        return adjust_saturation(img, random.uniform(max(0, 1 - self.value),
                                                     1 + self.value))


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value should be in [0, 0.5]")
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """Randomly-ordered brightness/contrast/saturation/hue jitter
    (reference transforms.py ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.ts = [BrightnessTransform(brightness),
                   ContrastTransform(contrast),
                   SaturationTransform(saturation), HueTransform(hue)]

    def __call__(self, img):
        order = list(range(4))
        random.shuffle(order)
        for i in order:
            img = self.ts[i](img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def __call__(self, img):
        angle = random.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, self.expand,
                      self.center, self.fill)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def __call__(self, img):
        arr = _as_hwc(img)
        h, w = arr.shape[:2]
        angle = random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = random.uniform(-self.translate[0], self.translate[0]) * w
            ty = random.uniform(-self.translate[1], self.translate[1]) * h
        scale = random.uniform(*self.scale) if self.scale else 1.0
        if self.shear is None:
            shear = (0.0, 0.0)
        elif isinstance(self.shear, numbers.Number):
            shear = (random.uniform(-self.shear, self.shear), 0.0)
        else:
            lo, hi = self.shear[0], self.shear[1]
            shear = (random.uniform(lo, hi), 0.0)
            if len(self.shear) == 4:
                shear = (shear[0], random.uniform(self.shear[2], self.shear[3]))
        return affine(arr, angle, (tx, ty), scale, shear,
                      self.interpolation, self.fill, self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def __call__(self, img):
        if random.random() >= self.prob:
            return img
        arr = _as_hwc(img)
        h, w = arr.shape[:2]
        d = self.distortion_scale
        half_h, half_w = int(h * d / 2), int(w * d / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(random.randint(0, half_w), random.randint(0, half_h)),
               (w - 1 - random.randint(0, half_w), random.randint(0, half_h)),
               (w - 1 - random.randint(0, half_w),
                h - 1 - random.randint(0, half_h)),
               (random.randint(0, half_w), h - 1 - random.randint(0, half_h))]
        return perspective(arr, start, end, self.interpolation, self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def __call__(self, img):
        return to_grayscale(img, self.num_output_channels)


class RandomErasing(BaseTransform):
    """Random cutout over CHW tensors or HWC arrays (reference
    transforms.py RandomErasing)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def __call__(self, img):
        if random.random() >= self.prob:
            return img
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) \
            and arr.shape[2] not in (1, 3)
        h, w = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = math.exp(random.uniform(math.log(self.ratio[0]),
                                         math.log(self.ratio[1])))
            eh = int(round(math.sqrt(target * ar)))
            ew = int(round(math.sqrt(target / ar)))
            if eh < h and ew < w and eh > 0 and ew > 0:
                i = random.randint(0, h - eh)
                j = random.randint(0, w - ew)
                return erase(img, i, j, eh, ew, self.value, self.inplace)
        return img
