"""Transform implementations over numpy HWC arrays (the dataset-native
format), mirroring the reference's functional semantics
(python/paddle/vision/transforms/transforms.py / functional.py)."""
from __future__ import annotations

import numbers
import random

import numpy as np


def _as_hwc(img) -> np.ndarray:
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def resize(img, size, interpolation="bilinear"):
    """Nearest/bilinear resize of an HWC numpy image."""
    arr = _as_hwc(img)
    if isinstance(size, int):
        h, w = arr.shape[:2]
        if h <= w:
            oh, ow = size, max(1, int(size * w / h))
        else:
            oh, ow = max(1, int(size * h / w)), size
    else:
        oh, ow = size
    h, w = arr.shape[:2]
    if (h, w) == (oh, ow):
        return arr
    ys = np.linspace(0, h - 1, oh)
    xs = np.linspace(0, w - 1, ow)
    if interpolation == "nearest":
        return arr[np.round(ys).astype(int)[:, None], np.round(xs).astype(int)[None, :]]
    y0 = np.floor(ys).astype(int); y1 = np.minimum(y0 + 1, h - 1)
    x0 = np.floor(xs).astype(int); x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    a = arr.astype(np.float32)
    top = a[y0[:, None], x0[None, :]] * (1 - wx) + a[y0[:, None], x1[None, :]] * wx
    bot = a[y1[:, None], x0[None, :]] * (1 - wx) + a[y1[:, None], x1[None, :]] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(arr.dtype) if np.issubdtype(arr.dtype, np.integer) else out


def to_tensor(img, data_format="CHW"):
    arr = _as_hwc(img)
    # rescale only integer (pixel-valued) input, never float (paddle parity)
    rescale = np.issubdtype(arr.dtype, np.integer) or arr.dtype == np.bool_
    arr = arr.astype(np.float32)
    if rescale:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return arr


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        return (arr - mean[:, None, None]) / std[:, None, None]
    return (arr - mean) / std


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):  # pragma: no cover - abstract
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, numbers.Number) else size

    def _apply_image(self, img):
        arr = _as_hwc(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max(0, (h - th) // 2)
        j = max(0, (w - tw) // 2)
        return arr[i : i + th, j : j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.padding = padding
        self.pad_if_needed = pad_if_needed

    def _apply_image(self, img):
        arr = _as_hwc(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else [self.padding] * 4
            arr = np.pad(arr, ((p[1], p[3]), (p[0], p[2]), (0, 0)))
        h, w = arr.shape[:2]
        th, tw = self.size
        if h < th or w < tw:
            if not self.pad_if_needed:
                raise ValueError(
                    f"image size ({h}, {w}) smaller than crop size ({th}, {tw}); "
                    "pass pad_if_needed=True")
            ph, pw = max(0, th - h), max(0, tw - w)
            arr = np.pad(arr, ((0, ph), (0, pw), (0, 0)))
            h, w = arr.shape[:2]
        i = random.randint(0, max(0, h - th))
        j = random.randint(0, max(0, w - tw))
        return arr[i : i + th, j : j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _as_hwc(img)[:, ::-1]
        return _as_hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _as_hwc(img)[::-1]
        return _as_hwc(img)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding if isinstance(padding, (list, tuple)) else [padding] * 4
        self.fill = fill

    def _apply_image(self, img):
        p = self.padding
        return np.pad(
            _as_hwc(img), ((p[1], p[3]), (p[0], p[2]), (0, 0)),
            constant_values=self.fill,
        )


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return _as_hwc(img).transpose(self.order)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std, self.data_format = mean, std, data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)
