"""Vision transforms (parity: python/paddle/vision/transforms/ — the
numpy/CHW subset used by the in-repo tests; PIL-specific paths are served by
the same numpy implementations)."""
from .transforms import (
    BaseTransform,
    CenterCrop,
    Compose,
    Normalize,
    Pad,
    RandomCrop,
    RandomHorizontalFlip,
    RandomVerticalFlip,
    Resize,
    ToTensor,
    Transpose,
    normalize,
    resize,
    to_tensor,
)

__all__ = [
    "BaseTransform", "CenterCrop", "Compose", "Normalize", "Pad",
    "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip", "Resize",
    "ToTensor", "Transpose", "normalize", "resize", "to_tensor",
]
