"""AlexNet, SqueezeNet, DenseNet, ShuffleNetV2, GoogLeNet (parity:
python/paddle/vision/models/{alexnet,squeezenet,densenet,shufflenetv2,
googlenet}.py)."""
from __future__ import annotations

from ... import nn
from ...tensor.manipulation import concat, reshape, split, transpose


class AlexNet(nn.Layer):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2))
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        self.classifier = nn.Sequential(
            nn.Dropout(), nn.Linear(256 * 36, 4096), nn.ReLU(),
            nn.Dropout(), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes)) if num_classes > 0 else None

    def forward(self, x):
        x = self.avgpool(self.features(x))
        if self.classifier is not None:
            x = self.classifier(x.flatten(1))
        return x


def alexnet(pretrained=False, **kwargs):
    return AlexNet(**kwargs)


class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_c, squeeze, 1)
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)

    def forward(self, x):
        x = nn.functional.relu(self.squeeze(x))
        return concat([nn.functional.relu(self.expand1(x)),
                       nn.functional.relu(self.expand3(x))], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2D(1)) if num_classes > 0 else None

    def forward(self, x):
        x = self.features(x)
        if self.classifier is not None:
            x = self.classifier(x).flatten(1)
        return x


def squeezenet1_0(pretrained=False, **kwargs):
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet("1.1", **kwargs)


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth_rate, bn_size):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(in_c)
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)

    def forward(self, x):
        y = self.conv1(nn.functional.relu(self.norm1(x)))
        y = self.conv2(nn.functional.relu(self.norm2(y)))
        return concat([x, y], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.norm = nn.BatchNorm2D(in_c)
        self.conv = nn.Conv2D(in_c, out_c, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, 2)

    def forward(self, x):
        return self.pool(self.conv(nn.functional.relu(self.norm(x))))


_DENSE_CFG = {
    121: (6, 12, 24, 16), 161: (6, 12, 36, 24),
    169: (6, 12, 32, 32), 201: (6, 12, 48, 32), 264: (6, 12, 64, 48),
}


class DenseNet(nn.Layer):
    def __init__(self, layers=121, growth_rate=32, bn_size=4,
                 num_classes=1000, with_pool=True):
        super().__init__()
        if layers == 161:
            growth_rate, init_c = 48, 96
        else:
            init_c = 64
        block_cfg = _DENSE_CFG[layers]
        feats = [nn.Conv2D(3, init_c, 7, stride=2, padding=3,
                           bias_attr=False),
                 nn.BatchNorm2D(init_c), nn.ReLU(),
                 nn.MaxPool2D(3, 2, padding=1)]
        c = init_c
        for i, n in enumerate(block_cfg):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth_rate, bn_size))
                c += growth_rate
            if i != len(block_cfg) - 1:
                feats.append(_Transition(c, c // 2))
                c //= 2
        feats += [nn.BatchNorm2D(c), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Linear(c, num_classes) if num_classes > 0 else None

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.classifier is not None:
            x = self.classifier(x.flatten(1))
        return x


def densenet121(pretrained=False, **kwargs):
    return DenseNet(121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return DenseNet(161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return DenseNet(169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return DenseNet(201, **kwargs)


def _channel_shuffle(x, groups):
    B, C, H, W = x.shape
    x = reshape(x, [B, groups, C // groups, H, W])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [B, C, H, W])


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=stride, padding=1,
                          groups=in_c, bias_attr=False),
                nn.BatchNorm2D(in_c),
                nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), nn.ReLU())
            b2_in = in_c
        else:
            self.branch1 = None
            b2_in = in_c // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), nn.ReLU(),
            nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                      groups=branch_c, bias_attr=False),
            nn.BatchNorm2D(branch_c),
            nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), nn.ReLU())

    def forward(self, x):
        if self.stride > 1:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        else:
            x1, x2 = split(x, 2, axis=1)
            out = concat([x1, self.branch2(x2)], axis=1)
        return _channel_shuffle(out, 2)


_SHUFFLE_CFG = {
    0.5: (48, 96, 192, 1024), 1.0: (116, 232, 464, 1024),
    1.5: (176, 352, 704, 1024), 2.0: (244, 488, 976, 2048),
}


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        c1, c2, c3, last_c = _SHUFFLE_CFG[scale]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, 24, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(24), nn.ReLU())
        self.maxpool = nn.MaxPool2D(3, 2, padding=1)
        stages = []
        in_c = 24
        for out_c, repeats in zip((c1, c2, c3), (4, 8, 4)):
            units = [_ShuffleUnit(in_c, out_c, 2)]
            units += [_ShuffleUnit(out_c, out_c, 1)
                      for _ in range(repeats - 1)]
            stages.append(nn.Sequential(*units))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv5 = nn.Sequential(
            nn.Conv2D(in_c, last_c, 1, bias_attr=False),
            nn.BatchNorm2D(last_c), nn.ReLU())
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc = nn.Linear(last_c, num_classes) if num_classes > 0 else None

    def forward(self, x):
        x = self.conv5(self.stages(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.fc is not None:
            x = self.fc(x.flatten(1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.5, **kwargs)  # smallest published ladder step


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.5, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=2.0, **kwargs)
