"""AlexNet, SqueezeNet, DenseNet, ShuffleNetV2, GoogLeNet (parity:
python/paddle/vision/models/{alexnet,squeezenet,densenet,shufflenetv2,
googlenet}.py)."""
from __future__ import annotations

from ... import nn
from ...tensor.manipulation import concat, reshape, split, transpose

_ACTS = {"relu": nn.ReLU, "swish": nn.Swish}


def _no_pretrained(pretrained):
    if pretrained:
        raise ValueError("pretrained weights are not bundled in this build")


class AlexNet(nn.Layer):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2))
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        self.classifier = nn.Sequential(
            nn.Dropout(), nn.Linear(256 * 36, 4096), nn.ReLU(),
            nn.Dropout(), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes)) if num_classes > 0 else None

    def forward(self, x):
        x = self.avgpool(self.features(x))
        if self.classifier is not None:
            x = self.classifier(x.flatten(1))
        return x


def alexnet(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return AlexNet(**kwargs)


class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_c, squeeze, 1)
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)

    def forward(self, x):
        x = nn.functional.relu(self.squeeze(x))
        return concat([nn.functional.relu(self.expand1(x)),
                       nn.functional.relu(self.expand3(x))], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2D(1)) if num_classes > 0 else None

    def forward(self, x):
        x = self.features(x)
        if self.classifier is not None:
            x = self.classifier(x).flatten(1)
        return x


def squeezenet1_0(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return SqueezeNet("1.1", **kwargs)


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth_rate, bn_size):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(in_c)
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)

    def forward(self, x):
        y = self.conv1(nn.functional.relu(self.norm1(x)))
        y = self.conv2(nn.functional.relu(self.norm2(y)))
        return concat([x, y], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.norm = nn.BatchNorm2D(in_c)
        self.conv = nn.Conv2D(in_c, out_c, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, 2)

    def forward(self, x):
        return self.pool(self.conv(nn.functional.relu(self.norm(x))))


_DENSE_CFG = {
    121: (6, 12, 24, 16), 161: (6, 12, 36, 24),
    169: (6, 12, 32, 32), 201: (6, 12, 48, 32), 264: (6, 12, 64, 48),
}


class DenseNet(nn.Layer):
    def __init__(self, layers=121, growth_rate=32, bn_size=4,
                 num_classes=1000, with_pool=True):
        super().__init__()
        if layers == 161:
            growth_rate, init_c = 48, 96
        else:
            init_c = 64
        block_cfg = _DENSE_CFG[layers]
        feats = [nn.Conv2D(3, init_c, 7, stride=2, padding=3,
                           bias_attr=False),
                 nn.BatchNorm2D(init_c), nn.ReLU(),
                 nn.MaxPool2D(3, 2, padding=1)]
        c = init_c
        for i, n in enumerate(block_cfg):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth_rate, bn_size))
                c += growth_rate
            if i != len(block_cfg) - 1:
                feats.append(_Transition(c, c // 2))
                c //= 2
        feats += [nn.BatchNorm2D(c), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Linear(c, num_classes) if num_classes > 0 else None

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.classifier is not None:
            x = self.classifier(x.flatten(1))
        return x


def densenet121(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return DenseNet(121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return DenseNet(161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return DenseNet(169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return DenseNet(201, **kwargs)


def _channel_shuffle(x, groups):
    B, C, H, W = x.shape
    x = reshape(x, [B, groups, C // groups, H, W])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [B, C, H, W])


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        self.stride = stride
        Act = _ACTS[act]
        branch_c = out_c // 2
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=stride, padding=1,
                          groups=in_c, bias_attr=False),
                nn.BatchNorm2D(in_c),
                nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), Act())
            b2_in = in_c
        else:
            self.branch1 = None
            b2_in = in_c // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), Act(),
            nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                      groups=branch_c, bias_attr=False),
            nn.BatchNorm2D(branch_c),
            nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), Act())

    def forward(self, x):
        if self.stride > 1:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        else:
            x1, x2 = split(x, 2, axis=1)
            out = concat([x1, self.branch2(x2)], axis=1)
        return _channel_shuffle(out, 2)


_SHUFFLE_CFG = {
    0.25: (24, 48, 96, 512), 0.33: (32, 64, 128, 512),
    0.5: (48, 96, 192, 1024), 1.0: (116, 232, 464, 1024),
    1.5: (176, 352, 704, 1024), 2.0: (244, 488, 976, 2048),
}


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True,
                 act="relu"):
        super().__init__()
        c1, c2, c3, last_c = _SHUFFLE_CFG[scale]
        if act not in _ACTS:
            raise ValueError(f"ShuffleNetV2 act must be one of {sorted(_ACTS)},"
                             f" got {act!r}")
        Act = _ACTS[act]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, 24, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(24), Act())
        self.maxpool = nn.MaxPool2D(3, 2, padding=1)
        stages = []
        in_c = 24
        for out_c, repeats in zip((c1, c2, c3), (4, 8, 4)):
            units = [_ShuffleUnit(in_c, out_c, 2, act)]
            units += [_ShuffleUnit(out_c, out_c, 1, act)
                      for _ in range(repeats - 1)]
            stages.append(nn.Sequential(*units))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv5 = nn.Sequential(
            nn.Conv2D(in_c, last_c, 1, bias_attr=False),
            nn.BatchNorm2D(last_c), Act())
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc = nn.Linear(last_c, num_classes) if num_classes > 0 else None

    def forward(self, x):
        x = self.conv5(self.stages(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.fc is not None:
            x = self.fc(x.flatten(1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return ShuffleNetV2(scale=0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return ShuffleNetV2(scale=0.33, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    """x1.0 ladder with swish activations (reference shufflenetv2.py
    shufflenet_v2_swish)."""
    _no_pretrained(pretrained)
    return ShuffleNetV2(scale=1.0, act="swish", **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return ShuffleNetV2(scale=0.5, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return ShuffleNetV2(scale=1.0, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return ShuffleNetV2(scale=1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return ShuffleNetV2(scale=2.0, **kwargs)


def densenet264(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return DenseNet(264, **kwargs)


# --- GoogLeNet (Inception v1) ---------------------------------------------
def _cconv(i, o, k, s=1):
    return nn.Sequential(
        nn.Conv2D(i, o, k, stride=s, padding=k // 2), nn.ReLU())


class _Incept(nn.Layer):
    """One inception-v1 cell: 1x1 / 1x1->3x3 / 1x1->5x5 / pool->1x1 branches
    concatenated (reference googlenet.py Inception)."""

    def __init__(self, in_c, f1, f3r, f3, f5r, f5, proj):
        super().__init__()
        self.b1 = _cconv(in_c, f1, 1)
        self.b3 = nn.Sequential(_cconv(in_c, f3r, 1), _cconv(f3r, f3, 3))
        self.b5 = nn.Sequential(_cconv(in_c, f5r, 1), _cconv(f5r, f5, 5))
        self.bp = nn.Sequential(nn.MaxPool2D(3, 1, padding=1),
                                _cconv(in_c, proj, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b3(x), self.b5(x), self.bp(x)],
                      axis=1)


class GoogLeNet(nn.Layer):
    """GoogLeNet / Inception v1 (reference vision/models/googlenet.py:107).

    forward returns ``(out, out1, out2)``: the main head plus the two
    auxiliary heads over the 4a and 4d cells, matching the reference's
    training contract.
    """

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _cconv(3, 64, 7, 2), nn.MaxPool2D(3, 2, padding=1),
            _cconv(64, 64, 1), _cconv(64, 192, 3),
            nn.MaxPool2D(3, 2, padding=1))
        self.i3a = _Incept(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Incept(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, 2, padding=1)
        self.i4a = _Incept(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Incept(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Incept(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Incept(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Incept(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, 2, padding=1)
        self.i5a = _Incept(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Incept(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.gap = nn.AdaptiveAvgPool2D(1)
            self.aux_pool = nn.AdaptiveAvgPool2D(4)
        if num_classes > 0:
            self.drop = nn.Dropout(0.4)
            self.head = nn.Linear(1024, num_classes)
            # aux heads (4a: 512 ch, 4d: 528 ch)
            self.aux1_conv = nn.Sequential(nn.Conv2D(512, 128, 1), nn.ReLU())
            self.aux1_fc = nn.Sequential(
                nn.Linear(128 * 16, 1024), nn.ReLU(), nn.Dropout(0.7),
                nn.Linear(1024, num_classes))
            self.aux2_conv = nn.Sequential(nn.Conv2D(528, 128, 1), nn.ReLU())
            self.aux2_fc = nn.Sequential(
                nn.Linear(128 * 16, 1024), nn.ReLU(), nn.Dropout(0.7),
                nn.Linear(1024, num_classes))

    def forward(self, x):
        x = self.pool3(self.i3b(self.i3a(self.stem(x))))
        a4a = self.i4a(x)
        a4d = self.i4d(self.i4c(self.i4b(a4a)))
        x = self.pool4(self.i4e(a4d))
        out = self.i5b(self.i5a(x))
        out1, out2 = a4a, a4d
        if self.with_pool:
            out = self.gap(out)
            out1 = self.aux_pool(out1)
            out2 = self.aux_pool(out2)
        if self.num_classes > 0:
            out = self.head(self.drop(out).flatten(1))
            out1 = self.aux1_fc(self.aux1_conv(out1).flatten(1))
            out2 = self.aux2_fc(self.aux2_conv(out2).flatten(1))
        return out, out1, out2


def googlenet(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return GoogLeNet(**kwargs)


# --- Inception v3 ----------------------------------------------------------
def _cbn(i, o, k, s=1, p=0):
    return nn.Sequential(
        nn.Conv2D(i, o, k, stride=s, padding=p, bias_attr=False),
        nn.BatchNorm2D(o), nn.ReLU())


class _InceptA(nn.Layer):
    def __init__(self, in_c, pool_c):
        super().__init__()
        self.b1 = _cbn(in_c, 64, 1)
        self.b5 = nn.Sequential(_cbn(in_c, 48, 1), _cbn(48, 64, 5, p=2))
        self.b3 = nn.Sequential(_cbn(in_c, 64, 1), _cbn(64, 96, 3, p=1),
                                _cbn(96, 96, 3, p=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _cbn(in_c, pool_c, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)], 1)


class _InceptB(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3 = _cbn(in_c, 384, 3, s=2)
        self.b3d = nn.Sequential(_cbn(in_c, 64, 1), _cbn(64, 96, 3, p=1),
                                 _cbn(96, 96, 3, s=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return concat([self.b3(x), self.b3d(x), self.pool(x)], 1)


class _InceptC(nn.Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _cbn(in_c, 192, 1)
        self.b7 = nn.Sequential(
            _cbn(in_c, c7, 1), _cbn(c7, c7, (1, 7), p=(0, 3)),
            _cbn(c7, 192, (7, 1), p=(3, 0)))
        self.b7d = nn.Sequential(
            _cbn(in_c, c7, 1), _cbn(c7, c7, (7, 1), p=(3, 0)),
            _cbn(c7, c7, (1, 7), p=(0, 3)), _cbn(c7, c7, (7, 1), p=(3, 0)),
            _cbn(c7, 192, (1, 7), p=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _cbn(in_c, 192, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)], 1)


class _InceptD(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3 = nn.Sequential(_cbn(in_c, 192, 1), _cbn(192, 320, 3, s=2))
        self.b7 = nn.Sequential(
            _cbn(in_c, 192, 1), _cbn(192, 192, (1, 7), p=(0, 3)),
            _cbn(192, 192, (7, 1), p=(3, 0)), _cbn(192, 192, 3, s=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return concat([self.b3(x), self.b7(x), self.pool(x)], 1)


class _InceptE(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _cbn(in_c, 320, 1)
        self.b3_stem = _cbn(in_c, 384, 1)
        self.b3_a = _cbn(384, 384, (1, 3), p=(0, 1))
        self.b3_b = _cbn(384, 384, (3, 1), p=(1, 0))
        self.b3d_stem = nn.Sequential(_cbn(in_c, 448, 1),
                                      _cbn(448, 384, 3, p=1))
        self.b3d_a = _cbn(384, 384, (1, 3), p=(0, 1))
        self.b3d_b = _cbn(384, 384, (3, 1), p=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _cbn(in_c, 192, 1))

    def forward(self, x):
        s3 = self.b3_stem(x)
        s3d = self.b3d_stem(x)
        return concat([
            self.b1(x),
            concat([self.b3_a(s3), self.b3_b(s3)], 1),
            concat([self.b3d_a(s3d), self.b3d_b(s3d)], 1),
            self.bp(x)], 1)


class InceptionV3(nn.Layer):
    """Inception v3 (reference vision/models/inceptionv3.py InceptionV3):
    5x A/B/C/D/E inception stages over a 299x299 stem."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _cbn(3, 32, 3, s=2), _cbn(32, 32, 3), _cbn(32, 64, 3, p=1),
            nn.MaxPool2D(3, 2), _cbn(64, 80, 1), _cbn(80, 192, 3),
            nn.MaxPool2D(3, 2))
        self.blocks = nn.Sequential(
            _InceptA(192, 32), _InceptA(256, 64), _InceptA(288, 64),
            _InceptB(288),
            _InceptC(768, 128), _InceptC(768, 160), _InceptC(768, 160),
            _InceptC(768, 192),
            _InceptD(768),
            _InceptE(1280), _InceptE(2048))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)
        else:
            self.fc = None

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.fc is not None:
            x = self.fc(self.dropout(x).flatten(1))
        return x


def inception_v3(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return InceptionV3(**kwargs)
