"""Vision model zoo (parity: python/paddle/vision/models/__init__.py)."""
from .lenet import LeNet
from .misc import (
    AlexNet,
    DenseNet,
    ShuffleNetV2,
    SqueezeNet,
    alexnet,
    densenet121,
    densenet161,
    densenet169,
    densenet201,
    shufflenet_v2_x0_5,
    shufflenet_v2_x1_0,
    shufflenet_v2_x1_5,
    shufflenet_v2_x2_0,
    squeezenet1_0,
    squeezenet1_1,
)
from .mobilenet import (
    MobileNetV1,
    MobileNetV2,
    MobileNetV3,
    mobilenet_v1,
    mobilenet_v2,
    mobilenet_v3_large,
    mobilenet_v3_small,
)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19
from .resnet import (
    BasicBlock,
    BottleneckBlock,
    ResNet,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
    wide_resnet50_2,
    wide_resnet101_2,
)

__all__ = [
    "LeNet",
    "AlexNet", "alexnet",
    "VGG", "vgg11", "vgg13", "vgg16", "vgg19",
    "MobileNetV1", "MobileNetV2", "MobileNetV3",
    "mobilenet_v1", "mobilenet_v2", "mobilenet_v3_large",
    "mobilenet_v3_small",
    "SqueezeNet", "squeezenet1_0", "squeezenet1_1",
    "DenseNet", "densenet121", "densenet161", "densenet169", "densenet201",
    "ShuffleNetV2", "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
    "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
    "BasicBlock",
    "BottleneckBlock",
    "ResNet",
    "resnet18",
    "resnet34",
    "resnet50",
    "resnet101",
    "resnet152",
    "wide_resnet50_2",
    "wide_resnet101_2",
]
