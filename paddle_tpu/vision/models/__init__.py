"""Vision model zoo (parity: python/paddle/vision/models/__init__.py)."""
from .lenet import LeNet
from .resnet import (
    BasicBlock,
    BottleneckBlock,
    ResNet,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
    wide_resnet50_2,
    wide_resnet101_2,
)

__all__ = [
    "LeNet",
    "BasicBlock",
    "BottleneckBlock",
    "ResNet",
    "resnet18",
    "resnet34",
    "resnet50",
    "resnet101",
    "resnet152",
    "wide_resnet50_2",
    "wide_resnet101_2",
]
