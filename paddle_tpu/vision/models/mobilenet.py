"""MobileNet v1/v2/v3 (parity: python/paddle/vision/models/
mobilenetv1.py, mobilenetv2.py, mobilenetv3.py)."""
from __future__ import annotations

from ... import nn


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNLayer(nn.Layer):
    def __init__(self, in_c, out_c, k, stride=1, padding=0, groups=1,
                 act="relu"):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                              groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = {"relu": nn.ReLU(), "relu6": nn.ReLU6(),
                    "hardswish": nn.Hardswish(), None: None}[act]

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act is not None else x


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        s = lambda c: int(c * scale)
        cfg = [  # (in, out, stride) depthwise-separable stacks
            (32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
            (256, 256, 1), (256, 512, 2), *[(512, 512, 1)] * 5,
            (512, 1024, 2), (1024, 1024, 1),
        ]
        layers = [ConvBNLayer(3, s(32), 3, stride=2, padding=1)]
        for in_c, out_c, stride in cfg:
            layers.append(ConvBNLayer(s(in_c), s(in_c), 3, stride=stride,
                                      padding=1, groups=s(in_c)))  # dw
            layers.append(ConvBNLayer(s(in_c), s(out_c), 1))  # pw
        self.features = nn.Sequential(*layers)
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc = nn.Linear(s(1024), num_classes) if num_classes > 0 else None

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.fc is not None:
            x = self.fc(x.flatten(1))
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        hidden = int(round(in_c * expand_ratio))
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNLayer(in_c, hidden, 1, act="relu6"))
        layers += [
            ConvBNLayer(hidden, hidden, 3, stride=stride, padding=1,
                        groups=hidden, act="relu6"),
            ConvBNLayer(hidden, out_c, 1, act=None),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        return x + self.conv(x) if self.use_res else self.conv(x)


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        in_c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        layers = [ConvBNLayer(3, in_c, 3, stride=2, padding=1, act="relu6")]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                layers.append(InvertedResidual(in_c, out_c,
                                               s if i == 0 else 1, t))
                in_c = out_c
        layers.append(ConvBNLayer(in_c, last_c, 1, act="relu6"))
        self.features = nn.Sequential(*layers)
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        self.classifier = (nn.Sequential(nn.Dropout(0.2),
                                         nn.Linear(last_c, num_classes))
                           if num_classes > 0 else None)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.classifier is not None:
            x = self.classifier(x.flatten(1))
        return x


class SqueezeExcitation(nn.Layer):
    def __init__(self, c, squeeze_c):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, squeeze_c, 1)
        self.fc2 = nn.Conv2D(squeeze_c, c, 1)

    def forward(self, x):
        s = self.pool(x)
        s = nn.functional.relu(self.fc1(s))
        s = nn.functional.hardsigmoid(self.fc2(s))
        return x * s


class _V3Block(nn.Layer):
    def __init__(self, in_c, exp_c, out_c, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if exp_c != in_c:
            layers.append(ConvBNLayer(in_c, exp_c, 1, act=act))
        layers.append(ConvBNLayer(exp_c, exp_c, k, stride=stride,
                                  padding=k // 2, groups=exp_c, act=act))
        if use_se:
            layers.append(SqueezeExcitation(exp_c,
                                            _make_divisible(exp_c // 4)))
        layers.append(ConvBNLayer(exp_c, out_c, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        return x + self.block(x) if self.use_res else self.block(x)


_V3_LARGE = [  # k, exp, out, se, act, stride
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_V3_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_c, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        in_c = _make_divisible(16 * scale)
        layers = [ConvBNLayer(3, in_c, 3, stride=2, padding=1,
                              act="hardswish")]
        for k, exp, out, se, act, s in cfg:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            layers.append(_V3Block(in_c, exp_c, out_c, k, s, se, act))
            in_c = out_c
        final_exp = _make_divisible(cfg[-1][1] * scale)
        layers.append(ConvBNLayer(in_c, final_exp, 1, act="hardswish"))
        self.features = nn.Sequential(*layers)
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(final_exp, last_c), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_c, num_classes))
        else:
            self.classifier = None

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.classifier is not None:
            x = self.classifier(x.flatten(1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3(_V3_LARGE, 1280, scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3(_V3_SMALL, 1024, scale=scale, **kwargs)


class MobileNetV3Small(MobileNetV3):
    """MobileNetV3-Small as a class export (reference mobilenetv3.py
    MobileNetV3Small; the functional spelling is mobilenet_v3_small)."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 1024, scale=scale,
                         num_classes=num_classes, with_pool=with_pool)


class MobileNetV3Large(MobileNetV3):
    """MobileNetV3-Large as a class export (reference mobilenetv3.py
    MobileNetV3Large)."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 1280, scale=scale,
                         num_classes=num_classes, with_pool=with_pool)
