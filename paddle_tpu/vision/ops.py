"""paddle.vision.ops parity: detection-model operators.

Reference: python/paddle/vision/ops.py (nms, roi_align, roi_pool, box
utilities) over phi detection kernels. TPU stance: NMS is an
O(N^2)-mask + sequential-suppression lax.while; RoI ops are bilinear
gathers — all static-shaped, jittable.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..autograd.engine import apply_op
from ..tensor.tensor import Tensor


def box_iou(boxes1, boxes2):
    """Pairwise IoU for [N,4] / [M,4] xyxy boxes -> [N, M]."""

    def fn(b1, b2):
        area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
        area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
        lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
        rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter + 1e-10)

    return apply_op("box_iou", fn, boxes1, boxes2)


def nms(boxes, iou_threshold: float = 0.3, scores=None,
        category_idxs=None, categories=None, top_k: int | None = None):
    """Greedy NMS (reference: vision/ops.py nms). Returns kept indices
    sorted by score. With category_idxs, suppression is per-category
    (batched NMS trick: offset boxes per class so classes never overlap).
    """
    import numpy as np

    def fn(b, s, cat):
        n = b.shape[0]
        if s is None:
            order = jnp.arange(n)
        else:
            order = jnp.argsort(-s)
        bb = b[order]
        if cat is not None:
            # shift each category into its own coordinate island
            span = jnp.max(bb) - jnp.min(bb) + 1.0
            offs = cat[order].astype(bb.dtype)[:, None] * span
            bb = bb + offs
        area = (bb[:, 2] - bb[:, 0]) * (bb[:, 3] - bb[:, 1])
        lt = jnp.maximum(bb[:, None, :2], bb[None, :, :2])
        rb = jnp.minimum(bb[:, None, 2:], bb[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        iou = inter / (area[:, None] + area[None, :] - inter + 1e-10)

        def body(i, keep):
            # suppress i if any still-kept higher-score box overlaps it
            sup = jnp.any((iou[i, :] > iou_threshold)
                          & keep & (jnp.arange(n) < i))
            return keep.at[i].set(~sup)

        keep = jax.lax.fori_loop(0, n, body, jnp.ones(n, bool))
        return order, keep

    b = boxes._data if isinstance(boxes, Tensor) else jnp.asarray(boxes)
    s = scores._data if isinstance(scores, Tensor) else scores
    c = (category_idxs._data if isinstance(category_idxs, Tensor)
         else category_idxs)
    order, keep = fn(b, None if s is None else jnp.asarray(s),
                     None if c is None else jnp.asarray(c))
    # keep refers to sorted positions; map back to original indices
    kept = np.asarray(order)[np.asarray(keep)]
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(jnp.asarray(kept, jnp.int64))


def _bilinear_sample(feat, y, x):
    """feat [C, H, W]; y/x arbitrary same-shaped grids -> [C, *grid]."""
    H, W = feat.shape[-2:]
    y0 = jnp.clip(jnp.floor(y), 0, H - 1)
    x0 = jnp.clip(jnp.floor(x), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    wy = jnp.clip(y - y0, 0, 1)
    wx = jnp.clip(x - x0, 0, 1)
    y0i, y1i, x0i, x1i = (a.astype(jnp.int32) for a in (y0, y1, x0, x1))
    v00 = feat[:, y0i, x0i]
    v01 = feat[:, y0i, x1i]
    v10 = feat[:, y1i, x0i]
    v11 = feat[:, y1i, x1i]
    return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
            + v10 * wy * (1 - wx) + v11 * wy * wx)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
              sampling_ratio: int = -1, aligned: bool = True):
    """RoIAlign (reference: vision/ops.py roi_align). x [N,C,H,W]; boxes
    [R,4] xyxy in input coords; boxes_num [N] rois per image. Returns
    [R, C, out_h, out_w]."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    ratio = 2 if sampling_ratio <= 0 else sampling_ratio

    def fn(feat, rois, rois_num):
        # map each roi to its batch index from boxes_num
        R = rois.shape[0]
        starts = jnp.cumsum(rois_num) - rois_num
        batch_idx = jnp.sum(
            (jnp.arange(R)[:, None] >= starts[None, :]).astype(jnp.int32),
            axis=1) - 1

        offset = 0.5 if aligned else 0.0

        def one(roi, bi):
            x1, y1, x2, y2 = (roi * spatial_scale) - offset
            rw = jnp.maximum(x2 - x1, 1e-3)
            rh = jnp.maximum(y2 - y1, 1e-3)
            bin_h, bin_w = rh / oh, rw / ow
            # sampling grid: ratio x ratio points per bin, averaged
            gy = (y1 + (jnp.arange(oh * ratio) + 0.5) * bin_h / ratio)
            gx = (x1 + (jnp.arange(ow * ratio) + 0.5) * bin_w / ratio)
            yy, xx = jnp.meshgrid(gy, gx, indexing="ij")
            sampled = _bilinear_sample(feat[bi], yy, xx)  # [C, oh*r, ow*r]
            C = sampled.shape[0]
            pooled = sampled.reshape(C, oh, ratio, ow, ratio).mean((2, 4))
            return pooled

        return jax.vmap(one)(rois, batch_idx)

    return apply_op("roi_align", fn, x, boxes, boxes_num)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0):
    """RoIPool: max over each bin (reference roi_pool). Approximated on a
    dense sampling grid (4x4 per bin) for static shapes."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    ratio = 4

    def fn(feat, rois, rois_num):
        R = rois.shape[0]
        starts = jnp.cumsum(rois_num) - rois_num
        batch_idx = jnp.sum(
            (jnp.arange(R)[:, None] >= starts[None, :]).astype(jnp.int32),
            axis=1) - 1

        def one(roi, bi):
            x1, y1, x2, y2 = roi * spatial_scale
            rw = jnp.maximum(x2 - x1, 1e-3)
            rh = jnp.maximum(y2 - y1, 1e-3)
            gy = y1 + (jnp.arange(oh * ratio) + 0.5) * rh / (oh * ratio)
            gx = x1 + (jnp.arange(ow * ratio) + 0.5) * rw / (ow * ratio)
            yy, xx = jnp.meshgrid(gy, gx, indexing="ij")
            sampled = _bilinear_sample(feat[bi], yy, xx)
            C = sampled.shape[0]
            return sampled.reshape(C, oh, ratio, ow, ratio).max((2, 4))

        return jax.vmap(one)(rois, batch_idx)

    return apply_op("roi_pool", fn, x, boxes, boxes_num)


class RoIAlign:
    def __init__(self, output_size, spatial_scale=1.0):
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale)


class RoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """2-D deformable convolution (v1 when ``mask is None``, else v2).

    Reference: python/paddle/vision/ops.py:753 (deform_conv2d) over the phi
    kernel paddle/phi/kernels/impl/deformable_conv_kernel_impl.h
    (modulated_deformable_im2col + GEMM). TPU-native: the im2col with
    learned offsets becomes one vectorized bilinear gather producing
    [N, C, kHkW, Ho, Wo] columns (XLA gathers), and the contraction with the
    kernel is one einsum that lands on the MXU — no per-position CUDA
    sampling kernel.

    Layouts (reference): x [N, C, H, W]; weight [M, C/groups, kH, kW];
    offset [N, 2*dg*kH*kW, Ho, Wo] with channel order (dg, kH*kW, {dy,dx});
    mask [N, dg*kH*kW, Ho, Wo]. Zero padding outside the input extent.
    """
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    dg, g = int(deformable_groups), int(groups)

    def fn(xv, off, w, b, m):
        N, C, H, W = xv.shape
        M, Cg, kH, kW = w.shape
        K = kH * kW
        Ho = (H + 2 * p[0] - (d[0] * (kH - 1) + 1)) // s[0] + 1
        Wo = (W + 2 * p[1] - (d[1] * (kW - 1) + 1)) // s[1] + 1
        # base sampling grid per kernel tap: [K, Ho] / [K, Wo]
        ky, kx = jnp.meshgrid(jnp.arange(kH), jnp.arange(kW), indexing="ij")
        base_y = (jnp.arange(Ho) * s[0] - p[0])[None, :] + (
            ky.reshape(-1) * d[0])[:, None]                  # [K, Ho]
        base_x = (jnp.arange(Wo) * s[1] - p[1])[None, :] + (
            kx.reshape(-1) * d[1])[:, None]                  # [K, Wo]
        off = off.reshape(N, dg, K, 2, Ho, Wo)
        ys = base_y[None, None, :, :, None] + off[:, :, :, 0]  # [N,dg,K,Ho,Wo]
        xs = base_x[None, None, :, None, :] + off[:, :, :, 1]

        Cd = C // dg
        xp = xv.reshape(N, dg, Cd, H * W)
        y0 = jnp.floor(ys)
        x0 = jnp.floor(xs)
        cols = 0.0
        for yy, wy in ((y0, 1.0 - (ys - y0)), (y0 + 1.0, ys - y0)):
            for xx, wx in ((x0, 1.0 - (xs - x0)), (x0 + 1.0, xs - x0)):
                valid = ((yy >= 0) & (yy <= H - 1)
                         & (xx >= 0) & (xx <= W - 1))
                lin = (jnp.clip(yy, 0, H - 1).astype(jnp.int32) * W
                       + jnp.clip(xx, 0, W - 1).astype(jnp.int32))
                vals = jnp.take_along_axis(
                    xp, lin.reshape(N, dg, 1, K * Ho * Wo), axis=3)
                wgt = (wy * wx * valid).reshape(N, dg, 1, K * Ho * Wo)
                cols = cols + vals * wgt.astype(xv.dtype)
        cols = cols.reshape(N, dg, Cd, K, Ho, Wo)
        if m is not None:
            cols = cols * m.reshape(N, dg, 1, K, Ho, Wo).astype(xv.dtype)
        # group conv as one contraction: [N,g,Cg,K,P] x [g,Mg,Cg,K]
        cols = cols.reshape(N, g, C // g, K, Ho * Wo)
        wg = w.reshape(g, M // g, Cg, K)
        out = jnp.einsum("ngckp,gmck->ngmp", cols, wg)
        out = out.reshape(N, M, Ho, Wo)
        if b is not None:
            out = out + b.reshape(1, M, 1, 1)
        return out

    return apply_op("deform_conv2d", fn, x, offset, weight, bias, mask)


def _make_deform_conv2d_layer():
    # deferred so vision.ops does not import nn at module load (cycle)
    from ..nn import Layer

    class DeformConv2D(Layer):
        """paddle.vision.ops.DeformConv2D layer parity (reference
        ops.py:927). A real Layer: its weight/bias register with parent
        models (parameters()/state_dict()) like any sublayer."""

        def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                     padding=0, dilation=1, deformable_groups=1, groups=1,
                     weight_attr=None, bias_attr=None):
            super().__init__()
            kh, kw = ((kernel_size, kernel_size)
                      if isinstance(kernel_size, int) else tuple(kernel_size))
            self._cfg = dict(stride=stride, padding=padding,
                             dilation=dilation,
                             deformable_groups=deformable_groups,
                             groups=groups)
            self.weight = self.create_parameter(
                [out_channels, in_channels // groups, kh, kw],
                attr=weight_attr)
            self.bias = (None if bias_attr is False else
                         self.create_parameter([out_channels],
                                               attr=bias_attr, is_bias=True))

        def forward(self, x, offset, mask=None):
            return deform_conv2d(x, offset, self.weight, self.bias,
                                 mask=mask, **self._cfg)

    return DeformConv2D


def _make_psroi_pool_layer():
    from ..nn import Layer

    class PSRoIPool(Layer):
        """paddle.vision.ops.PSRoIPool layer parity (reference ops.py)."""

        def __init__(self, output_size, spatial_scale=1.0):
            super().__init__()
            self._size = output_size
            self._scale = spatial_scale

        def forward(self, x, boxes, boxes_num):
            return psroi_pool(x, boxes, boxes_num, self._size, self._scale)

    return PSRoIPool


def _make_conv_norm_activation():
    from ..nn import BatchNorm2D, Conv2D, ReLU, Sequential

    class ConvNormActivation(Sequential):
        """Conv → Norm → Activation block (reference ops.py:1810; the
        torchvision misc block the paddle zoo models compose from)."""

        def __init__(self, in_channels, out_channels, kernel_size=3,
                     stride=1, padding=None, groups=1,
                     norm_layer=BatchNorm2D, activation_layer=ReLU,
                     dilation=1, bias=None):
            if padding is None:
                ks = ((kernel_size, kernel_size)
                      if isinstance(kernel_size, int) else tuple(kernel_size))
                ds = ((dilation, dilation)
                      if isinstance(dilation, int) else tuple(dilation))
                padding = [(k - 1) // 2 * d for k, d in zip(ks, ds)]
                if padding[0] == padding[1]:
                    padding = padding[0]
            if bias is None:
                bias = norm_layer is None
            layers = [Conv2D(in_channels, out_channels, kernel_size, stride,
                             padding, dilation=dilation, groups=groups,
                             bias_attr=None if bias else False)]
            if norm_layer is not None:
                layers.append(norm_layer(out_channels))
            if activation_layer is not None:
                layers.append(activation_layer())
            super().__init__(*layers)

    return ConvNormActivation


_LAZY_LAYERS = {
    "DeformConv2D": _make_deform_conv2d_layer,
    "PSRoIPool": _make_psroi_pool_layer,
    "ConvNormActivation": _make_conv_norm_activation,
}


def __getattr__(name):
    factory = _LAZY_LAYERS.get(name)
    if factory is not None:
        cls = factory()
        globals()[name] = cls
        return cls
    raise AttributeError(name)


def read_file(path, name=None):
    """Raw file bytes as a 1-D uint8 Tensor (reference ops.py read_file)."""
    with open(path, "rb") as f:
        data = f.read()
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte Tensor to [C, H, W] uint8 (reference ops.py
    decode_jpeg — a CPU host op there too; served by pillow here)."""
    import io

    from PIL import Image

    raw = bytes(np.asarray(x._data if isinstance(x, Tensor) else x,
                           np.uint8).tobytes())
    if mode not in ("unchanged", "gray", "rgb", "RGB"):
        raise ValueError(
            f"decode_jpeg: mode must be 'unchanged'|'gray'|'rgb', got "
            f"{mode!r}")
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode in ("rgb", "RGB"):
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


__all__ = ["box_iou", "nms", "roi_align", "roi_pool", "RoIAlign", "RoIPool",
           "deform_conv2d", "DeformConv2D", "PSRoIPool",
           "ConvNormActivation", "read_file", "decode_jpeg"]


# ---------------------------------------------------------------------------
# Detection tail ops (round-1 verdict item 5). Reference kernels:
# phi/kernels/impl/box_coder.h, gpu/prior_box_kernel.cu, gpu/yolo_box_kernel.cu,
# cpu/yolo_loss_kernel.cc, gpu/matrix_nms_kernel.cu, gpu/psroi_pool_kernel.cu,
# gpu/generate_proposals_kernel.cu, gpu/distribute_fpn_proposals_kernel.cu.
# TPU stance: everything static-shaped; "variable-count" outputs are padded
# arrays + explicit counts (XLA cannot do data-dependent shapes).
# ---------------------------------------------------------------------------


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes over a feature map (reference: prior_box_kernel.cu).
    Returns (boxes [H, W, P, 4], variances [H, W, P, 4]) normalized xyxy."""
    import numpy as np

    min_sizes = [float(m) for m in np.atleast_1d(min_sizes)]
    max_sizes = [float(m) for m in np.atleast_1d(max_sizes)] if max_sizes else []
    ars = [1.0]
    for ar in np.atleast_1d(aspect_ratios):
        ar = float(ar)
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)

    def fn(feat, img):
        H, W = feat.shape[2], feat.shape[3]
        imH, imW = img.shape[2], img.shape[3]
        step_w = float(steps[0]) or imW / W
        step_h = float(steps[1]) or imH / H
        cx = (jnp.arange(W) + offset) * step_w  # [W]
        cy = (jnp.arange(H) + offset) * step_h  # [H]
        whs = []  # per-prior (w, h) in pixels
        for k, ms in enumerate(min_sizes):
            def ar_whs():
                return [(ms * float(np.sqrt(a)), ms / float(np.sqrt(a)))
                        for a in ars if abs(a - 1.0) >= 1e-6]

            whs.append((ms, ms))
            if min_max_aspect_ratios_order:
                if max_sizes:
                    bs = float(np.sqrt(ms * max_sizes[k]))
                    whs.append((bs, bs))
                whs.extend(ar_whs())
            else:
                whs.extend(ar_whs())
                if max_sizes:
                    bs = float(np.sqrt(ms * max_sizes[k]))
                    whs.append((bs, bs))
        wh = jnp.asarray(whs, jnp.float32)  # [P, 2]
        P = wh.shape[0]
        shape = (H, W, P)
        boxes = jnp.stack([
            jnp.broadcast_to((cx[None, :, None] - wh[None, None, :, 0] / 2) / imW, shape),
            jnp.broadcast_to((cy[:, None, None] - wh[None, None, :, 1] / 2) / imH, shape),
            jnp.broadcast_to((cx[None, :, None] + wh[None, None, :, 0] / 2) / imW, shape),
            jnp.broadcast_to((cy[:, None, None] + wh[None, None, :, 1] / 2) / imH, shape),
        ], axis=-1)  # [H, W, P, 4]
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               (H, W, P, 4))
        return boxes, var

    return apply_op("prior_box", fn, input, image)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    """Encode/decode boxes against priors (reference: box_coder.h)."""
    import numpy as np

    def split_prior(p):
        norm = 0.0 if box_normalized else 1.0
        pw = p[..., 2] - p[..., 0] + norm
        ph = p[..., 3] - p[..., 1] + norm
        pcx = p[..., 0] + pw / 2
        pcy = p[..., 1] + ph / 2
        return pw, ph, pcx, pcy

    def fn(p, pv, t):
        if pv is not None and pv.ndim == 1:
            pv = pv[None, :]
        if code_type == "encode_center_size":
            # t [N,4] targets vs p [M,4] priors -> [N, M, 4]
            pw, ph, pcx, pcy = split_prior(p)  # [M]
            norm = 0.0 if box_normalized else 1.0
            tw = t[:, 2] - t[:, 0] + norm
            th = t[:, 3] - t[:, 1] + norm
            tcx = t[:, 0] + tw / 2
            tcy = t[:, 1] + th / 2
            ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
            oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
            ow = jnp.log(tw[:, None] / pw[None, :])
            oh = jnp.log(th[:, None] / ph[None, :])
            out = jnp.stack([ox, oy, ow, oh], axis=-1)
            if pv is not None:
                out = out / pv[None, :, :]
            return out
        # decode_center_size: t [N, M, 4] deltas; priors broadcast on `axis`
        pw, ph, pcx, pcy = split_prior(p)
        ex = (None, slice(None)) if axis == 0 else (slice(None), None)
        d = t
        if pv is not None:
            d = d * (pv[ex[0], ex[1], :] if pv.ndim == 2 else pv)
        dcx = d[..., 0] * pw[ex] + pcx[ex]
        dcy = d[..., 1] * ph[ex] + pcy[ex]
        dw = jnp.exp(d[..., 2]) * pw[ex]
        dh = jnp.exp(d[..., 3]) * ph[ex]
        norm = 0.0 if box_normalized else 1.0
        return jnp.stack([
            dcx - dw / 2, dcy - dh / 2,
            dcx + dw / 2 - norm, dcy + dh / 2 - norm,
        ], axis=-1)

    return apply_op("box_coder", fn, prior_box, prior_box_var, target_box)


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Decode YOLOv3 head predictions (reference: yolo_box_kernel.cu).
    x [N, an*(5+C), H, W] (plus `an` iou channels first when iou_aware);
    returns (boxes [N, an*H*W, 4] image-pixel xyxy, scores [N, an*H*W, C]).
    Box layout is anchor-major over (an, H, W)."""
    an = len(anchors) // 2

    def fn(v, imgs):
        N, _, H, W = v.shape
        if iou_aware:
            iou_pred = jax.nn.sigmoid(v[:, :an].reshape(N, an, 1, H, W))
            v = v[:, an:]
        v = v.reshape(N, an, 5 + class_num, H, W)
        aw = jnp.asarray(anchors[0::2], jnp.float32).reshape(1, an, 1, 1)
        ah = jnp.asarray(anchors[1::2], jnp.float32).reshape(1, an, 1, 1)
        gx = jnp.arange(W, dtype=jnp.float32).reshape(1, 1, 1, W)
        gy = jnp.arange(H, dtype=jnp.float32).reshape(1, 1, H, 1)
        bias = 0.5 * (scale_x_y - 1.0)
        bx = (jax.nn.sigmoid(v[:, :, 0]) * scale_x_y - bias + gx) / W
        by = (jax.nn.sigmoid(v[:, :, 1]) * scale_x_y - bias + gy) / H
        bw = jnp.exp(v[:, :, 2]) * aw / (W * downsample_ratio)
        bh = jnp.exp(v[:, :, 3]) * ah / (H * downsample_ratio)
        conf = jax.nn.sigmoid(v[:, :, 4])
        if iou_aware:
            conf = conf ** (1.0 - iou_aware_factor) * (
                iou_pred[:, :, 0] ** iou_aware_factor)
        cls = jax.nn.sigmoid(v[:, :, 5:])  # [N, an, C, H, W]
        img_h = imgs[:, 0].astype(jnp.float32).reshape(N, 1, 1, 1)
        img_w = imgs[:, 1].astype(jnp.float32).reshape(N, 1, 1, 1)
        x1 = (bx - bw / 2) * img_w
        y1 = (by - bh / 2) * img_h
        x2 = (bx + bw / 2) * img_w
        y2 = (by + bh / 2) * img_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0.0, img_w - 1)
            y1 = jnp.clip(y1, 0.0, img_h - 1)
            x2 = jnp.clip(x2, 0.0, img_w - 1)
            y2 = jnp.clip(y2, 0.0, img_h - 1)
        valid = conf >= conf_thresh
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)  # [N, an, H, W, 4]
        boxes = jnp.where(valid[..., None], boxes, 0.0)
        scores = conf[:, :, None] * cls  # [N, an, C, H, W]
        scores = jnp.where(valid[:, :, None], scores, 0.0)
        boxes = boxes.reshape(N, an * H * W, 4)
        scores = jnp.moveaxis(scores, 2, -1).reshape(N, an * H * W, class_num)
        return boxes, scores

    return apply_op("yolo_box", fn, x, img_size)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0, name=None):
    """YOLOv3 loss for one detection scale (reference: yolo_loss_kernel.cc).

    x [N, am*(5+C), H, W]; gt_box [N, B, 4] normalized (cx, cy, w, h);
    gt_label [N, B] int. Positives: each gt is assigned to its best anchor
    (by wh-IoU over the FULL anchor list); only anchors in ``anchor_mask``
    produce loss, at the gt's center cell. Objectness negatives with best-gt
    IoU above ``ignore_thresh`` are ignored. Returns loss [N]."""
    import numpy as np

    am = list(anchor_mask)
    n_mask = len(am)
    all_aw = np.asarray(anchors[0::2], np.float32)
    all_ah = np.asarray(anchors[1::2], np.float32)

    def bce(logit, label):
        return jnp.maximum(logit, 0) - logit * label + jnp.log1p(
            jnp.exp(-jnp.abs(logit)))

    def fn(v, gb, gl, gs):
        N, _, H, W = v.shape
        B = gb.shape[1]
        v = v.reshape(N, n_mask, 5 + class_num, H, W)
        in_w = W * downsample_ratio
        in_h = H * downsample_ratio

        # --- ignore mask: pred-box IoU vs every gt -----------------------
        aw = jnp.asarray(all_aw[am]).reshape(1, n_mask, 1, 1)
        ah = jnp.asarray(all_ah[am]).reshape(1, n_mask, 1, 1)
        gx = jnp.arange(W, dtype=jnp.float32).reshape(1, 1, 1, W)
        gy = jnp.arange(H, dtype=jnp.float32).reshape(1, 1, H, 1)
        px = (jax.nn.sigmoid(v[:, :, 0]) + gx) / W
        py = (jax.nn.sigmoid(v[:, :, 1]) + gy) / H
        pw = jnp.exp(v[:, :, 2]) * aw / in_w
        ph = jnp.exp(v[:, :, 3]) * ah / in_h
        # corners, normalized
        p1 = jnp.stack([px - pw / 2, py - ph / 2], -1)
        p2 = jnp.stack([px + pw / 2, py + ph / 2], -1)
        g1 = jnp.stack([gb[..., 0] - gb[..., 2] / 2,
                        gb[..., 1] - gb[..., 3] / 2], -1)  # [N, B, 2]
        g2 = jnp.stack([gb[..., 0] + gb[..., 2] / 2,
                        gb[..., 1] + gb[..., 3] / 2], -1)
        lt = jnp.maximum(p1[:, :, :, :, None, :], g1[:, None, None, None])
        rb = jnp.minimum(p2[:, :, :, :, None, :], g2[:, None, None, None])
        wh = jnp.clip(rb - lt, 0.0)
        inter = wh[..., 0] * wh[..., 1]
        parea = (pw * ph)[..., None]
        garea = (gb[..., 2] * gb[..., 3])[:, None, None, None, :]
        iou = inter / (parea + garea - inter + 1e-10)  # [N,a,H,W,B]
        gvalid = (gb[..., 2] > 0)[:, None, None, None, :]
        best_iou = jnp.max(jnp.where(gvalid, iou, 0.0), axis=-1)
        ignore = best_iou > ignore_thresh

        # --- positive assignment ----------------------------------------
        # best anchor per gt by wh-IoU at origin over the FULL anchor list
        gw = gb[..., 2] * in_w  # pixels
        gh = gb[..., 3] * in_h
        awf = jnp.asarray(all_aw).reshape(1, 1, -1)
        ahf = jnp.asarray(all_ah).reshape(1, 1, -1)
        inter_a = (jnp.minimum(gw[..., None], awf)
                   * jnp.minimum(gh[..., None], ahf))
        union_a = gw[..., None] * gh[..., None] + awf * ahf - inter_a
        best_a = jnp.argmax(inter_a / (union_a + 1e-10), axis=-1)  # [N,B]
        # local index within this scale's mask (or -1)
        local = jnp.full_like(best_a, -1)
        for li, a in enumerate(am):
            local = jnp.where(best_a == a, li, local)
        valid = (local >= 0) & (gb[..., 2] > 0)
        gi = jnp.clip((gb[..., 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gb[..., 1] * H).astype(jnp.int32), 0, H - 1)

        # scatter targets: [N, a, H, W] planes
        score_w = gs if gs is not None else jnp.ones_like(gb[..., 0])
        tx = gb[..., 0] * W - gi
        ty = gb[..., 1] * H - gj
        aw_g = jnp.take(jnp.asarray(all_aw), jnp.clip(best_a, 0))
        ah_g = jnp.take(jnp.asarray(all_ah), jnp.clip(best_a, 0))
        tw = jnp.log(jnp.clip(gw / aw_g, 1e-9))
        th = jnp.log(jnp.clip(gh / ah_g, 1e-9))
        box_w = (2.0 - gb[..., 2] * gb[..., 3]) * score_w  # paddle scale

        nidx = jnp.arange(N)[:, None].repeat(B, 1)
        li = jnp.clip(local, 0)

        def plane(vals):
            p = jnp.zeros((N, n_mask, H, W), jnp.float32)
            return p.at[nidx, li, gj, gi].set(
                jnp.where(valid, vals, 0.0), mode="drop")

        obj_t = plane(jnp.ones_like(tx))
        objw_t = plane(score_w)
        tx_t, ty_t = plane(tx), plane(ty)
        tw_t, th_t = plane(tw), plane(th)
        bw_t = plane(box_w)
        # class one-hot targets [N, a, H, W, C]
        smooth = 1.0 / class_num if (use_label_smooth and class_num > 1) else 0.0
        onehot = jax.nn.one_hot(gl, class_num)
        if smooth:
            onehot = onehot * (1.0 - smooth) + smooth * 0.5  # paddle-ish
        cls_t = jnp.zeros((N, n_mask, H, W, class_num), jnp.float32)
        cls_t = cls_t.at[nidx, li, gj, gi].set(
            jnp.where(valid[..., None], onehot, 0.0), mode="drop")

        pos = obj_t > 0
        lx = bw_t * bce(v[:, :, 0], tx_t) * pos
        ly = bw_t * bce(v[:, :, 1], ty_t) * pos
        lw = bw_t * jnp.abs(v[:, :, 2] - tw_t) * pos
        lh = bw_t * jnp.abs(v[:, :, 3] - th_t) * pos
        obj_logit = v[:, :, 4]
        lobj = (objw_t * bce(obj_logit, jnp.ones_like(obj_logit)) * pos
                + bce(obj_logit, jnp.zeros_like(obj_logit))
                * (~pos) * (~ignore))
        lcls = (bce(jnp.moveaxis(v[:, :, 5:], 2, -1), cls_t)
                * pos[..., None]).sum(-1)
        per_im = (lx + ly + lw + lh + lobj + lcls).sum(axis=(1, 2, 3))
        return per_im

    return apply_op("yolo_loss", fn, x, gt_box, gt_label, gt_score)


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2; reference: matrix_nms_kernel.cu). Decay-based,
    no sequential suppression — TPU-friendly closed form.

    bboxes [N, M, 4], scores [N, C, M]. Static-shape output: padded
    [N, keep_top_k, 6] (label, score, xyxy), invalid rows -1; plus index
    [N, keep_top_k] and rois_num [N]."""

    def fn(bb, sc):
        N, C, M = sc.shape
        K = min(nms_top_k, M)

        def one_image(b, s):
            # mask out background + below-threshold
            cls_ids = jnp.arange(C)
            keep_cls = cls_ids != background_label
            s = jnp.where(keep_cls[:, None], s, -1.0)
            s = jnp.where(s >= score_threshold, s, -1.0)
            # flatten (class, box), take top nms_top_k
            flat = s.reshape(-1)
            top_s, top_i = jax.lax.top_k(flat, K)
            top_c = top_i // M
            top_b = top_i % M
            boxes_k = b[top_b]
            # IoU among selected (same-class only suppresses)
            area = ((boxes_k[:, 2] - boxes_k[:, 0])
                    * (boxes_k[:, 3] - boxes_k[:, 1]))
            lt = jnp.maximum(boxes_k[:, None, :2], boxes_k[None, :, :2])
            rb = jnp.minimum(boxes_k[:, None, 2:], boxes_k[None, :, 2:])
            wh = jnp.clip(rb - lt, 0.0)
            inter = wh[..., 0] * wh[..., 1]
            iou = inter / (area[:, None] + area[None, :] - inter + 1e-10)
            same_cls = top_c[:, None] == top_c[None, :]
            higher = (jnp.arange(K)[None, :] < jnp.arange(K)[:, None])
            iou_h = jnp.where(same_cls & higher, iou, 0.0)  # [i, j<i]
            iou_max = jnp.max(iou_h, axis=1)  # compensation per i... per j
            # decay_j = min_i f(iou_ij) / f(iou_max_i) over higher-scored i
            if use_gaussian:
                f = lambda x: jnp.exp(-(x ** 2) / gaussian_sigma)
            else:
                f = lambda x: 1.0 - x
            comp = f(jnp.where(same_cls & higher, iou, 0.0))
            # entry [t, j]: suppressor j (higher-ranked) decays target t,
            # normalized by the suppressor's OWN max overlap f(iou_max[j])
            comp_norm = jnp.broadcast_to(f(iou_max)[None, :], (K, K))
            decay = jnp.where(same_cls & higher,
                              comp / jnp.maximum(comp_norm, 1e-10), 1.0)
            decay = jnp.min(decay, axis=1)
            new_s = jnp.where(top_s > 0, top_s * decay, -1.0)
            new_s = jnp.where(new_s >= post_threshold, new_s, -1.0)
            KK = min(keep_top_k, K)
            fin_s, fin_i = jax.lax.top_k(new_s, KK)
            out = jnp.concatenate([
                top_c[fin_i][:, None].astype(jnp.float32),
                fin_s[:, None],
                boxes_k[fin_i],
            ], axis=1)
            valid = fin_s > 0
            out = jnp.where(valid[:, None], out, -1.0)
            idx = jnp.where(valid, top_b[fin_i], -1)
            return out, idx, valid.sum()

        outs, idxs, nums = jax.vmap(one_image)(bb, sc)
        return outs, idxs, nums.astype(jnp.int32)

    out, idx, num = apply_op("matrix_nms", fn, bboxes, scores)
    res = [out]
    if return_index:
        res.append(idx)
    if return_rois_num:
        res.append(num)
    return tuple(res) if len(res) > 1 else out


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI average pooling (reference:
    psroi_pool_kernel.cu). x [N, C, H, W] with C = out_c * k * k;
    boxes [M, 4]; returns [M, out_c, k, k]."""
    import numpy as np

    k = output_size if isinstance(output_size, int) else output_size[0]

    def fn(feat, rois, rois_num):
        N, C, H, W = feat.shape
        out_c = C // (k * k)
        M = rois.shape[0]
        # map each roi to its image by boxes_num counts
        cum = jnp.cumsum(rois_num)
        img_of = jnp.searchsorted(cum, jnp.arange(M), side="right")

        def one(roi, bi):
            x1 = roi[0] * spatial_scale
            y1 = roi[1] * spatial_scale
            x2 = roi[2] * spatial_scale
            y2 = roi[3] * spatial_scale
            rw = jnp.maximum(x2 - x1, 0.1)
            rh = jnp.maximum(y2 - y1, 0.1)
            bin_w = rw / k
            bin_h = rh / k
            fm = feat[bi].reshape(out_c, k, k, H, W)
            ys = jnp.arange(H, dtype=jnp.float32)
            xs = jnp.arange(W, dtype=jnp.float32)

            def bin_val(ph, pw):
                hs = jnp.floor(y1 + ph * bin_h)
                he = jnp.ceil(y1 + (ph + 1) * bin_h)
                ws = jnp.floor(x1 + pw * bin_w)
                we = jnp.ceil(x1 + (pw + 1) * bin_w)
                mask_y = (ys >= hs) & (ys < he) & (ys >= 0) & (ys < H)
                mask_x = (xs >= ws) & (xs < we) & (xs >= 0) & (xs < W)
                m2 = mask_y[:, None] & mask_x[None, :]
                cnt = jnp.maximum(m2.sum(), 1)
                plane = fm[:, ph, pw]  # [out_c, H, W]
                return jnp.where(m2[None], plane, 0.0).sum((1, 2)) / cnt

            vals = jnp.stack([
                jnp.stack([bin_val(ph, pw) for pw in range(k)], -1)
                for ph in range(k)], -2)  # [out_c, k, k]
            return vals

        return jax.vmap(one)(rois, img_of)

    return apply_op("psroi_pool", fn, x, boxes, boxes_num)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Assign RoIs to FPN levels (reference:
    distribute_fpn_proposals_kernel.cu). Static-shape: per-level outputs are
    [M, 4] padded arrays with a count each; restore_ind maps the
    concatenated per-level order back to the input order."""
    import numpy as np

    n_levels = max_level - min_level + 1

    def fn(rois):
        M = rois.shape[0]
        off = 1.0 if pixel_offset else 0.0
        w = rois[:, 2] - rois[:, 0] + off
        h = rois[:, 3] - rois[:, 1] + off
        scale = jnp.sqrt(jnp.clip(w * h, 0.0))
        lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-8)) + refer_level
        lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
        outs = []
        counts = []
        order_parts = []
        for L in range(min_level, max_level + 1):
            m = lvl == L
            # stable partition: indices of this level first, padded
            key = jnp.where(m, jnp.arange(M), M + jnp.arange(M))
            perm = jnp.argsort(key)
            sel = rois[perm]
            cnt = m.sum()
            valid = jnp.arange(M) < cnt
            outs.append(jnp.where(valid[:, None], sel, -1.0))
            counts.append(cnt)
            order_parts.append(jnp.where(valid, perm, -1))
        # restore index: position of each original roi in the concatenated
        # per-level output
        restore = jnp.zeros((M,), jnp.int32)
        base = 0
        for i, part in enumerate(order_parts):
            pos = jnp.arange(M) + base
            restore = restore.at[jnp.clip(part, 0)].set(
                jnp.where(part >= 0, pos, restore[jnp.clip(part, 0)]).astype(jnp.int32))
            base = base + counts[i]
        return (*outs, restore, jnp.stack(counts).astype(jnp.int32))

    if rois_num is not None:
        raise NotImplementedError(
            "distribute_fpn_proposals: per-image rois_num bookkeeping is not "
            "implemented; pass the flat RoI tensor (level assignment is "
            "per-RoI and image-independent)")
    res = apply_op("distribute_fpn_proposals", fn, fpn_rois)
    multi_rois = list(res[:n_levels])
    restore_ind = res[n_levels]
    nums = res[n_levels + 1]
    return multi_rois, restore_ind, nums


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=True, name=None):
    """RPN proposal generation (reference: generate_proposals_kernel.cu).
    scores [N, A, H, W], bbox_deltas [N, 4A, H, W], anchors [H, W, A, 4]
    (or flattened), variances like anchors. Static-shape: returns
    rois [N, post_nms_top_n, 4] padded, roi_probs, rois_num [N]."""

    def fn(sc, deltas, imgs, anc, var):
        N, A, H, W = sc.shape
        anc = anc.reshape(-1, 4)
        var_f = var.reshape(-1, 4) if var is not None else None
        M = anc.shape[0]  # H*W*A with anchor-minor layout [H, W, A]
        K1 = min(pre_nms_top_n, M)
        K2 = min(post_nms_top_n, K1)
        off = 1.0 if pixel_offset else 0.0

        def one(s, d, im):
            # layouts: scores [A, H, W] -> [H, W, A] flat; deltas [4A, H, W]
            sf = jnp.moveaxis(s, 0, -1).reshape(-1)
            df = jnp.moveaxis(d.reshape(A, 4, H, W), (2, 3), (0, 1)
                              ).reshape(-1, 4)
            # decode (anchor + delta * var), center-size form
            aw = anc[:, 2] - anc[:, 0] + off
            ah = anc[:, 3] - anc[:, 1] + off
            acx = anc[:, 0] + aw / 2
            acy = anc[:, 1] + ah / 2
            dd = df * var_f if var_f is not None else df
            cx = dd[:, 0] * aw + acx
            cy = dd[:, 1] * ah + acy
            bw = jnp.exp(jnp.clip(dd[:, 2], -10, 10)) * aw
            bh = jnp.exp(jnp.clip(dd[:, 3], -10, 10)) * ah
            boxes = jnp.stack([cx - bw / 2, cy - bh / 2,
                               cx + bw / 2 - off, cy + bh / 2 - off], -1)
            imh, imw = im[0], im[1]
            boxes = jnp.stack([
                jnp.clip(boxes[:, 0], 0, imw - off),
                jnp.clip(boxes[:, 1], 0, imh - off),
                jnp.clip(boxes[:, 2], 0, imw - off),
                jnp.clip(boxes[:, 3], 0, imh - off)], -1)
            ww = boxes[:, 2] - boxes[:, 0] + off
            hh = boxes[:, 3] - boxes[:, 1] + off
            ok = (ww >= min_size) & (hh >= min_size)
            sf = jnp.where(ok, sf, -1e30)
            top_s, top_i = jax.lax.top_k(sf, K1)
            bb = boxes[top_i]
            # greedy nms over K1 sorted boxes
            area = (bb[:, 2] - bb[:, 0] + off) * (bb[:, 3] - bb[:, 1] + off)
            lt = jnp.maximum(bb[:, None, :2], bb[None, :, :2])
            rb = jnp.minimum(bb[:, None, 2:], bb[None, :, 2:])
            wh = jnp.clip(rb - lt + off, 0.0)
            inter = wh[..., 0] * wh[..., 1]
            iou = inter / (area[:, None] + area[None, :] - inter + 1e-10)

            def body(i, keep):
                sup = jnp.any((iou[i] > nms_thresh) & keep
                              & (jnp.arange(K1) < i))
                return keep.at[i].set(keep[i] & ~sup)

            keep0 = top_s > -1e29
            keep = jax.lax.fori_loop(0, K1, body, keep0)
            key = jnp.where(keep, -top_s, 1e30 + jnp.arange(K1, dtype=jnp.float32))
            perm = jnp.argsort(key)[:K2]
            valid = keep[perm]
            rois = jnp.where(valid[:, None], bb[perm], 0.0)
            probs = jnp.where(valid, top_s[perm], 0.0)
            return rois, probs, valid.sum()

        rois, probs, nums = jax.vmap(one)(sc, deltas, imgs.astype(jnp.float32))
        return rois, probs, nums.astype(jnp.int32)

    out = apply_op("generate_proposals", fn, scores, bbox_deltas, img_size,
                   anchors, variances)
    if return_rois_num:
        return out
    return out[0], out[1]
