"""paddle.vision.ops parity: detection-model operators.

Reference: python/paddle/vision/ops.py (nms, roi_align, roi_pool, box
utilities) over phi detection kernels. TPU stance: NMS is an
O(N^2)-mask + sequential-suppression lax.while; RoI ops are bilinear
gathers — all static-shaped, jittable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd.engine import apply_op
from ..tensor.tensor import Tensor


def box_iou(boxes1, boxes2):
    """Pairwise IoU for [N,4] / [M,4] xyxy boxes -> [N, M]."""

    def fn(b1, b2):
        area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
        area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
        lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
        rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter + 1e-10)

    return apply_op("box_iou", fn, boxes1, boxes2)


def nms(boxes, iou_threshold: float = 0.3, scores=None,
        category_idxs=None, categories=None, top_k: int | None = None):
    """Greedy NMS (reference: vision/ops.py nms). Returns kept indices
    sorted by score. With category_idxs, suppression is per-category
    (batched NMS trick: offset boxes per class so classes never overlap).
    """
    import numpy as np

    def fn(b, s, cat):
        n = b.shape[0]
        if s is None:
            order = jnp.arange(n)
        else:
            order = jnp.argsort(-s)
        bb = b[order]
        if cat is not None:
            # shift each category into its own coordinate island
            span = jnp.max(bb) - jnp.min(bb) + 1.0
            offs = cat[order].astype(bb.dtype)[:, None] * span
            bb = bb + offs
        area = (bb[:, 2] - bb[:, 0]) * (bb[:, 3] - bb[:, 1])
        lt = jnp.maximum(bb[:, None, :2], bb[None, :, :2])
        rb = jnp.minimum(bb[:, None, 2:], bb[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        iou = inter / (area[:, None] + area[None, :] - inter + 1e-10)

        def body(i, keep):
            # suppress i if any still-kept higher-score box overlaps it
            sup = jnp.any((iou[i, :] > iou_threshold)
                          & keep & (jnp.arange(n) < i))
            return keep.at[i].set(~sup)

        keep = jax.lax.fori_loop(0, n, body, jnp.ones(n, bool))
        return order, keep

    b = boxes._data if isinstance(boxes, Tensor) else jnp.asarray(boxes)
    s = scores._data if isinstance(scores, Tensor) else scores
    c = (category_idxs._data if isinstance(category_idxs, Tensor)
         else category_idxs)
    order, keep = fn(b, None if s is None else jnp.asarray(s),
                     None if c is None else jnp.asarray(c))
    # keep refers to sorted positions; map back to original indices
    kept = np.asarray(order)[np.asarray(keep)]
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(jnp.asarray(kept, jnp.int64))


def _bilinear_sample(feat, y, x):
    """feat [C, H, W]; y/x arbitrary same-shaped grids -> [C, *grid]."""
    H, W = feat.shape[-2:]
    y0 = jnp.clip(jnp.floor(y), 0, H - 1)
    x0 = jnp.clip(jnp.floor(x), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    wy = jnp.clip(y - y0, 0, 1)
    wx = jnp.clip(x - x0, 0, 1)
    y0i, y1i, x0i, x1i = (a.astype(jnp.int32) for a in (y0, y1, x0, x1))
    v00 = feat[:, y0i, x0i]
    v01 = feat[:, y0i, x1i]
    v10 = feat[:, y1i, x0i]
    v11 = feat[:, y1i, x1i]
    return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
            + v10 * wy * (1 - wx) + v11 * wy * wx)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
              sampling_ratio: int = -1, aligned: bool = True):
    """RoIAlign (reference: vision/ops.py roi_align). x [N,C,H,W]; boxes
    [R,4] xyxy in input coords; boxes_num [N] rois per image. Returns
    [R, C, out_h, out_w]."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    ratio = 2 if sampling_ratio <= 0 else sampling_ratio

    def fn(feat, rois, rois_num):
        # map each roi to its batch index from boxes_num
        R = rois.shape[0]
        starts = jnp.cumsum(rois_num) - rois_num
        batch_idx = jnp.sum(
            (jnp.arange(R)[:, None] >= starts[None, :]).astype(jnp.int32),
            axis=1) - 1

        offset = 0.5 if aligned else 0.0

        def one(roi, bi):
            x1, y1, x2, y2 = (roi * spatial_scale) - offset
            rw = jnp.maximum(x2 - x1, 1e-3)
            rh = jnp.maximum(y2 - y1, 1e-3)
            bin_h, bin_w = rh / oh, rw / ow
            # sampling grid: ratio x ratio points per bin, averaged
            gy = (y1 + (jnp.arange(oh * ratio) + 0.5) * bin_h / ratio)
            gx = (x1 + (jnp.arange(ow * ratio) + 0.5) * bin_w / ratio)
            yy, xx = jnp.meshgrid(gy, gx, indexing="ij")
            sampled = _bilinear_sample(feat[bi], yy, xx)  # [C, oh*r, ow*r]
            C = sampled.shape[0]
            pooled = sampled.reshape(C, oh, ratio, ow, ratio).mean((2, 4))
            return pooled

        return jax.vmap(one)(rois, batch_idx)

    return apply_op("roi_align", fn, x, boxes, boxes_num)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0):
    """RoIPool: max over each bin (reference roi_pool). Approximated on a
    dense sampling grid (4x4 per bin) for static shapes."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    ratio = 4

    def fn(feat, rois, rois_num):
        R = rois.shape[0]
        starts = jnp.cumsum(rois_num) - rois_num
        batch_idx = jnp.sum(
            (jnp.arange(R)[:, None] >= starts[None, :]).astype(jnp.int32),
            axis=1) - 1

        def one(roi, bi):
            x1, y1, x2, y2 = roi * spatial_scale
            rw = jnp.maximum(x2 - x1, 1e-3)
            rh = jnp.maximum(y2 - y1, 1e-3)
            gy = y1 + (jnp.arange(oh * ratio) + 0.5) * rh / (oh * ratio)
            gx = x1 + (jnp.arange(ow * ratio) + 0.5) * rw / (ow * ratio)
            yy, xx = jnp.meshgrid(gy, gx, indexing="ij")
            sampled = _bilinear_sample(feat[bi], yy, xx)
            C = sampled.shape[0]
            return sampled.reshape(C, oh, ratio, ow, ratio).max((2, 4))

        return jax.vmap(one)(rois, batch_idx)

    return apply_op("roi_pool", fn, x, boxes, boxes_num)


class RoIAlign:
    def __init__(self, output_size, spatial_scale=1.0):
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale)


class RoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


__all__ = ["box_iou", "nms", "roi_align", "roi_pool", "RoIAlign", "RoIPool"]
