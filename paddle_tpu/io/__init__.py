"""paddle.io parity: datasets, samplers, DataLoader.

Reference: python/paddle/io/ (reader.py:216 DataLoader, dataloader/*). The
multiprocess worker pool is host-side (feeding the TPU is a host job); worker
processes use the same index-batch protocol as the reference's worker.py.
"""
from .dataloader import DataLoader, get_worker_info
from .dataset import (
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    Dataset,
    IterableDataset,
    Subset,
    TensorDataset,
    random_split,
)
from .sampler import (
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    SubsetRandomSampler,
    WeightedRandomSampler,
)

__all__ = [
    "Dataset",
    "IterableDataset",
    "TensorDataset",
    "ComposeDataset",
    "ChainDataset",
    "ConcatDataset",
    "Subset",
    "random_split",
    "Sampler",
    "SequenceSampler",
    "RandomSampler",
    "WeightedRandomSampler",
    "SubsetRandomSampler",
    "BatchSampler",
    "DistributedBatchSampler",
    "DataLoader",
    "get_worker_info",
]
