"""Python wrapper for the native shared-memory ring (DataLoader transport).

Reference: the use_shared_memory DataLoader path (C++ BlockingQueue + shm
tensor segments). A worker process attaches by name and ``put``s pickled
batches; the main process ``get``s them — one memcpy per side, no pipe.
"""
from __future__ import annotations

import ctypes
import os
import pickle

from ..native import load_library


def _lib():
    lib = load_library("shm_ring")
    if not getattr(lib, "_configured", False):
        lib.pd_ring_create.restype = ctypes.c_void_p
        lib.pd_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.pd_ring_attach.restype = ctypes.c_void_p
        lib.pd_ring_attach.argtypes = [ctypes.c_char_p]
        lib.pd_ring_put.restype = ctypes.c_int
        # c_char_p: bytes pass zero-copy (length is explicit, NULs fine)
        lib.pd_ring_put.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int]
        lib.pd_ring_get.restype = ctypes.c_int
        lib.pd_ring_get.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
        lib.pd_ring_size.restype = ctypes.c_int
        lib.pd_ring_size.argtypes = [ctypes.c_void_p]
        lib.pd_ring_close.argtypes = [ctypes.c_void_p]
        lib.pd_ring_set_owner.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.pd_ring_free.argtypes = [ctypes.c_void_p]
        lib.pd_ring_free_buf.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        lib._configured = True
    return lib


class ShmRing:
    """Blocking MPMC byte-message ring over POSIX shared memory."""

    def __init__(self, name: str | None = None, capacity: int = 64 << 20,
                 create: bool = True):
        self._lib = _lib()
        if name is None:
            name = f"/pd_ring_{os.getpid()}_{id(self):x}"
        self.name = name
        if create:
            self._h = self._lib.pd_ring_create(name.encode(), capacity)
        else:
            self._h = self._lib.pd_ring_attach(name.encode())
        if not self._h:
            raise RuntimeError(f"ShmRing: cannot {'create' if create else 'attach'} {name}")
        self._closed = False
        # only the creating PROCESS may unlink; fork-inherited copies of a
        # creator ring must not tear the segment down when they finalize
        self._creator_pid = os.getpid() if create else None

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        return cls(name, create=False)

    def put_bytes(self, data: bytes, timeout: float | None = None) -> None:
        tmo = -1 if timeout is None else int(timeout * 1000)
        rc = self._lib.pd_ring_put(self._h, data, len(data), tmo)
        if rc == -1:
            raise TimeoutError("ShmRing.put timed out")
        if rc == -3:
            raise ValueError(
                f"message of {len(data)} bytes exceeds ring capacity")
        if rc != 0:
            raise RuntimeError("ShmRing closed")

    def get_bytes(self, timeout: float | None = None) -> bytes:
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_uint64(0)
        tmo = -1 if timeout is None else int(timeout * 1000)
        rc = self._lib.pd_ring_get(self._h, ctypes.byref(out),
                                   ctypes.byref(out_len), tmo)
        if rc == -1:
            raise TimeoutError("ShmRing.get timed out")
        if rc != 0:
            raise RuntimeError("ShmRing closed")
        try:
            # string_at = one memcpy; slicing the pointer would build a
            # python list of ints (catastrophic for MB payloads)
            return ctypes.string_at(out, out_len.value) if out_len.value else b""
        finally:
            self._lib.pd_ring_free_buf(out)

    def put(self, obj, timeout: float | None = None) -> None:
        self.put_bytes(pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL),
                       timeout)

    def get(self, timeout: float | None = None):
        return pickle.loads(self.get_bytes(timeout))

    def qsize_bytes(self) -> int:
        return max(self._lib.pd_ring_size(self._h), 0)

    def close(self) -> None:
        if not self._closed and self._h:
            self._lib.pd_ring_close(self._h)
            self._closed = True

    def free(self) -> None:
        if self._h:
            if (self._creator_pid is not None
                    and os.getpid() != self._creator_pid):
                self._lib.pd_ring_set_owner(self._h, 0)
            self._lib.pd_ring_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.free()
        except Exception:
            pass
