"""Datasets (parity: python/paddle/io/dataloader/dataset.py)."""
from __future__ import annotations

import bisect

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        lengths = {t.shape[0] for t in tensors}
        if len(lengths) != 1:
            raise ValueError("all tensors must have the same first dimension")
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        n = len(self.datasets[0])
        if any(len(d) != n for d in self.datasets):
            raise ValueError("all datasets must share length")

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            out.extend(sample if isinstance(sample, (list, tuple)) else [sample])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[ds_idx - 1] if ds_idx > 0 else 0
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    from ..framework.random import default_generator

    if np.isclose(sum(lengths), 1.0) and sum(lengths) <= 1:
        n = len(dataset)
        sizes = [int(np.floor(n * frac)) for frac in lengths]
        rem = n - sum(sizes)
        for i in range(rem):
            sizes[i % len(sizes)] += 1
        lengths = sizes
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths must equal dataset length")
    perm = np.random.RandomState(
        (generator or default_generator).initial_seed() & 0x7FFFFFFF
    ).permutation(len(dataset))
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[off : off + n].tolist()))
        off += n
    return out
