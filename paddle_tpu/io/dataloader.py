"""DataLoader.

Parity: paddle.io.DataLoader (reference: python/paddle/io/reader.py:216,
dataloader/dataloader_iter.py, worker.py). Host-side multiprocess workers via
the stdlib multiprocessing Pool protocol: the main process iterates the batch
sampler, ships index lists to workers, workers return collated numpy batches,
the main process wraps them as Tensors (device upload happens lazily on first
op, or eagerly via jnp.asarray).
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import queue as queue_mod
import threading
from dataclasses import dataclass

import numpy as np

from ..tensor.tensor import Tensor
from .dataset import IterableDataset
from .sampler import BatchSampler


@dataclass
class WorkerInfo:
    id: int
    num_workers: int
    dataset: object
    seed: int


_worker_info = None


def get_worker_info():
    return _worker_info


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp

        return Tensor(jnp.stack([b._data for b in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.number)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(items)) for items in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    return batch


def _np_collate(batch):
    """Collate to numpy (picklable) in worker processes."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return np.stack([b.numpy() for b in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.number)):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(_np_collate(list(items)) for items in transposed)
    if isinstance(sample, dict):
        return {k: _np_collate([b[k] for b in batch]) for k in sample}
    return batch


def _rewrap(obj):
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_rewrap(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _rewrap(v) for k, v in obj.items()}
    return obj


# Sentinel shipped through the shm ring when a batch is too large for it;
# the real payload travels on the sidecar pipe queue instead.
_VIA_PIPE = "__pd_batch_via_pipe__"


def _worker_loop(dataset, index_queue, data_queue, collate_fn, worker_id,
                 num_workers, seed, side_queue=None):
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset, seed)
    np.random.seed((seed + worker_id) & 0x7FFFFFFF)

    def put(msg):
        try:
            data_queue.put(msg)
        except ValueError:
            # shm ring: message exceeds ring capacity — fall back to the
            # pipe for this batch (marker through the ring keeps ordering)
            if side_queue is None:
                raise
            side_queue.put(msg)
            data_queue.put((msg[0], _VIA_PIPE, None))

    while True:
        task = index_queue.get()
        if task is None:
            break
        batch_id, indices = task
        try:
            samples = [dataset[i] for i in indices]
            data = collate_fn(samples)
            put((batch_id, data, None))
        except Exception as e:  # propagate to main process
            try:
                put((batch_id, None, e))
            except Exception:
                # the exception itself may be unpicklable — send its repr so
                # the main process still gets a diagnostic instead of hanging
                try:
                    put((batch_id, None, RuntimeError(
                        f"worker {worker_id}: {type(e).__name__}: {e!r} "
                        "(original exception was unpicklable)")))
                except Exception:
                    break  # transport closed during shutdown — just exit


class DataLoader:
    def __init__(
        self,
        dataset,
        feed_list=None,
        places=None,
        return_list=True,
        batch_sampler=None,
        batch_size=1,
        shuffle=False,
        drop_last=False,
        collate_fn=None,
        num_workers=0,
        use_buffer_reader=True,
        prefetch_factor=2,
        use_shared_memory=True,
        timeout=0,
        worker_init_fn=None,
        persistent_workers=False,
    ):
        self.dataset = dataset
        self.num_workers = num_workers
        self.collate_fn = collate_fn
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self.use_shared_memory = use_shared_memory
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_size = batch_size
            self.batch_sampler = None
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
                )
                self.batch_size = batch_size

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def __iter__(self):
        if self._iterable_mode:
            it = self._iter_iterable()
        elif self.batch_sampler is None:
            it = (self._collate_one(self.dataset[i]) for i in range(len(self.dataset)))
        elif self.num_workers == 0:
            it = self._iter_single()
        else:
            it = self._iter_multiprocess()
        return self._timed(it)

    @staticmethod
    def _timed(it):
        """Mark read spans on the global Benchmark timer (reader_cost)."""
        from ..profiler.timer import benchmark

        bench = benchmark()
        while True:
            bench.before_reader()
            try:
                batch = next(it)
            except StopIteration:
                return
            bench.after_reader()
            yield batch

    def _collate_one(self, sample):
        fn = self.collate_fn or default_collate_fn
        return fn([sample])

    def _iter_iterable(self):
        fn = self.collate_fn or default_collate_fn
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield fn(batch)

    def _iter_single(self):
        fn = self.collate_fn or default_collate_fn
        for indices in self.batch_sampler:
            yield fn([self.dataset[i] for i in indices])

    def _iter_multiprocess(self):
        """Index-queue / data-queue worker pool (worker.py protocol)."""
        ctx = mp.get_context("fork")
        from ..framework.random import default_generator

        seed = default_generator.initial_seed()
        index_queues = []
        # use_shared_memory: batches travel through the native shm ring
        # (one memcpy per side, no pipe) — reference DataLoader's
        # use_shared_memory path over C++ BlockingQueue + shm segments.
        # The MAP_SHARED mapping is inherited by forked workers, so the
        # same ring object works on both sides.
        ring = None
        if self.use_shared_memory:
            try:
                from .shm_ring import ShmRing

                ring = ShmRing(capacity=128 << 20)
            except Exception:
                ring = None  # no native toolchain: pipe transport fallback
        data_queue = ring if ring is not None else ctx.Queue()
        # sidecar pipe for batches that exceed the ring capacity
        side_queue = ctx.Queue() if ring is not None else None
        workers = []
        collate = _np_collate if self.collate_fn is None else self.collate_fn
        for wid in range(self.num_workers):
            iq = ctx.Queue()
            w = ctx.Process(
                target=_worker_loop,
                args=(self.dataset, iq, data_queue, collate, wid,
                      self.num_workers, seed, side_queue),
                daemon=True,
            )
            w.start()
            index_queues.append(iq)
            workers.append(w)
        try:
            sampler_iter = enumerate(iter(self.batch_sampler))
            n_dispatched = 0
            n_received = 0
            buffers = {}
            next_yield = 0
            # prime the pipeline
            for _ in range(self.num_workers * self.prefetch_factor):
                try:
                    bid, indices = next(sampler_iter)
                except StopIteration:
                    break
                index_queues[bid % self.num_workers].put((bid, indices))
                n_dispatched += 1
            while n_received < n_dispatched:
                bid, data, err = data_queue.get()
                if isinstance(data, str) and data == _VIA_PIPE:
                    # oversized batch: payload came through the sidecar pipe
                    bid, data, err = side_queue.get()
                n_received += 1
                if err is not None:
                    raise err
                buffers[bid] = data
                try:
                    nbid, indices = next(sampler_iter)
                    index_queues[nbid % self.num_workers].put((nbid, indices))
                    n_dispatched += 1
                except StopIteration:
                    pass
                while next_yield in buffers:
                    out = buffers.pop(next_yield)
                    next_yield += 1
                    yield _rewrap(out) if self.collate_fn is None else out
        finally:
            for iq in index_queues:
                iq.put(None)
            if ring is not None:
                ring.close()  # unblocks any worker mid-put
            for w in workers:
                w.join(timeout=1)
                if w.is_alive():
                    w.terminate()
            if ring is not None:
                ring.free()
