"""AMP autocast.

Parity: paddle.amp.auto_cast / amp_guard / decorate (reference:
python/paddle/amp/auto_cast.py:273/703/787 — O1 per-op autocast via
allow/block lists, O2 pure-low-precision with master weights). The cast hook
plugs into the autograd engine's apply_op, the same interception point the
reference generates into every ad_func (eager_gen.py:1826).
"""
from __future__ import annotations

import threading
from collections import Counter

import jax.numpy as jnp

from ..autograd import engine
from ..framework import dtype as dtype_mod
from ..framework import flags
from ..tensor.tensor import Tensor
from . import amp_lists


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = "bfloat16"
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()
        self.op_stats: Counter | None = None


_state = _AmpState()


def _amp_dtype():
    return dtype_mod.to_jax_dtype(_state.dtype)


def white_list():
    return amp_lists.WHITE_LIST | _state.custom_white


def black_list():
    return (amp_lists.BLACK_LIST - _state.custom_white) | _state.custom_black


def _cast_hook(op_name: str, leaves: list) -> list:
    if not _state.enabled:
        return leaves
    low = _amp_dtype()
    if _state.level == "O2":
        # pure low precision: cast every float input except blocklist ops
        target = jnp.float32 if op_name in black_list() else low
    else:
        if op_name in white_list():
            target = low
        elif op_name in black_list():
            target = jnp.float32
        else:
            # O1 gray ops: promote to the widest input float dtype
            has_f32 = any(
                isinstance(l, Tensor) and l._data.dtype == jnp.float32 for l in leaves
            )
            target = jnp.float32 if has_f32 else None
    if target is None:
        return leaves
    if _state.op_stats is not None and target == low:
        _state.op_stats[op_name] += 1
    out = []
    for leaf in leaves:
        if (
            isinstance(leaf, Tensor)
            and leaf._data.dtype in (jnp.float32, jnp.float16, jnp.bfloat16)
            and leaf._data.dtype != target
        ):
            out.append(leaf.astype(target))
        else:
            out.append(leaf)
    return out


engine.amp_cast_hook = _cast_hook


class auto_cast:
    """Context manager enabling AMP (paddle.amp.auto_cast parity)."""

    def __init__(
        self,
        enable: bool = True,
        custom_white_list=None,
        custom_black_list=None,
        level: str = "O1",
        dtype: str = "float16",
        use_promote: bool = True,
    ):
        if level not in ("O0", "O1", "O2"):
            raise ValueError(f"level must be O0/O1/O2, got {level}")
        self._cfg = (
            bool(enable) and level != "O0",
            set(custom_white_list or ()),
            set(custom_black_list or ()),
            level,
            dtype,
        )
        self._saved = None

    def __enter__(self):
        self._saved = (
            _state.enabled,
            _state.custom_white,
            _state.custom_black,
            _state.level,
            _state.dtype,
        )
        (
            _state.enabled,
            _state.custom_white,
            _state.custom_black,
            _state.level,
            _state.dtype,
        ) = self._cfg
        return self

    def __exit__(self, *exc):
        (
            _state.enabled,
            _state.custom_white,
            _state.custom_black,
            _state.level,
            _state.dtype,
        ) = self._saved
        return False


amp_guard = auto_cast


def decorate(
    models,
    optimizers=None,
    level: str = "O1",
    dtype: str = "float16",
    master_weight=None,
    save_dtype=None,
    master_grad: bool = False,
    excluded_layers=None,
):
    """O2: cast model params to low precision; optimizer keeps fp32 masters
    (multi_precision). Norm layers stay fp32 (paddle keeps them fp32 in O2)."""
    from ..nn.layer.norm import LayerNorm, RMSNorm, _BatchNormBase

    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        excluded = tuple(excluded_layers or ()) + (LayerNorm, RMSNorm, _BatchNormBase)
        for model in model_list:
            for layer in model.sublayers(include_self=True):
                if isinstance(layer, excluded):
                    continue
                for p in layer._parameters.values():
                    if p is not None and p._data.dtype == jnp.float32:
                        p._data = p._data.astype(dtype_mod.to_jax_dtype(dtype))
            model._casted_by_pure_fp16 = True
        if optimizers is not None:
            opt_list = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
            for opt in opt_list:
                opt._multi_precision = True
    if optimizers is None:
        return models
    return models, optimizers


def is_float16_supported(device=None) -> bool:
    return True


def is_bfloat16_supported(device=None) -> bool:
    return True


def collect_operator_stats():
    """Context manager counting low-precision op calls
    (paddle.amp.debugging.collect_operator_stats parity)."""

    class _Collector:
        def __enter__(self):
            _state.op_stats = Counter()
            return self

        def __exit__(self, *exc):
            stats = _state.op_stats
            _state.op_stats = None
            print("<------------------- op list -------------------->")
            for op, count in sorted((stats or {}).items()):
                print(f"  {op}: {count} low-precision calls")
            print("<------------------------------------------------->")
            return False

    return _Collector()
