from .auto_cast import (
    amp_guard,
    auto_cast,
    decorate,
    is_bfloat16_supported,
    is_float16_supported,
    white_list,
    black_list,
)
from .grad_scaler import AmpScaler, GradScaler
from . import debugging

__all__ = [
    "auto_cast",
    "amp_guard",
    "decorate",
    "GradScaler",
    "AmpScaler",
    "is_bfloat16_supported",
    "is_float16_supported",
    "debugging",
]
