"""Dynamic loss scaling.

Parity: paddle.amp.GradScaler / AmpScaler (reference:
python/paddle/amp/grad_scaler.py:578/:41 — dynamic scale doubling/halving on
inf/nan, unscale before step). Needed for fp16; bf16 typically runs unscaled.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor.tensor import Tensor


class AmpScaler:
    def __init__(
        self,
        enable: bool = True,
        init_loss_scaling: float = 2.0**16,
        incr_ratio: float = 2.0,
        decr_ratio: float = 0.5,
        incr_every_n_steps: int = 2000,
        decr_every_n_nan_or_inf: int = 1,
        use_dynamic_loss_scaling: bool = True,
    ):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        # Scale in float32: the default 2**16 exceeds float16 max (65504), so
        # a half-precision loss would overflow to inf before backward starts.
        if var.dtype == "float16" or var.dtype == "bfloat16":
            var = var.astype("float32")
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable or self._unscaled:
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g = p.grad._data.astype(jnp.float32) * inv
            if bool(jnp.any(~jnp.isfinite(g))):
                found = True
            p.grad._data = g.astype(p.grad._data.dtype)
        self._found_inf = found
        self._unscaled = True

    def minimize(self, optimizer, loss):
        self.step(optimizer)
        self.update()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not self._unscaled:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._unscaled = False

    def update(self):
        if not self._enable or not self._dynamic:
            self._found_inf = False
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def is_enable(self) -> bool:
        return self._enable

    def is_use_dynamic_loss_scaling(self) -> bool:
        return self._dynamic

    def get_loss_scaling(self) -> float:
        return self._scale

    def set_init_loss_scaling(self, value: float):
        self._scale = float(value)

    def state_dict(self) -> dict:
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
            "good_steps": self._good_steps,
            "bad_steps": self._bad_steps,
        }

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)


class GradScaler(AmpScaler):
    pass
