"""AMP op allow/block lists.

Parity: python/paddle/amp/amp_lists.py (WHITE_LIST/BLACK_LIST). Op names here
are the engine's apply_op names. bf16 is the TPU-native low precision; fp16 is
supported for API parity.
"""

# Ops that are numerically safe and fast in low precision (MXU ops).
WHITE_LIST = {
    "matmul",
    "mm",
    "bmm",
    "mv",
    "linear",
    "conv1d",
    "conv2d",
    "conv3d",
    "conv1d_transpose",
    "conv2d_transpose",
    "conv3d_transpose",
    "einsum",
    "addmm",
    "scaled_dot_product_attention",
    "flash_attn_unpadded",
}

# Ops that must stay fp32 (reductions / exp / norms — precision-sensitive).
BLACK_LIST = {
    "exp",
    "square",
    "log",
    "log2",
    "log10",
    "log1p",
    "mean",
    "sum",
    "prod",
    "softmax",
    "log_softmax",
    "cross_entropy",
    "softmax_with_cross_entropy",
    "nll_loss",
    "binary_cross_entropy",
    "bce_with_logits",
    "kl_div",
    "cosine_similarity",
    "layer_norm",
    "rms_norm",
    "batch_norm",
    "instance_norm",
    "group_norm",
    "local_response_norm",
    "cumsum",
    "cumprod",
    "logsumexp",
    "logcumsumexp",
    "norm",
    "vector_norm",
    "matrix_norm",
    "dist",
    "erfinv",
    "pow",
    "std",
    "var",
    "sigmoid_focal_loss",
    "ctc_loss",
    "svd",
    "qr",
    "eig",
    "eigh",
    "cholesky",
    "solve",
    "inv",
    "det",
    "slogdet",
    "lstsq",
    "pinv",
    "matrix_power",
}

# Everything else runs in whatever dtype its inputs already have.
