"""AMP op allow/block lists.

Parity: python/paddle/amp/amp_lists.py (WHITE_LIST/BLACK_LIST). Op names here
are the engine's apply_op names. bf16 is the TPU-native low precision; fp16 is
supported for API parity.

Both lists are DERIVED from the single-source op registry
(framework/op_registry.py ``amp`` column) — to change an op's AMP class,
edit its registry row, not this module.
"""
from ..framework.op_registry import amp_black_list, amp_white_list

# Ops that are numerically safe and fast in low precision (MXU ops).
WHITE_LIST = amp_white_list()

# Ops that must stay fp32 (reductions / exp / norms — precision-sensitive).
BLACK_LIST = amp_black_list()

# Everything else runs in whatever dtype its inputs already have.
