"""AMP numerical debugging.

Parity: python/paddle/amp/debugging.py (TensorCheckerConfig:157,
enable_tensor_checker:634, check_numerics:339, collect_operator_stats:540).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework import flags
from ..tensor.tensor import Tensor
from .auto_cast import collect_operator_stats  # re-export  # noqa: F401


class DebugMode:
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 4


class TensorCheckerConfig:
    def __init__(self, enable=False, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT, output_dir=None, checked_op_list=None, skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list
        self.skipped_op_list = skipped_op_list


def enable_tensor_checker(config: TensorCheckerConfig):
    if config.enable:
        level = 0 if config.debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT else 1
        flags.set_flags({"FLAGS_check_nan_inf": True, "FLAGS_check_nan_inf_level": level})


def disable_tensor_checker():
    flags.set_flags({"FLAGS_check_nan_inf": False})


def check_numerics(tensor: Tensor, op_type: str = "", var_name: str = "", debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT):
    """Return (num_nan, num_inf, num_zero) and optionally abort."""
    data = tensor._data
    n_nan = int(jnp.sum(jnp.isnan(data)))
    n_inf = int(jnp.sum(jnp.isinf(data)))
    n_zero = int(jnp.sum(data == 0))
    if debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT and (n_nan or n_inf):
        raise FloatingPointError(
            f"check_numerics: {op_type}/{var_name} has {n_nan} NaN, {n_inf} Inf"
        )
    return Tensor(jnp.asarray(n_nan)), Tensor(jnp.asarray(n_inf)), Tensor(jnp.asarray(n_zero))


_stats_ctx = None


def enable_operator_stats_collection():
    """Begin counting low-precision op calls (reference:
    debugging.enable_operator_stats_collection — the paired-call form of
    collect_operator_stats)."""
    global _stats_ctx
    if _stats_ctx is not None:
        raise RuntimeError("operator stats collection already enabled")
    _stats_ctx = collect_operator_stats()
    _stats_ctx.__enter__()


def disable_operator_stats_collection():
    """Stop collection and print the op table."""
    global _stats_ctx
    if _stats_ctx is None:
        raise RuntimeError("operator stats collection was not enabled")
    ctx, _stats_ctx = _stats_ctx, None
    ctx.__exit__(None, None, None)


def compare_accuracy(dump_path: str, another_dump_path: str,
                     output_filename: str, loss_scale: float = 1.0,
                     dump_all_tensors: bool = False):
    """Compare two runs' tensor dumps (reference:
    amp/accuracy_compare.py — workbook of fp32-vs-fp16 op outputs).

    Dumps are directories of .npy files with matching names (the
    TensorCheckerConfig dump format here); writes a CSV report of
    per-tensor max-abs and relative differences. ``loss_scale`` descales
    the SECOND dump (the scaled low-precision run) before diffing;
    tensors present in only one dump get explicit missing rows so an
    incomplete run cannot read as a clean comparison.
    """
    import csv
    import os

    import numpy as np

    if dump_all_tensors:
        raise NotImplementedError(
            "dump_all_tensors is a dump-phase option in the reference; "
            "this comparator reads already-dumped directories")

    a_files = {f for f in os.listdir(dump_path) if f.endswith(".npy")}
    b_files = {f for f in os.listdir(another_dump_path) if f.endswith(".npy")}
    rows = []
    for name in sorted(a_files - b_files):
        rows.append([name, "missing-in-second", "", "", "", ""])
    for name in sorted(b_files - a_files):
        rows.append([name, "missing-in-first", "", "", "", ""])
    for name in sorted(a_files & b_files):
        a = np.load(os.path.join(dump_path, name)).astype(np.float64)
        b = np.load(os.path.join(another_dump_path, name)).astype(np.float64)
        b = b / loss_scale
        if a.shape != b.shape:
            rows.append([name, "shape-mismatch", a.shape, b.shape, "", ""])
            continue
        diff = np.abs(a - b)
        denom = np.maximum(np.abs(a), 1e-12)
        rows.append([name, "ok", a.shape, b.shape,
                     float(diff.max()), float((diff / denom).max())])
    with open(output_filename, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["tensor", "status", "shape_a", "shape_b",
                    "max_abs_diff", "max_rel_diff"])
        w.writerows(rows)
    return rows
