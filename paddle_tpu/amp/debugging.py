"""AMP numerical debugging.

Parity: python/paddle/amp/debugging.py (TensorCheckerConfig:157,
enable_tensor_checker:634, check_numerics:339, collect_operator_stats:540).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework import flags
from ..tensor.tensor import Tensor
from .auto_cast import collect_operator_stats  # re-export  # noqa: F401


class DebugMode:
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 4


class TensorCheckerConfig:
    def __init__(self, enable=False, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT, output_dir=None, checked_op_list=None, skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list
        self.skipped_op_list = skipped_op_list


def enable_tensor_checker(config: TensorCheckerConfig):
    if config.enable:
        level = 0 if config.debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT else 1
        flags.set_flags({"FLAGS_check_nan_inf": True, "FLAGS_check_nan_inf_level": level})


def disable_tensor_checker():
    flags.set_flags({"FLAGS_check_nan_inf": False})


def check_numerics(tensor: Tensor, op_type: str = "", var_name: str = "", debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT):
    """Return (num_nan, num_inf, num_zero) and optionally abort."""
    data = tensor._data
    n_nan = int(jnp.sum(jnp.isnan(data)))
    n_inf = int(jnp.sum(jnp.isinf(data)))
    n_zero = int(jnp.sum(data == 0))
    if debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT and (n_nan or n_inf):
        raise FloatingPointError(
            f"check_numerics: {op_type}/{var_name} has {n_nan} NaN, {n_inf} Inf"
        )
    return Tensor(jnp.asarray(n_nan)), Tensor(jnp.asarray(n_inf)), Tensor(jnp.asarray(n_zero))
