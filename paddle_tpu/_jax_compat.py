"""Forward-compat shims: run newer-jax spellings on older jax releases.

The codebase targets current jax (``jax.set_mesh``, ``jax.shard_map``,
``lax.pcast``); older releases carry the same machinery under experimental
names. Each shim installs ONLY when the attribute is missing, so on a
current release :func:`install` is a no-op — and the shims reproduce the
exact call-site semantics this repo uses, not the full new API surface:

- ``jax.set_mesh(mesh)``: the legacy mesh context — ``Mesh`` is itself a
  context manager whose resource env bare ``PartitionSpec``s resolve
  against, which is precisely what ``with jax.set_mesh(mesh):`` provides.
- ``jax.shard_map(f, mesh=?, in_specs=, out_specs=, axis_names=?,
  check_vma=?)``: maps onto ``jax.experimental.shard_map.shard_map`` with
  ``auto = mesh axes - axis_names`` (partial-manual) and
  ``check_rep = check_vma``; ``mesh=None`` resolves from the active mesh
  context like the new API does.
- ``lax.pcast(x, axes, to="varying")``: replication-tracking cast; with
  replication checking off (every repo call site pairs it with
  ``check_vma=False``) it is the identity on the array value.
"""
from __future__ import annotations


def _context_mesh():
    from jax._src.mesh import thread_resources

    mesh = thread_resources.env.physical_mesh
    if mesh.empty:
        raise ValueError(
            "shard_map with mesh=None needs an active mesh context "
            "(jax.set_mesh)")
    return mesh


def install() -> None:
    import jax
    from jax import lax

    if not hasattr(jax, "set_mesh"):
        def set_mesh(mesh):
            return mesh  # Mesh.__enter__ IS the legacy mesh context

        jax.set_mesh = set_mesh

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                      axis_names=None, check_vma=None, **kw):
            m = mesh if mesh is not None else _context_mesh()
            auto = frozenset()
            if axis_names is not None:
                auto = frozenset(m.axis_names) - frozenset(axis_names)
            return _shard_map(
                f, m, in_specs=in_specs, out_specs=out_specs,
                check_rep=bool(check_vma) if check_vma is not None else False,
                auto=auto)

        jax.shard_map = shard_map

    if not hasattr(lax, "pcast"):
        def pcast(x, axis_name, to=None):
            del axis_name, to
            return x

        lax.pcast = pcast
