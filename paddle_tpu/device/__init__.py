"""paddle.device parity (reference: python/paddle/device/__init__.py)."""
from __future__ import annotations

import jax

from ..framework.place import (  # noqa: F401
    CPUPlace,
    CustomPlace,
    Place,
    TPUPlace,
    device_count,
    get_device,
    is_compiled_with_cuda,
    set_device,
)


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_custom_device(device_type: str):
    return device_type in get_all_device_type()


def synchronize(device=None):
    """Block until all queued device work completes (paddle.device.synchronize)."""
    (jax.device_put(0) + 0).block_until_ready()


class Stream:
    """Streams are an XLA-internal concept; the facade exists for API parity."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()

    def wait_event(self, event):
        pass


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


def current_stream(device=None):
    return Stream(device)


def set_stream(stream):
    return stream


def stream_guard(stream):
    import contextlib

    @contextlib.contextmanager
    def guard():
        yield

    return guard()


# ---------------------------------------------------------------------------
# memory stats (reference: paddle.device.cuda.memory_allocated etc. over the
# C++ allocator stats — fluid/memory/; here PJRT's per-device memory_stats)
# ---------------------------------------------------------------------------

def _device_of(device=None):
    devs = jax.devices()
    if device is None:
        return devs[0]
    if isinstance(device, int):
        return devs[device]
    if isinstance(device, str) and ":" in device:
        return devs[int(device.rsplit(":", 1)[1])]
    return devs[0]


def _stat(device, key) -> int:
    stats = _device_of(device).memory_stats() or {}
    return int(stats.get(key, 0))


def memory_allocated(device=None) -> int:
    return _stat(device, "bytes_in_use")


def max_memory_allocated(device=None) -> int:
    return _stat(device, "peak_bytes_in_use")


def memory_reserved(device=None) -> int:
    stats = _device_of(device).memory_stats() or {}
    return int(stats.get("bytes_reserved", stats.get("bytes_in_use", 0)))


def max_memory_reserved(device=None) -> int:
    stats = _device_of(device).memory_stats() or {}
    return int(stats.get("peak_bytes_reserved",
                         stats.get("peak_bytes_in_use", 0)))


def empty_cache():
    """XLA's allocator reuses buffers internally; nothing to flush (parity
    no-op, like the reference on non-auto-growth strategies)."""


class _CudaNamespace:
    """paddle.device.cuda API alias: the accelerator here is the TPU chip,
    but the method surface is kept so reference code runs unchanged."""

    Stream = Stream
    Event = Event

    @staticmethod
    def synchronize(device=None):
        return synchronize(device)

    @staticmethod
    def device_count():
        return device_count()

    memory_allocated = staticmethod(memory_allocated)
    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_reserved = staticmethod(memory_reserved)
    max_memory_reserved = staticmethod(max_memory_reserved)
    empty_cache = staticmethod(empty_cache)

    @staticmethod
    def current_stream(device=None):
        return current_stream(device)

    @staticmethod
    def stream_guard(stream):
        return stream_guard(stream)


cuda = _CudaNamespace()
