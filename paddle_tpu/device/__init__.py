"""paddle.device parity (reference: python/paddle/device/__init__.py)."""
from __future__ import annotations

import jax

from ..framework.place import (  # noqa: F401
    CPUPlace,
    CustomPlace,
    Place,
    TPUPlace,
    device_count,
    get_device,
    is_compiled_with_cuda,
    set_device,
)


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_custom_device(device_type: str):
    return device_type in get_all_device_type()


def synchronize(device=None):
    """Block until all queued device work completes (paddle.device.synchronize)."""
    (jax.device_put(0) + 0).block_until_ready()


class Stream:
    """API-parity facade over XLA's single ordered execution stream.

    XLA owns scheduling: there is exactly ONE logical stream per device, all
    dispatched work is ordered on it, and overlap (compute/collective/DMA)
    is decided by the compiler, not by user streams (reference
    core/stream.py maps to per-device CUDA streams). Consequently
    ``wait_stream``/``wait_event`` ARE correct as ordering no-ops — the
    ordering they would establish already holds. The operations with real
    semantics (synchronize, event query/elapsed-time) do real work below.
    """

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_stream(self, stream):
        # single ordered stream: cross-stream ordering always holds
        return None

    def record_event(self, event=None):
        ev = event or Event()
        ev.record(self)
        return ev

    def wait_event(self, event):
        # single ordered stream: event's work is already ordered before
        # anything dispatched after this call
        return None


import weakref as _weakref

_EVENT_ORDER: list = []  # weakrefs to recorded events, record order
_EVENT_SERIAL = [0]


class Event:
    """Marks a point in the dispatch order.

    ``record`` captures a token after currently-queued work; ``query``
    reports whether that work completed (non-blocking); ``synchronize``
    blocks on it; ``elapsed_time`` between two recorded events times the
    device work between them. Because XLA exposes no device timestamps,
    completion times are observed HOST-side; observation resolves events in
    record order (XLA's single stream guarantees earlier events complete
    first), so timing is accurate while the device is still busy and
    degrades to ~0 only when measurement happens after all work drained
    (reference core/event.py has device timestamps; this is the closest
    single-stream approximation).
    """

    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        self._marker = None
        self._time = None
        self._serial = None

    def record(self, stream=None):
        # a tiny device op AFTER queued work: its readiness == "everything
        # recorded before this point is done". Non-blocking — dispatch is
        # async, so query() can genuinely observe a pending state.
        self._marker = jax.device_put(0) + 0
        self._time = None
        _EVENT_SERIAL[0] += 1
        self._serial = _EVENT_SERIAL[0]
        _EVENT_ORDER.append(_weakref.ref(self))

    def query(self) -> bool:
        if self._marker is None:
            return True
        try:
            return self._marker.is_ready()
        except AttributeError:
            self._marker.block_until_ready()
            return True

    def _stamp(self):
        import time as _time

        if self._marker is not None and self._time is None:
            self._marker.block_until_ready()
            self._time = _time.perf_counter()

    def _completion_time(self):
        # resolve every earlier-recorded live event first: the single
        # ordered stream means their completion precedes ours, so stamps
        # stay monotone in record order
        if self._serial is not None and self._time is None:
            for ref in list(_EVENT_ORDER):
                ev = ref()
                if ev is None or ev._serial > self._serial:
                    if ev is None:
                        _EVENT_ORDER.remove(ref)
                    continue
                ev._stamp()
        self._stamp()
        return self._time

    def synchronize(self):
        self._completion_time()
        synchronize()

    def elapsed_time(self, end_event) -> float:
        """Milliseconds between this event's completion and
        ``end_event``'s (host-observed; see class docstring for limits)."""
        t0, t1 = self._completion_time(), end_event._completion_time()
        if t0 is None or t1 is None:
            raise RuntimeError("both events must be recorded first")
        return max(0.0, (t1 - t0) * 1000.0)


def current_stream(device=None):
    return Stream(device)


def set_stream(stream):
    return stream


def stream_guard(stream):
    import contextlib

    @contextlib.contextmanager
    def guard():
        yield

    return guard()


# ---------------------------------------------------------------------------
# memory stats (reference: paddle.device.cuda.memory_allocated etc. over the
# C++ allocator stats — fluid/memory/; here PJRT's per-device memory_stats)
# ---------------------------------------------------------------------------

def _device_of(device=None):
    devs = jax.devices()
    if device is None:
        return devs[0]
    if isinstance(device, int):
        return devs[device]
    if isinstance(device, str) and ":" in device:
        return devs[int(device.rsplit(":", 1)[1])]
    return devs[0]


def _stat(device, key) -> int:
    stats = _device_of(device).memory_stats() or {}
    return int(stats.get(key, 0))


def memory_allocated(device=None) -> int:
    return _stat(device, "bytes_in_use")


def max_memory_allocated(device=None) -> int:
    return _stat(device, "peak_bytes_in_use")


def memory_reserved(device=None) -> int:
    stats = _device_of(device).memory_stats() or {}
    return int(stats.get("bytes_reserved", stats.get("bytes_in_use", 0)))


def max_memory_reserved(device=None) -> int:
    stats = _device_of(device).memory_stats() or {}
    return int(stats.get("peak_bytes_reserved",
                         stats.get("peak_bytes_in_use", 0)))


def empty_cache():
    """XLA's allocator reuses buffers internally; nothing to flush (parity
    no-op, like the reference on non-auto-growth strategies)."""


class _CudaNamespace:
    """paddle.device.cuda API alias: the accelerator here is the TPU chip,
    but the method surface is kept so reference code runs unchanged."""

    Stream = Stream
    Event = Event

    @staticmethod
    def synchronize(device=None):
        return synchronize(device)

    @staticmethod
    def device_count():
        return device_count()

    memory_allocated = staticmethod(memory_allocated)
    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_reserved = staticmethod(memory_reserved)
    max_memory_reserved = staticmethod(max_memory_reserved)
    empty_cache = staticmethod(empty_cache)

    @staticmethod
    def current_stream(device=None):
        return current_stream(device)

    @staticmethod
    def stream_guard(stream):
        return stream_guard(stream)


cuda = _CudaNamespace()
