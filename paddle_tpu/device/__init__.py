"""paddle.device parity (reference: python/paddle/device/__init__.py)."""
from __future__ import annotations

import jax

from ..framework.place import (  # noqa: F401
    CPUPlace,
    CustomPlace,
    Place,
    TPUPlace,
    device_count,
    get_device,
    is_compiled_with_cuda,
    set_device,
)


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_custom_device(device_type: str):
    return device_type in get_all_device_type()


def synchronize(device=None):
    """Block until all queued device work completes (paddle.device.synchronize)."""
    (jax.device_put(0) + 0).block_until_ready()


class Stream:
    """Streams are an XLA-internal concept; the facade exists for API parity."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()

    def wait_event(self, event):
        pass


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


def current_stream(device=None):
    return Stream(device)


def set_stream(stream):
    return stream


def stream_guard(stream):
    import contextlib

    @contextlib.contextmanager
    def guard():
        yield

    return guard()
