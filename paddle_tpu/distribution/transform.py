"""Bijective transforms for TransformedDistribution.

Parity target: python/paddle/distribution/transform.py (AbsTransform,
AffineTransform, ChainTransform, ExpTransform, IndependentTransform,
PowerTransform, ReshapeTransform, SigmoidTransform, SoftmaxTransform,
StackTransform, StickBreakingTransform, TanhTransform). TPU-native: each
transform is a pure jnp map with analytic log-det-jacobian, so chains remain
jit/grad-composable end to end.
"""
from __future__ import annotations

import functools
import operator

import jax
import jax.numpy as jnp

from .distribution import _as_jnp, _wrap

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]


class Type:
    BIJECTION = "bijection"
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"

    @classmethod
    def is_injective(cls, t):
        return t in (cls.BIJECTION, cls.INJECTION)


class Transform:
    _type = Type.INJECTION

    @property
    def _domain_event_dim(self):
        return 0

    @property
    def _codomain_event_dim(self):
        return 0

    def forward(self, x):
        return _wrap(self._forward(_as_jnp(x)))

    def inverse(self, y):
        return _wrap(self._inverse(_as_jnp(y)))

    def forward_log_det_jacobian(self, x):
        return _wrap(self._forward_log_det_jacobian(_as_jnp(x)))

    def inverse_log_det_jacobian(self, y):
        y = _as_jnp(y)
        return _wrap(-self._forward_log_det_jacobian(self._inverse(y)))

    def forward_shape(self, shape):
        return list(shape)

    def inverse_shape(self, shape):
        return list(shape)

    # subclass hooks (pure jnp)
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AbsTransform(Transform):
    _type = Type.SURJECTION

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # right-inverse (positive branch), matching reference

    def _forward_log_det_jacobian(self, x):
        return jnp.zeros_like(x)


class AffineTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, loc, scale):
        self.loc = _as_jnp(loc)
        self.scale = _as_jnp(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), jnp.shape(x))


class ExpTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, power):
        self.power = _as_jnp(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh(x)^2) = 2 (log2 - x - softplus(-2x))
        return 2.0 * (jnp.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    _type = Type.OTHER  # not injective

    @property
    def _domain_event_dim(self):
        return 1

    @property
    def _codomain_event_dim(self):
        return 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)


class StickBreakingTransform(Transform):
    """R^k -> interior of (k+1)-simplex (reference transform.py StickBreaking)."""

    _type = Type.BIJECTION

    @property
    def _domain_event_dim(self):
        return 1

    @property
    def _codomain_event_dim(self):
        return 1

    def _forward(self, x):
        offset = x.shape[-1] + 1 - jnp.arange(1, x.shape[-1] + 1)
        z = jax.nn.sigmoid(x - jnp.log(offset.astype(x.dtype)))
        z_cumprod = jnp.cumprod(1 - z, axis=-1)
        pad_z = jnp.concatenate([z, jnp.ones_like(z[..., :1])], -1)
        pad_cum = jnp.concatenate([jnp.ones_like(z[..., :1]), z_cumprod], -1)
        return pad_z * pad_cum

    def _inverse(self, y):
        y_crop = y[..., :-1]
        sf = 1 - jnp.cumsum(y_crop, axis=-1)
        offset = y_crop.shape[-1] + 1 - jnp.arange(1, y_crop.shape[-1] + 1)
        return (jnp.log(y_crop) - jnp.log(sf)
                + jnp.log(offset.astype(y.dtype)))

    def _forward_log_det_jacobian(self, x):
        y = self._forward(x)
        offset = x.shape[-1] + 1 - jnp.arange(1, x.shape[-1] + 1)
        xs = x - jnp.log(offset.astype(x.dtype))
        # d y_k / d x_k = z_k (1-z_k) prod_{j<k}(1-z_j); with
        # 1 - sigmoid(t) = exp(-t) sigmoid(t) this telescopes to:
        return jnp.sum(-xs + jax.nn.log_sigmoid(xs) + jnp.log(y[..., :-1]), -1)

    def forward_shape(self, shape):
        return list(shape[:-1]) + [shape[-1] + 1]

    def inverse_shape(self, shape):
        return list(shape[:-1]) + [shape[-1] - 1]


class ReshapeTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        if functools.reduce(operator.mul, self.in_event_shape, 1) != \
                functools.reduce(operator.mul, self.out_event_shape, 1):
            raise ValueError("in/out event sizes must match")

    @property
    def _domain_event_dim(self):
        return len(self.in_event_shape)

    @property
    def _codomain_event_dim(self):
        return len(self.out_event_shape)

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)


class IndependentTransform(Transform):
    """Promote trailing batch dims of `base` to event dims (sums the ldj)."""

    def __init__(self, base, reinterpreted_batch_rank: int):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        self._type = base._type

    @property
    def _domain_event_dim(self):
        return self.base._domain_event_dim + self.reinterpreted_batch_rank

    @property
    def _codomain_event_dim(self):
        return self.base._codomain_event_dim + self.reinterpreted_batch_rank

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        ldj = self.base._forward_log_det_jacobian(x)
        axes = tuple(range(-self.reinterpreted_batch_rank, 0))
        return jnp.sum(ldj, axis=axes) if axes else ldj

    def forward_shape(self, shape):
        return self.base.forward_shape(shape)

    def inverse_shape(self, shape):
        return self.base.inverse_shape(shape)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)
        self._type = (Type.BIJECTION
                      if all(t._type == Type.BIJECTION for t in self.transforms)
                      else Type.INJECTION)

    @property
    def _domain_event_dim(self):
        return max((t._domain_event_dim for t in self.transforms), default=0)

    @property
    def _codomain_event_dim(self):
        return max((t._codomain_event_dim for t in self.transforms), default=0)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        total = None
        for t in self.transforms:
            ldj = t._forward_log_det_jacobian(x)
            # reduce to the chain's event granularity
            extra = self._codomain_event_dim - t._codomain_event_dim
            if extra > 0:
                ldj = jnp.sum(ldj, axis=tuple(range(-extra, 0)))
            total = ldj if total is None else total + ldj
            x = t._forward(x)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return list(shape)

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return list(shape)


class StackTransform(Transform):
    """Apply a list of transforms to slices along `axis`."""

    def __init__(self, transforms, axis: int = 0):
        self.transforms = list(transforms)
        self.axis = int(axis)
        self._type = (Type.BIJECTION
                      if all(t._type == Type.BIJECTION for t in self.transforms)
                      else Type.INJECTION)

    def _map(self, fn_name, v):
        parts = jnp.split(v, len(self.transforms), axis=self.axis)
        outs = [getattr(t, fn_name)(p.squeeze(self.axis))
                for t, p in zip(self.transforms, parts)]
        return jnp.stack(outs, axis=self.axis)

    def _forward(self, x):
        return self._map("_forward", x)

    def _inverse(self, y):
        return self._map("_inverse", y)

    def _forward_log_det_jacobian(self, x):
        return self._map("_forward_log_det_jacobian", x)
