"""paddle.distribution parity: probability distributions over jnp densities.

Parity target: python/paddle/distribution/__init__.py (18 families +
transforms + KL registry). See distribution.py / families.py / transform.py /
kl.py for the TPU-native design notes.
"""
from . import transform
from .distribution import Distribution, ExponentialFamily
from .families import (
    Bernoulli, Beta, Binomial, Categorical, Cauchy, ContinuousBernoulli,
    Dirichlet, Exponential, Gamma, Geometric, Gumbel, Laplace, LogNormal,
    Multinomial, MultivariateNormal, Normal, Poisson, Uniform,
)
from .kl import kl_divergence, register_kl
from .transform import (
    AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform, SigmoidTransform,
    SoftmaxTransform, StackTransform, StickBreakingTransform, TanhTransform,
    Transform,
)
from .transformed_distribution import Independent, TransformedDistribution

__all__ = [
    "Distribution", "ExponentialFamily", "Bernoulli", "Beta", "Binomial",
    "Categorical", "Cauchy", "ContinuousBernoulli", "Dirichlet", "Exponential",
    "Gamma", "Geometric", "Gumbel", "Independent", "Laplace", "LogNormal",
    "Multinomial", "MultivariateNormal", "Normal", "Poisson",
    "TransformedDistribution", "Uniform", "kl_divergence", "register_kl",
    "transform", "Transform", "AbsTransform", "AffineTransform",
    "ChainTransform", "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]
