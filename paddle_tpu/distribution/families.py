"""Concrete distribution families.

Parity target: the per-family modules under python/paddle/distribution/
(normal.py, uniform.py, bernoulli.py, categorical.py, beta.py, dirichlet.py,
exponential.py, gamma.py, geometric.py, gumbel.py, laplace.py, lognormal.py,
multinomial.py, multivariate_normal.py, poisson.py, binomial.py, cauchy.py,
continuous_bernoulli.py). TPU-native: densities are jnp formulas (jit/vmap
composable), sampling uses jax.random with keys from the framework Generator,
reparameterized rsample wherever the underlying sampler is differentiable.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from .distribution import Distribution, ExponentialFamily, _as_jnp, _next_key, _wrap

__all__ = [
    "Normal", "Uniform", "Bernoulli", "ContinuousBernoulli", "Categorical",
    "Beta", "Binomial", "Cauchy", "Dirichlet", "Exponential", "Gamma",
    "Geometric", "Gumbel", "Laplace", "LogNormal", "Multinomial",
    "MultivariateNormal", "Poisson",
]


def _broadcast_shapes(*arrs):
    return jnp.broadcast_shapes(*[jnp.shape(a) for a in arrs])


class Normal(ExponentialFamily):
    _PARAM_ATTRS = ("loc", "scale")

    def __init__(self, loc, scale, name=None):
        self._store_params(loc=loc, scale=scale)
        self.loc = _as_jnp(loc)
        self.scale = _as_jnp(scale)
        super().__init__(batch_shape=_broadcast_shapes(self.loc, self.scale))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(self.scale**2, self._batch_shape))

    def rsample(self, shape=()):
        eps = jax.random.normal(_next_key(), self._extend_shape(shape), self.loc.dtype)
        return _wrap(self.loc + self.scale * eps)

    def log_prob(self, value):
        v = self._validate_value(value)
        var = self.scale**2
        return _wrap(-((v - self.loc) ** 2) / (2 * var)
                     - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        h = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
        return _wrap(jnp.broadcast_to(h, self._batch_shape))

    def cdf(self, value):
        v = self._validate_value(value)
        return _wrap(0.5 * (1 + jsp.erf((v - self.loc) / (self.scale * math.sqrt(2)))))

    def icdf(self, value):
        v = self._validate_value(value)
        return _wrap(self.loc + self.scale * math.sqrt(2) * jsp.erfinv(2 * v - 1))

    @property
    def _natural_parameters(self):
        return (self.loc / (self.scale**2), -0.5 / (self.scale**2))

    def _log_normalizer(self, x, y):
        return -0.25 * x**2 / y + 0.5 * jnp.log(-math.pi / y)

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def probs(self, value):  # paddle legacy alias
        return self.prob(value)


class LogNormal(Normal):
    """exp(Normal(loc, scale)); shares Normal's base measure via transform."""

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(jnp.exp(self.loc + self.scale**2 / 2),
                                      self._batch_shape))

    @property
    def variance(self):
        s2 = self.scale**2
        return _wrap(jnp.broadcast_to((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2),
                                      self._batch_shape))

    def rsample(self, shape=()):
        return _wrap(jnp.exp(_as_jnp(super().rsample(shape))))

    def log_prob(self, value):
        v = self._validate_value(value)
        logv = jnp.log(v)
        return _wrap(_as_jnp(super().log_prob(logv)) - logv)

    def entropy(self):
        return _wrap(_as_jnp(super().entropy()) + self.loc)

    def cdf(self, value):
        return super().cdf(jnp.log(self._validate_value(value)))


class Uniform(Distribution):
    _PARAM_ATTRS = ("low", "high")

    def __init__(self, low, high, name=None):
        self._store_params(low=low, high=high)
        self.low = _as_jnp(low)
        self.high = _as_jnp(high)
        super().__init__(batch_shape=_broadcast_shapes(self.low, self.high))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to((self.low + self.high) / 2, self._batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to((self.high - self.low) ** 2 / 12, self._batch_shape))

    def rsample(self, shape=()):
        u = jax.random.uniform(_next_key(), self._extend_shape(shape), self.low.dtype)
        return _wrap(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = self._validate_value(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return _wrap(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return _wrap(jnp.broadcast_to(jnp.log(self.high - self.low), self._batch_shape))

    def cdf(self, value):
        v = self._validate_value(value)
        return _wrap(jnp.clip((v - self.low) / (self.high - self.low), 0.0, 1.0))

    def icdf(self, value):
        v = self._validate_value(value)
        return _wrap(self.low + v * (self.high - self.low))


class Bernoulli(ExponentialFamily):
    _PARAM_ATTRS = ("probs", "logits")

    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            self._store_params(probs=probs)
            self._set_params(probs=_as_jnp(probs))
        else:
            self._store_params(logits=logits)
            self._set_params(logits=_as_jnp(logits))
        super().__init__(batch_shape=jnp.shape(self.probs))

    def _set_params(self, probs=None, logits=None):
        if probs is not None:
            self.probs = probs
            self.logits = jnp.log(probs) - jnp.log1p(-probs)
        else:
            self.logits = logits
            self.probs = jax.nn.sigmoid(logits)

    @property
    def mean(self):
        return _wrap(self.probs)

    @property
    def variance(self):
        return _wrap(self.probs * (1 - self.probs))

    def sample(self, shape=()):
        s = jax.random.bernoulli(_next_key(), self.probs, self._extend_shape(shape))
        return _wrap(s.astype(self.probs.dtype))

    def rsample(self, shape=(), temperature=1.0):
        """Gumbel-softmax relaxation (reference bernoulli.py rsample)."""
        u = jax.random.uniform(
            _next_key(), self._extend_shape(shape), self.probs.dtype,
            minval=1e-6, maxval=1.0 - 1e-6)
        logistic = jnp.log(u) - jnp.log1p(-u)
        return _wrap(jax.nn.sigmoid((self.logits + logistic) / temperature))

    def log_prob(self, value):
        v = self._validate_value(value).astype(self.probs.dtype)
        # -softplus(-logits)*v - softplus(logits)*(1-v), numerically stable
        return _wrap(v * -jax.nn.softplus(-self.logits)
                     + (1 - v) * -jax.nn.softplus(self.logits))

    def entropy(self):
        p = self.probs
        return _wrap(-(jnp.where(p > 0, p * jnp.log(p), 0.0)
                       + jnp.where(p < 1, (1 - p) * jnp.log1p(-p), 0.0)))

    def cdf(self, value):
        v = self._validate_value(value)
        out = jnp.where(v < 0, 0.0, jnp.where(v < 1, 1 - self.probs, 1.0))
        return _wrap(out.astype(self.probs.dtype))

    @property
    def _natural_parameters(self):
        return (self.logits,)

    def _log_normalizer(self, x):
        return jax.nn.softplus(x)

    @property
    def _mean_carrier_measure(self):
        return 0.0


class ContinuousBernoulli(Distribution):
    """CB(lambda) — continuous relaxation on [0,1] (reference continuous_bernoulli.py)."""

    _PARAM_ATTRS = ("probs",)

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self._store_params(probs=probs)
        self.probs = _as_jnp(probs)
        self._lims = lims
        super().__init__(batch_shape=jnp.shape(self.probs))

    def _outside(self):
        lo, hi = self._lims
        return (self.probs < lo) | (self.probs > hi)

    def _cut_probs(self):
        lo, hi = self._lims
        return jnp.where(self._outside(), self.probs, lo * jnp.ones_like(self.probs))

    @property
    def mean(self):
        cp = self._cut_probs()
        m = cp / (2 * cp - 1) + 1 / (2 * jnp.arctanh(1 - 2 * cp))
        return _wrap(jnp.where(self._outside(), m, 0.5 + (self.probs - 0.5) / 3))

    @property
    def variance(self):
        cp = self._cut_probs()
        v = cp * (cp - 1) / (1 - 2 * cp) ** 2 + 1 / (2 * jnp.arctanh(1 - 2 * cp)) ** 2
        return _wrap(jnp.where(self._outside(), v, 1 / 12 - (self.probs - 0.5) ** 2 / 3))

    def rsample(self, shape=()):
        u = jax.random.uniform(_next_key(), self._extend_shape(shape),
                               self.probs.dtype, minval=1e-6, maxval=1 - 1e-6)
        return self.icdf(u)

    def log_prob(self, value):
        v = self._validate_value(value)
        bern = v * jnp.log(jnp.clip(self.probs, 1e-6)) \
            + (1 - v) * jnp.log(jnp.clip(1 - self.probs, 1e-6))
        return _wrap(bern + self._log_const())

    def _log_const(self):
        cp = self._cut_probs()
        out = jnp.log(2 * jnp.abs(jnp.arctanh(1 - 2 * cp)) / jnp.abs(1 - 2 * cp))
        taylor = math.log(2.0) + 4 / 3 * (self.probs - 0.5) ** 2 \
            + 104 / 45 * (self.probs - 0.5) ** 4
        return jnp.where(self._outside(), out, taylor)

    def cdf(self, value):
        v = self._validate_value(value)
        cp = self._cut_probs()
        unnorm = (cp**v * (1 - cp) ** (1 - v) + cp - 1) / (2 * cp - 1)
        return _wrap(jnp.clip(jnp.where(self._outside(), unnorm, v), 0.0, 1.0))

    def icdf(self, value):
        v = self._validate_value(value)
        cp = self._cut_probs()
        num = jnp.log1p(v * (2 * cp - 1) / (1 - cp))
        den = jnp.log(cp) - jnp.log1p(-cp)
        return _wrap(jnp.where(self._outside(), num / den, v))

    def entropy(self):
        # E[-log p(x)] = -(lambda-dependent closed form); use mean identity
        m = _as_jnp(self.mean)
        p = jnp.clip(self.probs, 1e-6, 1 - 1e-6)
        return _wrap(-(m * jnp.log(p) + (1 - m) * jnp.log1p(-p)) - self._log_const())


class Categorical(Distribution):
    _PARAM_ATTRS = ("logits", "probs")

    def __init__(self, logits=None, probs=None, name=None):
        # paddle's Categorical(logits) accepts unnormalized nonneg weights OR logits;
        # we follow the reference: the first positional arg is `logits`.
        if logits is not None:
            self._store_params(logits=logits)
            self._set_params(logits=_as_jnp(logits))
        else:
            self._store_params(probs=probs)
            self._set_params(probs=_as_jnp(probs))
        super().__init__(batch_shape=jnp.shape(self.probs)[:-1])
        self._num_events = jnp.shape(self.probs)[-1]

    def _set_params(self, logits=None, probs=None):
        if logits is not None:
            self.logits = logits
            self.probs = jax.nn.softmax(logits, axis=-1)
        else:
            self.probs = probs / jnp.sum(probs, -1, keepdims=True)
            self.logits = jnp.log(jnp.clip(self.probs, 1e-38))

    @property
    def mean(self):
        raise NotImplementedError("Categorical has no mean")

    def sample(self, shape=()):
        out_shape = tuple(shape) + self._batch_shape
        s = jax.random.categorical(_next_key(), self.logits, axis=-1, shape=out_shape)
        return _wrap(s.astype(jnp.int64 if jax.config.jax_enable_x64 else jnp.int32))

    def log_prob(self, value):
        v = self._validate_value(value).astype(jnp.int32)
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return _wrap(jnp.take_along_axis(
            jnp.broadcast_to(logp, jnp.shape(v) + (self._num_events,)),
            v[..., None], axis=-1)[..., 0])

    def probs_of(self, value):
        return _wrap(jnp.exp(_as_jnp(self.log_prob(value))))

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return _wrap(-jnp.sum(jnp.exp(logp) * logp, axis=-1))


class Beta(ExponentialFamily):
    _PARAM_ATTRS = ("alpha", "beta")

    def __init__(self, alpha, beta, name=None):
        self._store_params(alpha=alpha, beta=beta)
        self.alpha = _as_jnp(alpha)
        self.beta = _as_jnp(beta)
        super().__init__(batch_shape=_broadcast_shapes(self.alpha, self.beta))

    @property
    def mean(self):
        return _wrap(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return _wrap(self.alpha * self.beta / (s**2 * (s + 1)))

    def rsample(self, shape=()):
        return _wrap(jax.random.beta(_next_key(), self.alpha, self.beta,
                                     self._extend_shape(shape)))

    def log_prob(self, value):
        v = self._validate_value(value)
        return _wrap((self.alpha - 1) * jnp.log(v) + (self.beta - 1) * jnp.log1p(-v)
                     - _log_beta(self.alpha, self.beta))

    def entropy(self):
        a, b = self.alpha, self.beta
        return _wrap(_log_beta(a, b) - (a - 1) * jsp.digamma(a)
                     - (b - 1) * jsp.digamma(b)
                     + (a + b - 2) * jsp.digamma(a + b))


def _log_beta(a, b):
    return jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(a + b)


class Dirichlet(ExponentialFamily):
    _PARAM_ATTRS = ("concentration",)

    def __init__(self, concentration, name=None):
        self._store_params(concentration=concentration)
        self.concentration = _as_jnp(concentration)
        shape = jnp.shape(self.concentration)
        super().__init__(batch_shape=shape[:-1], event_shape=shape[-1:])

    @property
    def mean(self):
        return _wrap(self.concentration
                     / jnp.sum(self.concentration, -1, keepdims=True))

    @property
    def variance(self):
        a0 = jnp.sum(self.concentration, -1, keepdims=True)
        m = self.concentration / a0
        return _wrap(m * (1 - m) / (a0 + 1))

    def rsample(self, shape=()):
        # jax.random.dirichlet broadcasts alpha over leading sample dims
        out = jax.random.dirichlet(
            _next_key(), self.concentration,
            tuple(shape) + self._batch_shape)
        return _wrap(out)

    def log_prob(self, value):
        v = self._validate_value(value)
        a = self.concentration
        return _wrap(jnp.sum((a - 1) * jnp.log(v), -1)
                     + jsp.gammaln(jnp.sum(a, -1))
                     - jnp.sum(jsp.gammaln(a), -1))

    def entropy(self):
        a = self.concentration
        a0 = jnp.sum(a, -1)
        k = a.shape[-1]
        return _wrap(jnp.sum(jsp.gammaln(a), -1) - jsp.gammaln(a0)
                     + (a0 - k) * jsp.digamma(a0)
                     - jnp.sum((a - 1) * jsp.digamma(a), -1))


class Exponential(ExponentialFamily):
    _PARAM_ATTRS = ("rate",)

    def __init__(self, rate, name=None):
        self._store_params(rate=rate)
        self.rate = _as_jnp(rate)
        super().__init__(batch_shape=jnp.shape(self.rate))

    @property
    def mean(self):
        return _wrap(1.0 / self.rate)

    @property
    def variance(self):
        return _wrap(1.0 / self.rate**2)

    def rsample(self, shape=()):
        e = jax.random.exponential(_next_key(), self._extend_shape(shape),
                                   self.rate.dtype)
        return _wrap(e / self.rate)

    def log_prob(self, value):
        v = self._validate_value(value)
        return _wrap(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return _wrap(1.0 - jnp.log(self.rate))

    def cdf(self, value):
        v = self._validate_value(value)
        return _wrap(-jnp.expm1(-self.rate * v))

    def icdf(self, value):
        v = self._validate_value(value)
        return _wrap(-jnp.log1p(-v) / self.rate)


class Gamma(ExponentialFamily):
    _PARAM_ATTRS = ("concentration", "rate")

    def __init__(self, concentration, rate, name=None):
        self._store_params(concentration=concentration, rate=rate)
        self.concentration = _as_jnp(concentration)
        self.rate = _as_jnp(rate)
        super().__init__(batch_shape=_broadcast_shapes(self.concentration, self.rate))

    @property
    def mean(self):
        return _wrap(self.concentration / self.rate)

    @property
    def variance(self):
        return _wrap(self.concentration / self.rate**2)

    def rsample(self, shape=()):
        g = jax.random.gamma(_next_key(), self.concentration,
                             self._extend_shape(shape))
        return _wrap(g / self.rate)

    def log_prob(self, value):
        v = self._validate_value(value)
        a, b = self.concentration, self.rate
        return _wrap(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v - jsp.gammaln(a))

    def entropy(self):
        a, b = self.concentration, self.rate
        return _wrap(a - jnp.log(b) + jsp.gammaln(a) + (1 - a) * jsp.digamma(a))


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k in {0,1,2,...} (reference geometric.py)."""

    _PARAM_ATTRS = ("probs",)

    def __init__(self, probs, name=None):
        self._store_params(probs=probs)
        self.probs = _as_jnp(probs)
        super().__init__(batch_shape=jnp.shape(self.probs))

    @property
    def mean(self):
        return _wrap((1 - self.probs) / self.probs)

    @property
    def variance(self):
        return _wrap((1 - self.probs) / self.probs**2)

    @property
    def stddev(self):
        return _wrap(jnp.sqrt((1 - self.probs)) / self.probs)

    def sample(self, shape=()):
        u = jax.random.uniform(_next_key(), self._extend_shape(shape),
                               self.probs.dtype, minval=1e-7)
        return _wrap(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs)))

    rsample = sample

    def log_prob(self, value):
        v = self._validate_value(value)
        return _wrap(v * jnp.log1p(-self.probs) + jnp.log(self.probs))

    def pmf(self, k):
        return _wrap(jnp.exp(_as_jnp(self.log_prob(k))))

    def log_pmf(self, k):
        return self.log_prob(k)

    def entropy(self):
        p = self.probs
        return _wrap(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)) / p)

    def cdf(self, value):
        v = self._validate_value(value)
        return _wrap(1 - jnp.power(1 - self.probs, jnp.floor(v) + 1))


class Gumbel(Distribution):
    _PARAM_ATTRS = ("loc", "scale")

    def __init__(self, loc, scale, name=None):
        self._store_params(loc=loc, scale=scale)
        self.loc = _as_jnp(loc)
        self.scale = _as_jnp(scale)
        super().__init__(batch_shape=_broadcast_shapes(self.loc, self.scale))

    @property
    def mean(self):
        return _wrap(self.loc + self.scale * jnp.euler_gamma)

    @property
    def variance(self):
        return _wrap(math.pi**2 / 6 * self.scale**2)

    @property
    def stddev(self):
        return _wrap(math.pi / math.sqrt(6) * self.scale)

    def rsample(self, shape=()):
        g = jax.random.gumbel(_next_key(), self._extend_shape(shape), self.loc.dtype)
        return _wrap(self.loc + self.scale * g)

    def log_prob(self, value):
        v = self._validate_value(value)
        z = (v - self.loc) / self.scale
        return _wrap(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def entropy(self):
        return _wrap(jnp.log(self.scale) + 1 + jnp.euler_gamma
                     + jnp.zeros(self._batch_shape))

    def cdf(self, value):
        v = self._validate_value(value)
        return _wrap(jnp.exp(-jnp.exp(-(v - self.loc) / self.scale)))


class Laplace(Distribution):
    _PARAM_ATTRS = ("loc", "scale")

    def __init__(self, loc, scale, name=None):
        self._store_params(loc=loc, scale=scale)
        self.loc = _as_jnp(loc)
        self.scale = _as_jnp(scale)
        super().__init__(batch_shape=_broadcast_shapes(self.loc, self.scale))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return _wrap(2 * self.scale**2)

    @property
    def stddev(self):
        return _wrap(math.sqrt(2) * self.scale)

    def rsample(self, shape=()):
        l = jax.random.laplace(_next_key(), self._extend_shape(shape), self.loc.dtype)
        return _wrap(self.loc + self.scale * l)

    def log_prob(self, value):
        v = self._validate_value(value)
        return _wrap(-jnp.abs(v - self.loc) / self.scale
                     - jnp.log(2 * self.scale))

    def entropy(self):
        return _wrap(1 + jnp.log(2 * self.scale) + jnp.zeros(self._batch_shape))

    def cdf(self, value):
        v = self._validate_value(value)
        z = (v - self.loc) / self.scale
        return _wrap(0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z)))

    def icdf(self, value):
        v = self._validate_value(value)
        t = v - 0.5
        return _wrap(self.loc - self.scale * jnp.sign(t) * jnp.log1p(-2 * jnp.abs(t)))


class Cauchy(Distribution):
    _PARAM_ATTRS = ("loc", "scale")

    def __init__(self, loc, scale, name=None):
        self._store_params(loc=loc, scale=scale)
        self.loc = _as_jnp(loc)
        self.scale = _as_jnp(scale)
        super().__init__(batch_shape=_broadcast_shapes(self.loc, self.scale))

    @property
    def mean(self):
        raise ValueError("Cauchy has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy has no variance")

    def rsample(self, shape=()):
        c = jax.random.cauchy(_next_key(), self._extend_shape(shape), self.loc.dtype)
        return _wrap(self.loc + self.scale * c)

    def log_prob(self, value):
        v = self._validate_value(value)
        return _wrap(-math.log(math.pi) - jnp.log(self.scale)
                     - jnp.log1p(((v - self.loc) / self.scale) ** 2))

    def entropy(self):
        return _wrap(jnp.log(4 * math.pi * self.scale) + jnp.zeros(self._batch_shape))

    def cdf(self, value):
        v = self._validate_value(value)
        return _wrap(jnp.arctan((v - self.loc) / self.scale) / math.pi + 0.5)

    def icdf(self, value):
        v = self._validate_value(value)
        return _wrap(self.loc + self.scale * jnp.tan(math.pi * (v - 0.5)))


class Poisson(ExponentialFamily):
    _PARAM_ATTRS = ("rate",)

    def __init__(self, rate, name=None):
        self._store_params(rate=rate)
        self.rate = _as_jnp(rate)
        super().__init__(batch_shape=jnp.shape(self.rate))

    @property
    def mean(self):
        return _wrap(self.rate)

    @property
    def variance(self):
        return _wrap(self.rate)

    def sample(self, shape=()):
        s = jax.random.poisson(_next_key(), self.rate, self._extend_shape(shape))
        return _wrap(s.astype(self.rate.dtype))

    rsample = sample

    def log_prob(self, value):
        v = self._validate_value(value)
        return _wrap(v * jnp.log(self.rate) - self.rate - jsp.gammaln(v + 1))

    def entropy(self):
        # series approximation consistent with reference (moment expansion)
        r = self.rate
        return _wrap(0.5 * jnp.log(2 * math.pi * math.e * r)
                     - 1 / (12 * r) - 1 / (24 * r**2))


class Binomial(Distribution):
    _PARAM_ATTRS = ("probs",)

    def __init__(self, total_count, probs, name=None):
        self._store_params(probs=probs)
        self.total_count = _as_jnp(total_count)
        self.probs = _as_jnp(probs)
        super().__init__(batch_shape=_broadcast_shapes(self.total_count, self.probs))

    @property
    def mean(self):
        return _wrap(self.total_count * self.probs)

    @property
    def variance(self):
        return _wrap(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=()):
        s = jax.random.binomial(_next_key(), self.total_count, self.probs,
                                shape=self._extend_shape(shape))
        return _wrap(s.astype(self.probs.dtype))

    rsample = sample

    def log_prob(self, value):
        v = self._validate_value(value)
        n, p = self.total_count, self.probs
        log_comb = (jsp.gammaln(n + 1) - jsp.gammaln(v + 1)
                    - jsp.gammaln(n - v + 1))
        return _wrap(log_comb + v * jnp.log(p) + (n - v) * jnp.log1p(-p))

    def entropy(self):
        # exact by enumeration over support (total_count must be concrete)
        n = int(jnp.max(self.total_count))
        k = jnp.arange(n + 1, dtype=self.probs.dtype)
        k = k.reshape((n + 1,) + (1,) * len(self._batch_shape))
        lp = _as_jnp(self.log_prob(k))
        valid = k <= self.total_count
        return _wrap(-jnp.sum(jnp.where(valid, jnp.exp(lp) * lp, 0.0), axis=0))


class Multinomial(Distribution):
    _PARAM_ATTRS = ("probs",)

    def __init__(self, total_count, probs, name=None):
        self._store_params(probs=probs)
        self.total_count = int(total_count)
        self._set_params(probs=_as_jnp(probs))
        shape = jnp.shape(self.probs)
        super().__init__(batch_shape=shape[:-1], event_shape=shape[-1:])

    def _set_params(self, probs):
        self.probs = probs / jnp.sum(probs, -1, keepdims=True)

    @property
    def mean(self):
        return _wrap(self.total_count * self.probs)

    @property
    def variance(self):
        return _wrap(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=()):
        logits = jnp.log(jnp.clip(self.probs, 1e-38))
        k = self._event_shape[0]
        draws = jax.random.categorical(
            _next_key(), logits, axis=-1,
            shape=(self.total_count,) + tuple(shape) + self._batch_shape)
        onehot = jax.nn.one_hot(draws, k, dtype=self.probs.dtype)
        return _wrap(jnp.sum(onehot, axis=0))

    rsample = sample

    def log_prob(self, value):
        v = self._validate_value(value)
        logits = jnp.log(jnp.clip(self.probs, 1e-38))
        return _wrap(jsp.gammaln(jnp.sum(v, -1) + 1)
                     - jnp.sum(jsp.gammaln(v + 1), -1)
                     + jnp.sum(v * logits, -1))

    def entropy(self):
        # upper-bound via sum of binomial marginal entropies (exact enumeration
        # per category; the joint correction term is omitted as in practice)
        p = jnp.clip(self.probs, 1e-9, 1 - 1e-9)
        b = Binomial(jnp.full(p.shape, self.total_count, p.dtype), p)
        return _wrap(jnp.sum(_as_jnp(b.entropy()), -1))


class MultivariateNormal(Distribution):
    _PARAM_ATTRS = ("loc", "_scale_tril")

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        if sum(x is not None for x in
               (covariance_matrix, precision_matrix, scale_tril)) != 1:
            raise ValueError("pass exactly one of covariance_matrix/"
                             "precision_matrix/scale_tril")
        self._mvn_form = ("scale_tril" if scale_tril is not None else
                          "cov" if covariance_matrix is not None else "prec")
        mat = (scale_tril if scale_tril is not None else
               covariance_matrix if covariance_matrix is not None
               else precision_matrix)
        self._store_params(loc=loc, _mvn_mat=mat)
        self._set_params(loc=_as_jnp(loc), _mvn_mat=_as_jnp(mat))
        d = jnp.shape(self.loc)[-1]
        batch = jnp.broadcast_shapes(jnp.shape(self.loc)[:-1],
                                     jnp.shape(self._scale_tril)[:-2])
        super().__init__(batch_shape=batch, event_shape=(d,))

    def _set_params(self, loc=None, _mvn_mat=None):
        if loc is not None:
            self.loc = loc
        if _mvn_mat is not None:
            if self._mvn_form == "scale_tril":
                self._scale_tril = _mvn_mat
            elif self._mvn_form == "cov":
                self._scale_tril = jnp.linalg.cholesky(_mvn_mat)
            else:
                self._scale_tril = jnp.linalg.cholesky(jnp.linalg.inv(_mvn_mat))

    @property
    def scale_tril(self):
        return _wrap(self._scale_tril)

    @property
    def covariance_matrix(self):
        L = self._scale_tril
        return _wrap(L @ jnp.swapaxes(L, -1, -2))

    @property
    def precision_matrix(self):
        return _wrap(jnp.linalg.inv(_as_jnp(self.covariance_matrix)))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self._batch_shape + self._event_shape))

    @property
    def variance(self):
        var = jnp.sum(self._scale_tril**2, axis=-1)
        return _wrap(jnp.broadcast_to(var, self._batch_shape + self._event_shape))

    def rsample(self, shape=()):
        eps = jax.random.normal(_next_key(), self._extend_shape(shape),
                                self.loc.dtype)
        return _wrap(self.loc + jnp.einsum("...ij,...j->...i", self._scale_tril, eps))

    def log_prob(self, value):
        v = self._validate_value(value)
        diff = v - self.loc
        y = jax.scipy.linalg.solve_triangular(
            self._scale_tril, diff[..., None], lower=True)[..., 0]
        half_log_det = jnp.sum(
            jnp.log(jnp.diagonal(self._scale_tril, axis1=-2, axis2=-1)), -1)
        d = self._event_shape[0]
        return _wrap(-0.5 * jnp.sum(y**2, -1) - half_log_det
                     - 0.5 * d * math.log(2 * math.pi))

    def entropy(self):
        half_log_det = jnp.sum(
            jnp.log(jnp.diagonal(self._scale_tril, axis1=-2, axis2=-1)), -1)
        d = self._event_shape[0]
        return _wrap(jnp.broadcast_to(
            0.5 * d * (1 + math.log(2 * math.pi)) + half_log_det,
            self._batch_shape))
