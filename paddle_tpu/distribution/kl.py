"""KL divergence registry.

Parity target: python/paddle/distribution/kl.py (register_kl + kl_divergence
with MRO-based dispatch; _kl_expfamily_expfamily computes the Bregman
divergence with autograd — here via jax.grad on the log-normalizer).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from .distribution import Distribution, ExponentialFamily, _as_jnp, _wrap
from .families import (
    Bernoulli, Beta, Categorical, Dirichlet, Exponential, Gamma, Geometric,
    Gumbel, Laplace, LogNormal, Normal, Poisson, Uniform,
)

__all__ = ["register_kl", "kl_divergence"]

_REGISTRY: dict[tuple[type, type], callable] = {}


def register_kl(cls_p, cls_q):
    def decorator(fn):
        _REGISTRY[(cls_p, cls_q)] = fn
        return fn

    return decorator


def _dispatch(type_p, type_q):
    matches = [
        (p, q) for (p, q) in _REGISTRY
        if issubclass(type_p, p) and issubclass(type_q, q)
    ]
    if not matches:
        return None
    # most-derived match wins: smallest MRO index on both sides
    def score(pq):
        p, q = pq
        return (type_p.__mro__.index(p), type_q.__mro__.index(q))
    return _REGISTRY[min(matches, key=score)]


def kl_divergence(p: Distribution, q: Distribution):
    fn = _dispatch(type(p), type(q))
    if fn is None:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})")
    orig_p = getattr(p, "_orig_params", None) or {}
    orig_q = getattr(q, "_orig_params", None) or {}
    if not (orig_p or orig_q):
        return fn(p, q)

    # Record one GradNode so gradients flow to Tensor-valued params of either
    # side (same swap mechanism as Distribution._graph_wrap).
    from ..autograd.engine import apply_op
    from ..tensor.tensor import Tensor

    def pure(pvals, qvals):
        saved_p = {n: getattr(p, n) for n in p._swap_attrs()} if orig_p else {}
        saved_q = {n: getattr(q, n) for n in q._swap_attrs()} if orig_q else {}
        try:
            if orig_p:
                p._in_graph_call = True
                p._set_params(**dict(zip(orig_p, pvals)))
            if orig_q:
                q._in_graph_call = True
                q._set_params(**dict(zip(orig_q, qvals)))
            out = fn(p, q)
            return out._data if isinstance(out, Tensor) else out
        finally:
            for obj, saved in ((p, saved_p), (q, saved_q)):
                obj._in_graph_call = False
                for n, v in saved.items():
                    setattr(obj, n, v)

    return apply_op(
        f"kl_{type(p).__name__}_{type(q).__name__}", pure,
        tuple(orig_p.values()), tuple(orig_q.values()))


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return _wrap(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(LogNormal, LogNormal)
def _kl_lognormal(p, q):
    return _kl_normal_normal(p, q)


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    result = jnp.log((q.high - q.low) / (p.high - p.low))
    outside = (q.low > p.low) | (q.high < p.high)
    return _wrap(jnp.where(outside, jnp.inf, result))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    pp, qq = p.probs, q.probs
    t1 = jnp.where(pp > 0, pp * (jnp.log(pp) - jnp.log(qq)), 0.0)
    t2 = jnp.where(pp < 1, (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qq)), 0.0)
    return _wrap(t1 + t2)


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    logp = jax.nn.log_softmax(p.logits, -1)
    logq = jax.nn.log_softmax(q.logits, -1)
    return _wrap(jnp.sum(jnp.exp(logp) * (logp - logq), -1))


@register_kl(Geometric, Geometric)
def _kl_geometric(p, q):
    # -H(p) - E_p[X] log(1-q) - log q, with E_p[X] = (1-p)/p
    return _wrap(-_as_jnp(p.entropy())
                 - (1 - p.probs) / p.probs * jnp.log1p(-q.probs)
                 - jnp.log(q.probs))


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    ratio = q.rate / p.rate
    return _wrap(jnp.log(1 / ratio) + ratio - 1)


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    t1 = q.concentration * (jnp.log(p.rate) - jnp.log(q.rate))
    t2 = jsp.gammaln(q.concentration) - jsp.gammaln(p.concentration)
    t3 = (p.concentration - q.concentration) * jsp.digamma(p.concentration)
    t4 = (q.rate - p.rate) * p.concentration / p.rate
    return _wrap(t1 + t2 + t3 + t4)


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    from .families import _log_beta

    sp = p.alpha + p.beta
    t1 = _log_beta(q.alpha, q.beta) - _log_beta(p.alpha, p.beta)
    t2 = (p.alpha - q.alpha) * jsp.digamma(p.alpha)
    t3 = (p.beta - q.beta) * jsp.digamma(p.beta)
    t4 = (q.alpha - p.alpha + q.beta - p.beta) * jsp.digamma(sp)
    return _wrap(t1 + t2 + t3 + t4)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    a, b = p.concentration, q.concentration
    a0 = jnp.sum(a, -1)
    t1 = jsp.gammaln(a0) - jnp.sum(jsp.gammaln(a), -1)
    t2 = -jsp.gammaln(jnp.sum(b, -1)) + jnp.sum(jsp.gammaln(b), -1)
    t3 = jnp.sum((a - b) * (jsp.digamma(a) - jsp.digamma(a0[..., None])), -1)
    return _wrap(t1 + t2 + t3)


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    scale_ratio = p.scale / q.scale
    loc_abs = jnp.abs(p.loc - q.loc)
    return _wrap(-jnp.log(scale_ratio) + loc_abs / q.scale
                 + scale_ratio * jnp.exp(-loc_abs / p.scale) - 1)


@register_kl(Poisson, Poisson)
def _kl_poisson(p, q):
    return _wrap(p.rate * (jnp.log(p.rate) - jnp.log(q.rate))
                 - (p.rate - q.rate))


@register_kl(Gumbel, Gumbel)
def _kl_gumbel(p, q):
    beta_ratio = p.scale / q.scale
    loc_diff = (p.loc - q.loc) / q.scale
    # KL = log(b2/b1) + g(b1/b2-1) + (m1-m2)/b2 + e^{(m2-m1)/b2} G(1+b1/b2) - 1
    return _wrap(jnp.log(q.scale) - jnp.log(p.scale)
                 + jnp.euler_gamma * (beta_ratio - 1)
                 + loc_diff
                 + jnp.exp(-loc_diff + jsp.gammaln(1 + beta_ratio)) - 1)


@register_kl(ExponentialFamily, ExponentialFamily)
def _kl_expfamily_expfamily(p, q):
    """Bregman divergence of the log-normalizers (via jax.grad)."""
    if type(p) is not type(q):
        raise NotImplementedError(
            "exp-family KL fallback requires matching families")
    p_nat = tuple(_as_jnp(x) for x in p._natural_parameters)
    q_nat = tuple(_as_jnp(x) for x in q._natural_parameters)
    grads = jax.grad(lambda ps: jnp.sum(p._log_normalizer(*ps)))(p_nat)
    lg_p_elem = p._log_normalizer(*p_nat)
    lg_q_elem = q._log_normalizer(*q_nat)
    kl = lg_q_elem - lg_p_elem
    for pn, qn, g in zip(p_nat, q_nat, grads):
        kl = kl - (qn - pn) * g
    return _wrap(kl)
