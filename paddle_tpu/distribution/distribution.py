"""Distribution base classes.

Parity target: paddle.distribution.Distribution / ExponentialFamily
(reference: python/paddle/distribution/distribution.py:46,
exponential_family.py:22). TPU-native design: every density is a pure
jnp function of its parameters, so distributions compose with jit/vmap/grad
for free; sampling draws keys from the framework Generator (traced-key aware),
and ExponentialFamily entropy uses the Bregman identity with jax.grad on the
log-normalizer instead of hand-derived formulas.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import config
from ..framework import op_registry
from ..framework.random import default_generator
from ..tensor.tensor import Tensor

__all__ = ["Distribution", "ExponentialFamily"]


def _as_jnp(x, dtype=None):
    """Coerce Tensor / array / python scalar to a jnp array (float default)."""
    if isinstance(x, Tensor):
        x = x._data
    if isinstance(x, (jax.Array, jax.core.Tracer)):
        return x if dtype is None else x.astype(dtype)
    arr = np.asarray(x)
    if dtype is None and arr.dtype in (np.float64, np.int64, np.int32):
        if np.issubdtype(arr.dtype, np.floating) or np.issubdtype(arr.dtype, np.integer):
            dtype = config.get_default_dtype()
    return jnp.asarray(arr, dtype=dtype)


def _wrap(x) -> Tensor:
    return Tensor(x)


def _next_key():
    return default_generator.next_key()


# Methods auto-wrapped so gradients flow to Tensor-valued ctor params (and to
# Tensor `value` args). The density formulas stay raw-jnp; the wrapper swaps
# traced parameter values in via _set_params under one recorded GradNode.
_GRAPHED_METHODS = ("rsample", "sample", "log_prob", "prob", "entropy",
                    "cdf", "icdf")


def _graph_wrap(method):
    import functools

    @functools.wraps(method)
    def wrapper(self, *args, **kwargs):
        orig = getattr(self, "_orig_params", None)
        # Reentrancy guard: inside a graphed call the params are already the
        # traced values; nested wrapped methods must run plain (e.g.
        # LogNormal.log_prob -> super().log_prob).
        if not orig or getattr(self, "_in_graph_call", False):
            return method.__get__(self)(*args, **kwargs)
        from ..autograd.engine import apply_op

        names = list(orig)
        ctr_box = {}

        def pure(vals, *call_args, **call_kwargs):
            # Re-traces (higher-order grad) must redraw identical noise:
            # pin the generator counter to its value at first entry.
            if "ctr" not in ctr_box:
                ctr_box["ctr"] = default_generator._counter
            default_generator._counter = ctr_box["ctr"]
            saved = {n: getattr(self, n) for n in self._swap_attrs()}
            try:
                self._in_graph_call = True
                self._set_params(**dict(zip(names, vals)))
                out = method.__get__(self)(*call_args, **call_kwargs)
                return out._data if isinstance(out, Tensor) else out
            finally:
                self._in_graph_call = False
                for n, v in saved.items():
                    setattr(self, n, v)

        op_name = f"{type(self).__name__}.{method.__name__}"
        # Dynamically-formed name (one per concrete distribution class):
        # register the row here so the strict dispatch gate stays sound.
        op_registry.register_op(op_name, notes="distribution graphed method")
        if method.__name__ in ("sample", "rsample"):
            # samplers draw from the global generator INSIDE the body; a
            # cached executable would freeze the noise (and leak traced
            # keys into the generator state)
            from ..autograd.engine import never_eager_cache

            never_eager_cache(op_name)
        return apply_op(op_name, pure, tuple(orig.values()), *args, **kwargs)

    wrapper._graphed = True
    return wrapper


class Distribution:
    """Abstract base. Subclasses implement sample/log_prob/entropy over jnp."""

    # attribute names assigned by _set_params (default: the ctor param names)
    _PARAM_ATTRS: tuple = ()

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        for name in _GRAPHED_METHODS:
            fn = cls.__dict__.get(name)
            if callable(fn) and not getattr(fn, "_graphed", False):
                setattr(cls, name, _graph_wrap(fn))

    def _store_params(self, **ctor_args):
        """Record differentiable Tensor ctor args for graph-aware methods."""
        diff = {k: v for k, v in ctor_args.items()
                if isinstance(v, Tensor) and not v.stop_gradient}
        if diff:
            self._orig_params = diff

    def _swap_attrs(self):
        return self._PARAM_ATTRS or tuple(getattr(self, "_orig_params", {}))

    def _set_params(self, **vals):
        for k, v in vals.items():
            setattr(self, k, v)

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(int(d) for d in batch_shape)
        self._event_shape = tuple(int(d) for d in event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        return _wrap(jnp.sqrt(_as_jnp(self.variance)))

    def _extend_shape(self, sample_shape):
        return tuple(sample_shape) + self._batch_shape + self._event_shape

    def sample(self, shape=()):
        """Draw (non-differentiable) samples of shape + batch + event."""
        return _wrap(jax.lax.stop_gradient(_as_jnp(self.rsample(shape))))

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return _wrap(jnp.exp(_as_jnp(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError

    def cdf(self, value):
        raise NotImplementedError

    def icdf(self, value):
        raise NotImplementedError

    def kl_divergence(self, other: "Distribution"):
        from .kl import kl_divergence

        return kl_divergence(self, other)

    def _validate_value(self, value):
        return _as_jnp(value)

    def __repr__(self):
        return f"{type(self).__name__}(batch_shape={self.batch_shape}, event_shape={self.event_shape})"


class ExponentialFamily(Distribution):
    """p(x) = h(x) exp(<eta, T(x)> - A(eta)).

    Entropy falls out of the Bregman identity
    H = A(eta) - <eta, grad A(eta)> + E[log h(x)] — computed with jax.grad on
    `_log_normalizer` (reference derives this by hand per family;
    exponential_family.py:40 uses autograd the same way).
    """

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        raise NotImplementedError

    def entropy(self):
        nparams = [_as_jnp(p) for p in self._natural_parameters]
        # grad of sum(A) is elementwise-correct because A is separable per batch
        grads = jax.grad(lambda ps: jnp.sum(self._log_normalizer(*ps)))(tuple(nparams))
        ent = -self._mean_carrier_measure + self._log_normalizer(*nparams)
        for p, g in zip(nparams, grads):
            ent = ent - p * g
        return _wrap(ent)
