"""TransformedDistribution + Independent wrappers.

Parity target: python/paddle/distribution/transformed_distribution.py,
independent.py.
"""
from __future__ import annotations

import jax.numpy as jnp

from .distribution import Distribution, _as_jnp, _wrap
from .transform import ChainTransform, Type

__all__ = ["TransformedDistribution", "Independent"]


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        if not isinstance(transforms, (list, tuple)):
            transforms = [transforms]
        self.base = base
        self.transforms = list(transforms)
        chain = ChainTransform(self.transforms)
        base_shape = tuple(base.batch_shape) + tuple(base.event_shape)
        out_shape = tuple(chain.forward_shape(base_shape))
        event_dim = max(chain._codomain_event_dim, len(base.event_shape))
        cut = len(out_shape) - event_dim
        super().__init__(batch_shape=out_shape[:cut], event_shape=out_shape[cut:])
        self._chain = chain

    def sample(self, shape=()):
        x = self.base.sample(shape)
        return self._chain.forward(x)

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        return self._chain.forward(x)

    def log_prob(self, value):
        if not Type.is_injective(self._chain._type):
            raise TypeError("log_prob undefined for non-injective transforms")
        y = _as_jnp(value)
        lp = 0.0
        event_dim = len(self._event_shape)
        for t in reversed(self.transforms):
            x = t._inverse(y)
            ldj = t._forward_log_det_jacobian(x)
            extra = event_dim - t._codomain_event_dim
            if extra > 0:
                ldj = jnp.sum(ldj, axis=tuple(range(-extra, 0)))
            lp = lp - ldj
            event_dim = event_dim - t._codomain_event_dim + t._domain_event_dim
            y = x
        base_lp = _as_jnp(self.base.log_prob(y))
        extra = event_dim - len(self.base.event_shape)
        if extra > 0:
            base_lp = jnp.sum(base_lp, axis=tuple(range(-extra, 0)))
        return _wrap(lp + base_lp)


class Independent(Distribution):
    """Reinterpret trailing batch dims of `base` as event dims."""

    def __init__(self, base, reinterpreted_batch_rank: int):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        b = tuple(base.batch_shape)
        k = self.reinterpreted_batch_rank
        if k > len(b):
            raise ValueError("reinterpreted_batch_rank exceeds base batch rank")
        super().__init__(batch_shape=b[:len(b) - k],
                         event_shape=b[len(b) - k:] + tuple(base.event_shape))

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def _reduce(self, x):
        axes = tuple(range(-self.reinterpreted_batch_rank, 0))
        return jnp.sum(_as_jnp(x), axis=axes) if axes else _as_jnp(x)

    def log_prob(self, value):
        return _wrap(self._reduce(self.base.log_prob(value)))

    def entropy(self):
        return _wrap(self._reduce(self.base.entropy()))
