"""Linear algebra ops (paddle.linalg parity).

Parity: python/paddle/tensor/linalg.py. Decompositions route through
jax.numpy.linalg / jax.scipy.linalg (XLA lowers these to TPU-supported
factorizations; some fall back to CPU on TPU just like the reference's
CPU-only kernels).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd.engine import apply_op
from .tensor import Tensor
from .math import matmul, dot, bmm, mv  # re-exported  # noqa: F401


def transpose_last2(x):
    return apply_op("transpose_last2", lambda v: jnp.swapaxes(v, -1, -2), x)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def fn(v):
        if p is None or p == "fro":
            if axis is None:
                return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(v))))
            return jnp.linalg.norm(v, ord=None, axis=_ax(axis), keepdims=keepdim)
        if p == "nuc":
            return jnp.linalg.norm(v, ord="nuc", axis=_ax(axis), keepdims=keepdim)
        if p == float("inf") or p == float("-inf") or isinstance(p, (int, float)):
            if axis is None:
                flat = jnp.abs(v.reshape(-1))
                if p == float("inf"):
                    return jnp.max(flat)
                if p == float("-inf"):
                    return jnp.min(flat)
                if p == 0:
                    return jnp.sum((flat != 0).astype(v.dtype))
                return jnp.sum(flat**p) ** (1.0 / p)
            return jnp.linalg.norm(v, ord=p, axis=_ax(axis), keepdims=keepdim)
        raise ValueError(f"unsupported norm order {p}")

    return apply_op("norm", fn, x)


def _ax(axis):
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return axis


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return apply_op(
        "vector_norm",
        lambda v: jnp.linalg.vector_norm(v, ord=p, axis=_ax(axis), keepdims=keepdim),
        x,
    )


def matrix_norm(x, p="fro", axis=[-2, -1], keepdim=False, name=None):
    return apply_op(
        "matrix_norm",
        lambda v: jnp.linalg.matrix_norm(v, ord=p, keepdims=keepdim),
        x,
    )


def dist(x, y, p=2, name=None):
    return norm(x - y, p=p)


def cond(x, p=None, name=None):
    return apply_op("cond", lambda v: jnp.linalg.cond(v, p=p), x)


def cross(x, y, axis=9, name=None):
    def fn(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis with dim 3
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)

    return apply_op("cross", fn, x, y)


def cholesky(x, upper=False, name=None):
    def fn(v):
        L = jnp.linalg.cholesky(v)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L

    return apply_op("cholesky", fn, x)


def cholesky_solve(x, y, upper=False, name=None):
    def fn(b, L):
        Lm = jnp.swapaxes(L, -1, -2).conj() if upper else L
        z = jax.scipy.linalg.solve_triangular(Lm, b, lower=True)
        return jax.scipy.linalg.solve_triangular(jnp.swapaxes(Lm, -1, -2).conj(), z, lower=False)

    return apply_op("cholesky_solve", fn, x, y)


def inv(x, name=None):
    return apply_op("inv", jnp.linalg.inv, x)


inverse = inv


def det(x, name=None):
    return apply_op("det", jnp.linalg.det, x)


def slogdet(x, name=None):
    def fn(v):
        sign, logabs = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logabs])

    return apply_op("slogdet", fn, x)


def solve(x, y, name=None):
    return apply_op("solve", jnp.linalg.solve, x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
        )

    return apply_op("triangular_solve", fn, x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    def fn(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv

    sol, res, rank, sv = apply_op("lstsq", fn, x, y)
    return sol, res, rank, sv


def qr(x, mode="reduced", name=None):
    def fn(v):
        q, r = jnp.linalg.qr(v, mode=mode)
        return q, r

    return apply_op("qr", fn, x)


def svd(x, full_matrices=False, name=None):
    def fn(v):
        u, s, vh = jnp.linalg.svd(v, full_matrices=full_matrices)
        return u, s, jnp.swapaxes(vh, -1, -2).conj()

    return apply_op("svd", fn, x)


def svdvals(x, name=None):
    return apply_op("svdvals", lambda v: jnp.linalg.svd(v, compute_uv=False), x)


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    u, s, v = svd(x)
    from .manipulation import slice as slice_op

    return u[..., :q], s[..., :q], v[..., :q]


def eig(x, name=None):
    def fn(v):
        w, vec = jnp.linalg.eig(v)
        return w, vec

    return apply_op("eig", fn, x)


def eigvals(x, name=None):
    return apply_op("eigvals", jnp.linalg.eigvals, x)


def eigh(x, UPLO="L", name=None):
    def fn(v):
        w, vec = jnp.linalg.eigh(v, UPLO=UPLO)
        return w, vec

    return apply_op("eigh", fn, x)


def eigvalsh(x, UPLO="L", name=None):
    return apply_op("eigvalsh", lambda v: jnp.linalg.eigvalsh(v, UPLO=UPLO), x)


def matrix_power(x, n, name=None):
    return apply_op("matrix_power", lambda v: jnp.linalg.matrix_power(v, n), x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply_op(
        "matrix_rank", lambda v: jnp.linalg.matrix_rank(v, rtol=tol).astype(jnp.int64), x
    )


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op("pinv", lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian), x)


def multi_dot(x, name=None):
    return apply_op("multi_dot", lambda *vs: jnp.linalg.multi_dot(vs), *x)


def lu(x, pivot=True, get_infos=False, name=None):
    def fn(v):
        lu_mat, piv = jax.scipy.linalg.lu_factor(v)
        return lu_mat, (piv + 1).astype(jnp.int32)

    lu_mat, piv = apply_op("lu", fn, x)
    if get_infos:
        return lu_mat, piv, Tensor(jnp.zeros((), jnp.int32))
    return lu_mat, piv


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True, name=None):
    def fn(lu_mat, piv):
        m = lu_mat.shape[-2]
        L = jnp.tril(lu_mat, -1) + jnp.eye(m, lu_mat.shape[-1], dtype=lu_mat.dtype)
        U = jnp.triu(lu_mat)
        perm = jnp.arange(m)
        piv0 = piv - 1

        def body(i, p):
            a, b = p[i], p[piv0[i]]
            p = p.at[i].set(b)
            return p.at[piv0[i]].set(a)

        perm = jax.lax.fori_loop(0, piv.shape[-1], body, perm)
        P = jnp.eye(m, dtype=lu_mat.dtype)[perm].T
        return P, L[..., :m, :], U

    return apply_op("lu_unpack", fn, lu_data, lu_pivots)


def corrcoef(x, rowvar=True, name=None):
    return apply_op("corrcoef", lambda v: jnp.corrcoef(v, rowvar=rowvar), x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    def fn(v, *ws):
        fw = ws[0] if fweights is not None else None
        aw = ws[-1] if aweights is not None else None
        return jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0,
                       fweights=fw, aweights=aw)

    args = [x] + [w for w in (fweights, aweights) if w is not None]
    return apply_op("cov", fn, *args)


def householder_product(x, tau, name=None):
    def fn(a, t):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)

        def body(i, Q):
            v = jnp.where(jnp.arange(m) < i, 0.0, a[..., :, i].at[i].set(1.0))
            H = eye - t[..., i] * jnp.outer(v, v.conj())
            return Q @ H

        Q = jax.lax.fori_loop(0, n, body, eye)
        return Q[..., :, :n]

    return apply_op("householder_product", fn, x, tau)



def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Batched pairwise p-norm distances [*,P,M] x [*,R,M] -> [*,P,R]
    (reference tensor/linalg.py cdist over the phi dist kernels). The
    euclidean case contracts on the MXU (||a-b||^2 = ||a||^2 + ||b||^2
    - 2ab) matching the reference's use_mm compute mode."""

    use_mm = compute_mode in ("use_mm_for_euclid_dist_if_necessary",
                              "use_mm_for_euclid_dist")

    def fn(a, b):
        if p == 2.0 and use_mm:
            a2 = jnp.sum(a * a, axis=-1)[..., :, None]
            b2 = jnp.sum(b * b, axis=-1)[..., None, :]
            ab = jnp.matmul(a, jnp.swapaxes(b, -1, -2))
            d2 = jnp.clip(a2 + b2 - 2 * ab, 0.0)
            # zero distances: sqrt'(0) is inf — define the grad as 0 there
            # (torch convention) via a masked sqrt
            pos = d2 > 0
            return jnp.where(pos, jnp.sqrt(jnp.where(pos, d2, 1.0)), 0.0)
        diff = jnp.abs(a[..., :, None, :] - b[..., None, :, :])
        if p == 0:
            return jnp.sum((diff != 0).astype(a.dtype), axis=-1)
        if jnp.isinf(p):
            return jnp.max(diff, axis=-1)
        return jnp.sum(diff ** p, axis=-1) ** (1.0 / p)

    return apply_op("cdist", fn, x, y)


def matrix_exp(x, name=None):
    """Matrix exponential (reference linalg.matrix_exp over phi)."""
    return apply_op("matrix_exp",
                    lambda v: jax.scipy.linalg.expm(v), x)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Low-rank PCA: (U, S, V) with q components (reference
    linalg.pca_lowrank — the torch-style randomized algorithm; computed
    here via the exact thin SVD, which the TPU's MXU-backed jnp SVD makes
    affordable at these ranks and is a strict-accuracy superset of the
    randomized reference)."""
    if q is None:
        q = min(6, x.shape[-2], x.shape[-1])

    def fn(v):
        a = v - jnp.mean(v, axis=-2, keepdims=True) if center else v
        u, s, vh = jnp.linalg.svd(a, full_matrices=False)
        return (u[..., :, :q], s[..., :q],
                jnp.swapaxes(vh, -1, -2)[..., :, :q])

    return apply_op("pca_lowrank", fn, x)
