"""Statistics ops (paddle.tensor.stat parity)."""
from __future__ import annotations

import jax.numpy as jnp

from ..autograd.engine import apply_op
from .math import _axis


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op(
        "std",
        lambda v: jnp.std(v, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        x,
    )


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op(
        "var",
        lambda v: jnp.var(v, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        x,
    )


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def fn(v):
        if mode == "avg":
            return jnp.median(v, axis=_axis(axis), keepdims=keepdim)
        # mode == "min": lower median
        ax = _axis(axis)
        if ax is None:
            flat = jnp.sort(v.reshape(-1))
            return flat[(flat.shape[0] - 1) // 2]
        srt = jnp.sort(v, axis=ax)
        idx = (v.shape[ax] - 1) // 2
        out = jnp.take(srt, idx, axis=ax)
        return jnp.expand_dims(out, ax) if keepdim else out

    return apply_op("median", fn, x)


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    return apply_op(
        "nanmedian", lambda v: jnp.nanmedian(v, axis=_axis(axis), keepdims=keepdim), x
    )


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    def fn(v):
        return jnp.quantile(
            v, jnp.asarray(q), axis=_axis(axis), keepdims=keepdim, method=interpolation
        )

    return apply_op("quantile", fn, x)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    def fn(v):
        return jnp.nanquantile(
            v, jnp.asarray(q), axis=_axis(axis), keepdims=keepdim, method=interpolation
        )

    return apply_op("nanquantile", fn, x)
