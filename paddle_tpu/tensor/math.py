"""Math ops (elementwise, reductions, cumulative, special functions).

Parity: python/paddle/tensor/math.py (+ ops.py) in the reference. Each op is a
pure jax function routed through the autograd engine's apply_op.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
import jax.scipy.special as jsp

from ..autograd.engine import apply_op, make_op
from ..framework import dtype as dtype_mod
from .tensor import Tensor


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


# --- binary elementwise ---
add = make_op("add", jnp.add)
subtract = make_op("subtract", jnp.subtract)
multiply = make_op("multiply", jnp.multiply)
mod = make_op("mod", jnp.mod)
remainder = mod
floor_mod = mod
floor_divide = make_op("floor_divide", jnp.floor_divide)
maximum = make_op("maximum", jnp.maximum)
minimum = make_op("minimum", jnp.minimum)
fmax = make_op("fmax", jnp.fmax)
fmin = make_op("fmin", jnp.fmin)
hypot = make_op("hypot", jnp.hypot)
logaddexp = make_op("logaddexp", jnp.logaddexp)
nextafter = make_op("nextafter", jnp.nextafter)
copysign = make_op("copysign", jnp.copysign)
heaviside = make_op("heaviside", jnp.heaviside)
gcd = make_op("gcd", jnp.gcd)
lcm = make_op("lcm", jnp.lcm)
ldexp = make_op("ldexp", jnp.ldexp)
inner = make_op("inner", jnp.inner)
outer = make_op("outer", lambda x, y: jnp.outer(x, y))
kron = make_op("kron", jnp.kron)


def divide(x, y, name=None):
    # paddle divide: int/int -> float (true divide)
    return apply_op("divide", jnp.true_divide, x, y)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def fn(v, s, b):
        return v * s + b if bias_after_scale else (v + b) * s

    return apply_op("scale", fn, x, scale, bias)


def pow(x, y, name=None):
    return apply_op("pow", jnp.power, x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)

    return apply_op("matmul", fn, x, y)


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return apply_op("bmm", jnp.matmul, x, y)


def dot(x, y, name=None):
    return apply_op("dot", lambda a, b: jnp.sum(a * b, axis=-1), x, y)


def mv(x, vec, name=None):
    return apply_op("mv", jnp.matmul, x, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op("addmm", lambda i, a, b: beta * i + alpha * jnp.matmul(a, b), input, x, y)


# --- unary elementwise ---
def _unary(name, fn):
    return make_op(name, fn)


abs = _unary("abs", jnp.abs)
absolute = abs
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
square = _unary("square", jnp.square)
exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
arcsin, arccos, arctan = asin, acos, atan
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
atan2 = make_op("atan2", jnp.arctan2)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
trunc = _unary("trunc", jnp.trunc)
frac = _unary("frac", lambda x: x - jnp.trunc(x))
sign = _unary("sign", jnp.sign)
sgn = sign
reciprocal = _unary("reciprocal", jnp.reciprocal)
neg = _unary("neg", jnp.negative)
negative = neg
erf = _unary("erf", jsp.erf)
erfinv = _unary("erfinv", jsp.erfinv)
lgamma = _unary("lgamma", jsp.gammaln)
digamma = _unary("digamma", jsp.digamma)
polygamma = lambda x, n, name=None: apply_op("polygamma", lambda v: jsp.polygamma(n, v), x)
i0 = _unary("i0", jsp.i0)
i0e = _unary("i0e", jsp.i0e)
i1 = _unary("i1", jsp.i1)
i1e = _unary("i1e", jsp.i1e)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
logit = lambda x, eps=None, name=None: apply_op(
    "logit",
    lambda v: jsp.logit(jnp.clip(v, eps, 1 - eps) if eps is not None else v),
    x,
)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)
conj = _unary("conj", jnp.conj)
angle = _unary("angle", jnp.angle)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
exponent = _unary("exponent", lambda x: jnp.frexp(x)[1].astype(jnp.int32))


def round(x, decimals=0, name=None):
    return apply_op("round", lambda v: jnp.round(v, decimals), x)


def rint(x, name=None):
    return apply_op("rint", jnp.rint, x)


def increment(x, value=1.0, name=None):
    out = apply_op("increment", lambda v: v + jnp.asarray(value, v.dtype), x)
    x._data = out._data
    x._grad_node = out._grad_node
    x._out_index = out._out_index
    return x


def clip(x, min=None, max=None, name=None):
    # Tensor bounds stay in-graph (differentiable + trace-safe), scalars are
    # closed over.
    if isinstance(min, Tensor) and isinstance(max, Tensor):
        return apply_op("clip", lambda v, lo, hi: jnp.clip(v, lo, hi), x, min, max)
    if isinstance(min, Tensor):
        return apply_op("clip", lambda v, lo: jnp.clip(v, lo, max), x, min)
    if isinstance(max, Tensor):
        return apply_op("clip", lambda v, hi: jnp.clip(v, min, hi), x, max)
    return apply_op("clip", lambda v: jnp.clip(v, min, max), x)


def lerp(x, y, weight, name=None):
    return apply_op("lerp", lambda a, b, w: a + w * (b - a), x, y, weight)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_op(
        "nan_to_num", lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf, neginf=neginf), x
    )


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op("stanh", lambda v: scale_b * jnp.tanh(scale_a * v), x)


def multiplex(inputs, index, name=None):
    return apply_op(
        "multiplex",
        lambda idx, *ins: jnp.stack(ins, 0)[idx.reshape(-1), jnp.arange(ins[0].shape[0])],
        index,
        *inputs,
    )


# --- reductions ---
def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    want = dtype_mod.to_jax_dtype(dtype) if dtype is not None else None

    def fn(v):
        # accumulate IN the requested dtype (overflow semantics parity)
        return jnp.sum(v, axis=_axis(axis), keepdims=keepdim, dtype=want)

    return apply_op("sum", fn, x)


def mean(x, axis=None, keepdim=False, name=None):
    return apply_op("mean", lambda v: jnp.mean(v, axis=_axis(axis), keepdims=keepdim), x)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    want = dtype_mod.to_jax_dtype(dtype) if dtype is not None else None

    def fn(v):
        out = jnp.prod(v, axis=_axis(axis), keepdims=keepdim)
        return out.astype(want) if want is not None else out

    return apply_op("prod", fn, x)


def max(x, axis=None, keepdim=False, name=None):
    return apply_op("max", lambda v: jnp.max(v, axis=_axis(axis), keepdims=keepdim), x)


def min(x, axis=None, keepdim=False, name=None):
    return apply_op("min", lambda v: jnp.min(v, axis=_axis(axis), keepdims=keepdim), x)


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    want = dtype_mod.to_jax_dtype(dtype) if dtype is not None else None
    return apply_op(
        "nansum",
        lambda v: jnp.nansum(v, axis=_axis(axis), keepdims=keepdim, dtype=want),
        x,
    )


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply_op("nanmean", lambda v: jnp.nanmean(v, axis=_axis(axis), keepdims=keepdim), x)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply_op(
        "logsumexp", lambda v: jsp.logsumexp(v, axis=_axis(axis), keepdims=keepdim), x
    )


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("trace", lambda v: jnp.trace(v, offset, axis1, axis2), x)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("diagonal", lambda v: jnp.diagonal(v, offset, axis1, axis2), x)


# --- cumulative ---
def cumsum(x, axis=None, dtype=None, name=None):
    want = dtype_mod.to_jax_dtype(dtype) if dtype is not None else None

    def fn(v):
        return jnp.cumsum(
            v if axis is not None else v.reshape(-1),
            axis=axis if axis is not None else 0,
            dtype=want,
        )

    return apply_op("cumsum", fn, x)


def cumprod(x, dim=None, dtype=None, name=None):
    want = dtype_mod.to_jax_dtype(dtype) if dtype is not None else None
    return apply_op("cumprod", lambda v: jnp.cumprod(v, axis=dim, dtype=want), x)


def cummax(x, axis=None, dtype="int64", name=None):
    def fn(v):
        a = 0 if axis is None else axis
        vv = v.reshape(-1) if axis is None else v
        values = jax.lax.associative_scan(jnp.maximum, vv, axis=a)
        eq = vv == values
        idx = jnp.arange(vv.shape[a]).reshape([-1 if i == a % vv.ndim else 1 for i in range(vv.ndim)])
        indices = jax.lax.associative_scan(jnp.maximum, jnp.where(eq, idx, 0), axis=a)
        return values, indices.astype(jnp.int64)

    return apply_op("cummax", fn, x)


def cummin(x, axis=None, dtype="int64", name=None):
    def fn(v):
        a = 0 if axis is None else axis
        vv = v.reshape(-1) if axis is None else v
        values = jax.lax.associative_scan(jnp.minimum, vv, axis=a)
        eq = vv == values
        idx = jnp.arange(vv.shape[a]).reshape([-1 if i == a % vv.ndim else 1 for i in range(vv.ndim)])
        indices = jax.lax.associative_scan(jnp.maximum, jnp.where(eq, idx, 0), axis=a)
        return values, indices.astype(jnp.int64)

    return apply_op("cummin", fn, x)


def logcumsumexp(x, axis=None, name=None):
    def fn(v):
        vv = v.reshape(-1) if axis is None else v
        a = 0 if axis is None else axis
        return jax.lax.associative_scan(jnp.logaddexp, vv, axis=a)

    return apply_op("logcumsumexp", fn, x)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [x]
    if prepend is not None:
        args.append(prepend)
    if append is not None:
        args.append(append)

    def fn(v, *rest):
        pre = rest[0] if prepend is not None else None
        app = rest[-1] if append is not None and len(rest) > (1 if prepend is not None else 0) else (
            rest[0] if append is not None and prepend is None else None
        )
        return jnp.diff(v, n=n, axis=axis, prepend=pre, append=app)

    return apply_op("diff", fn, *args)


# --- checks ---
isfinite = _unary("isfinite", jnp.isfinite)
isinf = _unary("isinf", jnp.isinf)
isnan = _unary("isnan", jnp.isnan)
isreal = _unary("isreal", jnp.isreal)
isneginf = _unary("isneginf", jnp.isneginf)
isposinf = _unary("isposinf", jnp.isposinf)


def all(x, axis=None, keepdim=False, name=None):
    return apply_op("all", lambda v: jnp.all(v, axis=_axis(axis), keepdims=keepdim), x)


def any(x, axis=None, keepdim=False, name=None):
    return apply_op("any", lambda v: jnp.any(v, axis=_axis(axis), keepdims=keepdim), x)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply_op(
        "count_nonzero",
        lambda v: jnp.count_nonzero(v, axis=_axis(axis), keepdims=keepdim).astype(jnp.int64),
        x,
    )


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def take(x, index, mode="raise", name=None):
    if mode == "raise":
        # Bounds-check eagerly when concrete (paddle raises on OOB); traced
        # values can't raise, fall back to clip there.
        idx_data = index._data if isinstance(index, Tensor) else jnp.asarray(index)
        n = x._data.size
        if not isinstance(idx_data, jax.core.Tracer) and not isinstance(
            x._data, jax.core.Tracer
        ):
            import numpy as _np

            idx_np = _np.asarray(idx_data)
            if idx_np.size and (idx_np.min() < -n or idx_np.max() >= n):
                raise IndexError(
                    f"take index out of range for tensor of {n} elements"
                )
        mode = "clip"
    jmode = {"clip": "clip", "wrap": "wrap"}[mode]

    def fn(v, i):
        flat = v.reshape(-1)
        i = jnp.where(i < 0, i + flat.shape[0], i)  # paddle: negatives index from end
        return jnp.take(flat, i, mode=jmode)

    return apply_op("take", fn, x, index)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Trapezoidal-rule integral (reference: paddle.trapezoid)."""

    def fn(y_, x_):
        if x_ is not None:
            return jnp.trapezoid(y_, x=x_, axis=axis)
        return jnp.trapezoid(y_, dx=1.0 if dx is None else dx, axis=axis)

    return apply_op("trapezoid", fn, y, x)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Cumulative trapezoidal integral (reference:
    paddle.cumulative_trapezoid)."""

    def fn(y_, x_):
        yl = jnp.moveaxis(y_, axis, -1)
        if x_ is not None:
            # move x into the same layout BEFORE broadcasting against yl
            xl = (jnp.moveaxis(x_, axis, -1) if x_.ndim == y_.ndim else x_)
            widths = jnp.diff(jnp.broadcast_to(xl, yl.shape), axis=-1)
        else:
            widths = 1.0 if dx is None else dx
        avg = (yl[..., 1:] + yl[..., :-1]) / 2.0
        out = jnp.cumsum(avg * widths, axis=-1)
        return jnp.moveaxis(out, -1, axis)

    return apply_op("cumulative_trapezoid", fn, y, x)


def vander(x, n=None, increasing=False, name=None):
    """Vandermonde matrix (reference: paddle.vander)."""

    def fn(x_):
        return jnp.vander(x_, N=n, increasing=increasing)

    return apply_op("vander", fn, x)


def renorm(x, p, axis, max_norm, name=None):
    """Renormalize sub-tensors along ``axis`` so each slice's p-norm is at
    most ``max_norm`` (reference: phi/kernels/impl/renorm_impl.h)."""

    def fn(v):
        ax = axis % v.ndim
        red = tuple(i for i in range(v.ndim) if i != ax)
        norms = jnp.sum(jnp.abs(v) ** p, axis=red, keepdims=True) ** (1.0 / p)
        scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return v * scale

    return apply_op("renorm", fn, x)


# --- round-4 tensor-surface tail (reference tensor/math.py parity) ---------

def add_n(inputs, name=None):
    """Elementwise sum of a tensor list (reference math.py add_n). Always
    returns a FRESH tensor (never aliases an input)."""
    if isinstance(inputs, Tensor):
        inputs = [inputs]

    def fn(*vs):
        out = vs[0]
        for v in vs[1:]:
            out = out + v
        return out

    return apply_op("add_n", fn, *inputs)


def frexp(x, name=None):
    """(mantissa, exponent) with x = m * 2**e, 0.5 <= |m| < 1 (reference
    math.py frexp). Exponent returned in x's dtype (reference behavior)."""
    def fn(v):
        m, e = jnp.frexp(v)
        return m, e.astype(v.dtype)

    return apply_op("frexp", fn, x)


def gammaln(x, name=None):
    """log|Gamma(x)| (reference math.py gammaln)."""
    return apply_op("gammaln", lambda v: jsp.gammaln(v), x)


def multigammaln(x, p, name=None):
    """Log multivariate gamma (reference math.py multigammaln)."""
    def fn(v):
        import math as _m

        c = 0.25 * p * (p - 1) * _m.log(_m.pi)
        terms = [jsp.gammaln(v - 0.5 * i) for i in range(p)]
        out = c
        for t_ in terms:
            out = out + t_
        return out

    return apply_op("multigammaln", fn, x)


def signbit(x, name=None):
    """True where the sign bit is set (reference math.py signbit)."""
    return apply_op("signbit", lambda v: jnp.signbit(v), x)


def polar(abs, angle, name=None):
    """Complex from magnitude and phase (reference creation.py polar)."""
    def fn(r, theta):
        return (r * jnp.cos(theta)) + 1j * (r * jnp.sin(theta))

    return apply_op("polar", fn, abs, angle)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,
                name=None):
    """Recompute global ids into shard-local ids (reference math.py
    shard_index, the sharded-embedding helper): ids inside this shard map
    to id - shard_id*shard_size, others to ignore_value."""
    if not 0 <= shard_id < nshards:
        raise ValueError(
            f"shard_id {shard_id} must be in [0, {nshards})")
    size = (index_num + nshards - 1) // nshards

    def fn(v):
        lo = shard_id * size
        inside = (v >= lo) & (v < lo + size)
        return jnp.where(inside, v - lo, ignore_value)

    return apply_op("shard_index", fn, input)


def combinations(x, r=2, with_replacement=False, name=None):
    """Length-``r`` combinations of a 1-D tensor (reference math.py:7448).

    The index set depends only on the STATIC length and ``r``, so it is built
    host-side with itertools and the device does one static-shape gather —
    no masked_select dynamic shapes (XLA-friendly, unlike the reference's
    meshgrid+mask formulation which materializes n**r intermediates).
    """
    import itertools

    if len(x.shape) != 1:
        raise TypeError(f"Expect a 1-D vector, but got x shape {x.shape}")
    if not isinstance(r, int) or r < 0:
        raise ValueError(f"Expect a non-negative int, but got r={r}")
    from .creation import empty

    if r == 0:
        return empty([0], dtype=x.dtype)
    n = int(x.shape[0])
    if (r > n and not with_replacement) or (n == 0 and with_replacement):
        return empty([0, r], dtype=x.dtype)
    combine = (itertools.combinations_with_replacement if with_replacement
               else itertools.combinations)
    idx = np.asarray(list(combine(range(n), r)), dtype=np.int64)
    return apply_op("combinations", lambda v: jnp.take(v, idx, axis=0), x)
