"""SelectedRows: sparse row-slice gradients (embedding updates).

Reference: paddle/phi/core/selected_rows.h:27 — a TensorBase holding
(rows, value, height) where ``value[i]`` is the data for global row
``rows[i]``; produced by sparse embedding backward and consumed by
merge_add / sgd-on-selected-rows kernels.

TPU-native: XLA has no sparse-row buffer type — the idiomatic equivalent is
(indices, values) pairs with ``segment_sum`` merges and ``scatter-add``
application, which is exactly what this class wraps. The framework's
embedding backward stays dense (XLA turns the one-hot matmul into a
scatter), so SelectedRows here serves the API surface: row-slice
accumulation, merge, and dense materialization.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .tensor import Tensor

__all__ = ["SelectedRows", "merge_selected_rows"]


def _data(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class SelectedRows:
    """(rows, value, height): value[i] is global row rows[i] of a
    [height, *value.shape[1:]] dense tensor. Rows may repeat (unmerged
    gradient contributions — reference merge_add semantics)."""

    def __init__(self, rows, value, height: int):
        self.rows = jnp.asarray(_data(rows), jnp.int32)
        self.value = _data(value)
        if self.rows.ndim != 1:
            raise ValueError(f"rows must be 1-D, got {self.rows.shape}")
        if self.value.shape[0] != self.rows.shape[0]:
            raise ValueError(
                f"value rows {self.value.shape[0]} != len(rows) "
                f"{self.rows.shape[0]}")
        self.height = int(height)

    @property
    def shape(self):
        return (self.height, *self.value.shape[1:])

    def has_duplicates(self) -> bool:
        return bool(jnp.unique(self.rows).shape[0] < self.rows.shape[0])

    def merge(self) -> "SelectedRows":
        """Sum duplicate row contributions (reference
        phi::funcs::MergeAdd). Rows come out sorted and unique."""
        uniq, inv = jnp.unique(self.rows, return_inverse=True)
        merged = jax.ops.segment_sum(
            self.value, inv, num_segments=uniq.shape[0])
        return SelectedRows(uniq, merged, self.height)

    def to_dense(self) -> Tensor:
        """Materialize the [height, ...] dense tensor (scatter-add)."""
        dense = jnp.zeros(self.shape, self.value.dtype)
        return Tensor(dense.at[self.rows].add(self.value))

    def apply_to(self, param, lr: float = 1.0) -> Tensor:
        """param - lr * grad for a SelectedRows grad — the reference's
        sgd-on-selected-rows kernel (touches only the listed rows)."""
        p = _data(param)
        return Tensor(p.at[self.rows].add(-lr * self.value))

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"nnz_rows={int(self.rows.shape[0])}, "
                f"row_dim={self.value.shape[1:]})")


def merge_selected_rows(x: SelectedRows) -> SelectedRows:
    """Functional alias of :meth:`SelectedRows.merge` (reference
    paddle.incubate merge_selected_rows op)."""
    if not isinstance(x, SelectedRows):
        raise TypeError(f"expected SelectedRows, got {type(x)}")
    return x.merge()
