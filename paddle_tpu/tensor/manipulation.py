"""Shape/layout manipulation ops.

Parity: python/paddle/tensor/manipulation.py. All views are functional (XLA
has no aliasing at this level); in-place variants rebind the handle's data and
grad node, which keeps autograd exact.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..autograd.engine import apply_op
from .tensor import Tensor


_py_slice = slice  # capture the builtin before the paddle-style `slice` op shadows it


def _int_list(v):
    if isinstance(v, Tensor):
        return [int(s) for s in v.numpy()]
    if isinstance(v, (int, np.integer)):
        return [int(v)]
    return [int(s.item() if isinstance(s, Tensor) else s) for s in v]


def _inplace(x, out):
    x._data = out._data
    x._grad_node = out._grad_node
    x._out_index = out._out_index
    x.stop_gradient = out.stop_gradient
    return x


def reshape(x, shape, name=None):
    shape = _int_list(shape)
    return apply_op("reshape", lambda v: jnp.reshape(v, shape), x)


def reshape_(x, shape, name=None):
    return _inplace(x, reshape(x, shape))


view = reshape


def transpose(x, perm, name=None):
    perm = _int_list(perm)
    return apply_op("transpose", lambda v: jnp.transpose(v, perm), x)


def t(x, name=None):
    if x.ndim <= 1:
        return x.clone()
    return apply_op("t", lambda v: v.T, x)


def moveaxis(x, source, destination, name=None):
    return apply_op("moveaxis", lambda v: jnp.moveaxis(v, source, destination), x)


def swapaxes(x, axis0, axis1, name=None):
    return apply_op("swapaxes", lambda v: jnp.swapaxes(v, axis0, axis1), x)


transpose_ = lambda x, perm, name=None: _inplace(x, transpose(x, perm))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def fn(v):
        nd = v.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = v.shape[:s] + (-1,) + v.shape[e + 1 :]
        return jnp.reshape(v, new_shape)

    return apply_op("flatten", fn, x)


def unflatten(x, axis, shape, name=None):
    """Split ``axis`` into ``shape`` (reference manipulation.py unflatten).
    One -1 entry in ``shape`` is inferred."""
    def fn(v):
        a = axis % v.ndim
        return jnp.reshape(v, v.shape[:a] + tuple(shape) + v.shape[a + 1:])

    return apply_op("unflatten", fn, x)


def squeeze(x, axis=None, name=None):
    def fn(v):
        if axis is None:
            return jnp.squeeze(v)
        axes = tuple(a % v.ndim for a in (_int_list(axis)))
        axes = tuple(a for a in axes if v.shape[a] == 1)
        return jnp.squeeze(v, axis=axes) if axes else v

    return apply_op("squeeze", fn, x)


def squeeze_(x, axis=None, name=None):
    return _inplace(x, squeeze(x, axis))


def unsqueeze(x, axis, name=None):
    axes = _int_list(axis)

    def fn(v):
        # axes are positions in the FINAL shape (numpy expand_dims semantics)
        final_nd = v.ndim + len(axes)
        norm = tuple(a % final_nd for a in axes)
        return jnp.expand_dims(v, norm)

    return apply_op("unsqueeze", fn, x)


def unsqueeze_(x, axis, name=None):
    return _inplace(x, unsqueeze(x, axis))


def concat(x, axis=0, name=None):
    tensors = list(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply_op("concat", lambda *vs: jnp.concatenate(vs, axis=axis), *tensors)


def stack(x, axis=0, name=None):
    tensors = list(x)
    return apply_op("stack", lambda *vs: jnp.stack(vs, axis=axis), *tensors)


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    dim_size = x._data.shape[axis]
    if isinstance(num_or_sections, int):
        if dim_size % num_or_sections != 0:
            raise ValueError(
                f"The input's size along the split dimension ({dim_size}) must be "
                f"evenly divisible by num_or_sections ({num_or_sections})"
            )
        sections = [dim_size // num_or_sections] * num_or_sections
    else:
        sections = [int(s) for s in num_or_sections]
        if -1 in sections:
            known = builtins_sum(s for s in sections if s != -1)
            sections = [dim_size - known if s == -1 else s for s in sections]
    offsets = np.cumsum([0] + sections)

    def fn(v):
        return tuple(
            jax.lax.slice_in_dim(v, int(offsets[i]), int(offsets[i + 1]), axis=axis)
            for i in range(len(sections))
        )

    return list(apply_op("split", fn, x))


def builtins_sum(it):
    total = 0
    for v in it:
        total += v
    return total


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    n = x._data.shape[axis]

    def fn(v):
        return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(v, n, axis=axis))

    return list(apply_op("unbind", fn, x))


unstack = unbind


def tile(x, repeat_times, name=None):
    reps = _int_list(repeat_times)
    return apply_op("tile", lambda v: jnp.tile(v, reps), x)


def expand(x, shape, name=None):
    shape = _int_list(shape)

    def fn(v):
        target = list(shape)
        offset = len(target) - v.ndim
        for i in range(v.ndim):
            if target[offset + i] == -1:
                target[offset + i] = v.shape[i]
        return jnp.broadcast_to(v, target)

    return apply_op("expand", fn, x)


def expand_as(x, y, name=None):
    return apply_op("expand_as", lambda v, w: jnp.broadcast_to(v, w.shape), x, y)


def broadcast_to(x, shape, name=None):
    shape = _int_list(shape)
    return apply_op("broadcast_to", lambda v: jnp.broadcast_to(v, shape), x)


def broadcast_tensors(inputs, name=None):
    return list(apply_op("broadcast_tensors", lambda *vs: tuple(jnp.broadcast_arrays(*vs)), *inputs))


def flip(x, axis, name=None):
    axes = _int_list(axis)
    return apply_op("flip", lambda v: jnp.flip(v, axis=axes), x)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op("rot90", lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), x)


def roll(x, shifts, axis=None, name=None):
    sh = _int_list(shifts) if not isinstance(shifts, int) else shifts
    ax = _int_list(axis) if axis is not None and not isinstance(axis, int) else axis
    if isinstance(sh, list) and len(sh) == 1:
        sh = sh[0]
    if isinstance(ax, list) and len(ax) == 1:
        ax = ax[0]
    return apply_op("roll", lambda v: jnp.roll(v, sh, axis=ax), x)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        reps = jnp.asarray(repeats.numpy())
        return apply_op(
            "repeat_interleave", lambda v: jnp.repeat(v, reps, axis=axis), x
        )
    return apply_op("repeat_interleave", lambda v: jnp.repeat(v, repeats, axis=axis), x)


# --- gather/scatter family ---
def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply_op("gather", lambda v, i: jnp.take(v, i.reshape(-1) if i.ndim > 1 else i, axis=axis), x, index)


def gather_nd(x, index, name=None):
    def fn(v, idx):
        idx_tuple = tuple(jnp.moveaxis(idx, -1, 0))
        return v[idx_tuple]

    return apply_op("gather_nd", fn, x, index)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply_op(
        "take_along_axis", lambda v, i: jnp.take_along_axis(v, i, axis=axis), arr, indices
    )


def put_along_axis(arr, indices, values, axis, reduce="assign", include_self=True, broadcast=True, name=None):
    def fn(v, i, val):
        val = jnp.broadcast_to(val, i.shape).astype(v.dtype) if not hasattr(val, "shape") or val.shape != i.shape else val.astype(v.dtype)
        dims = list(range(v.ndim))
        idx = [jnp.arange(s).reshape([-1 if d == k else 1 for k in range(v.ndim)]) for d, s in enumerate(i.shape)]
        idx[axis] = i
        full = tuple(jnp.broadcast_to(ix, i.shape) for ix in idx)
        if reduce == "assign":
            return v.at[full].set(val)
        if reduce in ("add", "sum"):
            return v.at[full].add(val)
        if reduce in ("mul", "multiply"):
            return v.at[full].multiply(val)
        if reduce == "amax":
            return v.at[full].max(val)
        if reduce == "amin":
            return v.at[full].min(val)
        raise ValueError(f"unsupported reduce: {reduce}")

    vals = values if isinstance(values, Tensor) else Tensor(jnp.asarray(values))
    return apply_op("put_along_axis", fn, arr, indices, vals)


def scatter(x, index, updates, overwrite=True, name=None):
    def fn(v, i, u):
        i = i.reshape(-1)
        if overwrite:
            return v.at[i].set(u.astype(v.dtype))
        # paddle semantics: zero the target rows then accumulate
        zeroed = v.at[i].set(jnp.zeros_like(u, dtype=v.dtype))
        return zeroed.at[i].add(u.astype(v.dtype))

    return apply_op("scatter", fn, x, index, updates)


def scatter_(x, index, updates, overwrite=True, name=None):
    return _inplace(x, scatter(x, index, updates, overwrite))


def scatter_nd_add(x, index, updates, name=None):
    def fn(v, i, u):
        idx_tuple = tuple(jnp.moveaxis(i, -1, 0))
        return v.at[idx_tuple].add(u.astype(v.dtype))

    return apply_op("scatter_nd_add", fn, x, index, updates)


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros

    base = zeros(shape, dtype=updates.dtype)
    return scatter_nd_add(base, index, updates)


def index_select(x, index, axis=0, name=None):
    return apply_op("index_select", lambda v, i: jnp.take(v, i, axis=axis), x, index)


def index_sample(x, index, name=None):
    return apply_op(
        "index_sample", lambda v, i: jnp.take_along_axis(v, i, axis=1), x, index
    )


def index_add(x, index, axis, value, name=None):
    def fn(v, i, val):
        moved = jnp.moveaxis(v, axis, 0)
        moved = moved.at[i].add(jnp.moveaxis(val, axis, 0).astype(v.dtype))
        return jnp.moveaxis(moved, 0, axis)

    return apply_op("index_add", fn, x, index, value)


def index_put(x, indices, value, accumulate=False, name=None):
    def fn(v, val, *idx):
        if accumulate:
            return v.at[tuple(idx)].add(val.astype(v.dtype))
        return v.at[tuple(idx)].set(val.astype(v.dtype))

    return apply_op("index_put", fn, x, value, *indices)


def index_fill(x, index, axis, value, name=None):
    def fn(v, i):
        moved = jnp.moveaxis(v, axis, 0)
        moved = moved.at[i].set(jnp.asarray(value, v.dtype))
        return jnp.moveaxis(moved, 0, axis)

    return apply_op("index_fill", fn, x, index)


def masked_select(x, mask, name=None):
    # Dynamic output shape: eager-only (like reference's masked_select on GPU).
    return apply_op("masked_select", lambda v: v[np.asarray(mask._data)], x)


def masked_fill(x, mask, value, name=None):
    val = value._data if isinstance(value, Tensor) else value

    def fn(v, m):
        return jnp.where(m, jnp.asarray(val, v.dtype), v)

    return apply_op("masked_fill", fn, x, mask)


def masked_fill_(x, mask, value, name=None):
    return _inplace(x, masked_fill(x, mask, value))


def masked_scatter(x, mask, value, name=None):
    def fn(v, m, val):
        flat_idx = jnp.cumsum(m.reshape(-1).astype(jnp.int32)) - 1
        gathered = jnp.take(val.reshape(-1), jnp.clip(flat_idx, 0, val.size - 1))
        return jnp.where(m, gathered.reshape(v.shape).astype(v.dtype), v)

    return apply_op("masked_scatter", fn, x, mask, value)


# --- slicing ---
def slice(input, axes, starts, ends, name=None):
    axes = _int_list(axes)
    starts = _int_list(starts)
    ends = _int_list(ends)

    def fn(v):
        out = v
        for a, s, e in zip(axes, starts, ends):
            dim = v.shape[a]
            s2 = max(s + dim, 0) if s < 0 else min(s, dim)
            e2 = max(e + dim, 0) if e < 0 else min(e, dim)
            out = jax.lax.slice_in_dim(out, s2, e2, axis=a)
        return out

    return apply_op("slice", fn, input)


def strided_slice(x, axes, starts, ends, strides, name=None):
    axes, starts, ends, strides = map(_int_list, (axes, starts, ends, strides))

    def fn(v):
        idx = [_py_slice(None)] * v.ndim
        for a, s, e, st in zip(axes, starts, ends, strides):
            idx[a] = _py_slice(s, e, st)
        return v[tuple(idx)]

    return apply_op("strided_slice", fn, x)


def crop(x, shape=None, offsets=None, name=None):
    shape = _int_list(shape)
    offsets = _int_list(offsets) if offsets is not None else [0] * len(shape)

    def fn(v):
        starts = offsets
        sizes = [sh if sh != -1 else v.shape[i] - starts[i] for i, sh in enumerate(shape)]
        return jax.lax.dynamic_slice(v, starts, sizes)

    return apply_op("crop", fn, x)


def as_strided(x, shape, stride, offset=0, name=None):
    def fn(v):
        flat = v.reshape(-1)
        idx = np.zeros(shape, dtype=np.int64) + offset
        for d, (s, st) in enumerate(zip(shape, stride)):
            rng = np.arange(s) * st
            idx = idx + rng.reshape([-1 if i == d else 1 for i in range(len(shape))])
        return flat[idx]

    return apply_op("as_strided", fn, x)


def tensordot(x, y, axes=2, name=None):
    ax = axes
    if isinstance(ax, Tensor):
        ax = ax.tolist()
    return apply_op("tensordot", lambda a, b: jnp.tensordot(a, b, axes=ax), x, y)


def atleast_1d(*inputs, name=None):
    outs = [apply_op("atleast_1d", jnp.atleast_1d, x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply_op("atleast_2d", jnp.atleast_2d, x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply_op("atleast_3d", jnp.atleast_3d, x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def unfold(x, axis, size, step, name=None):
    def fn(v):
        n = (v.shape[axis] - size) // step + 1
        idx = (np.arange(n) * step)[:, None] + np.arange(size)[None, :]
        moved = jnp.moveaxis(v, axis, 0)
        out = moved[idx]  # [n, size, ...]
        return jnp.moveaxis(out, (0, 1), (axis, v.ndim))

    return apply_op("unfold", fn, x)


# --- round-4 tensor-surface tail (reference manipulation.py parity) --------

def tensor_split(x, num_or_indices, axis=0, name=None):
    """Split into (possibly unequal) sections — unlike ``split``, the
    sections need not divide the axis (reference manipulation.py
    tensor_split / numpy semantics)."""
    def fn(v):
        return tuple(jnp.array_split(v, num_or_indices, axis=axis))

    return apply_op("tensor_split", fn, x)


def hsplit(x, num_or_indices, name=None):
    def fn(v):
        ax = 0 if v.ndim == 1 else 1
        return tuple(jnp.array_split(v, num_or_indices, axis=ax))

    return apply_op("tensor_split", fn, x)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def reverse(x, axis, name=None):
    """Deprecated-in-reference alias of flip (manipulation.py reverse)."""
    return flip(x, axis)


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    """Write ``y`` onto the selected diagonal (reference manipulation.py
    diagonal_scatter). ``y``'s last dim is the diagonal (the shape
    ``x.diagonal(offset, axis1, axis2)`` returns)."""
    def fn(v, src):
        a1, a2 = axis1 % v.ndim, axis2 % v.ndim
        vm = jnp.moveaxis(v, (a1, a2), (-2, -1))
        i = jnp.arange(src.shape[-1])
        r = i + max(-offset, 0)
        c = i + max(offset, 0)
        out = vm.at[..., r, c].set(src.astype(v.dtype))
        return jnp.moveaxis(out, (-2, -1), (a1, a2))

    return apply_op("diagonal_scatter", fn, x, y)


def select_scatter(x, values, axis, index, name=None):
    """Write ``values`` into position ``index`` along ``axis`` (reference
    manipulation.py select_scatter)."""
    def fn(v, src):
        sel = [_py_slice(None)] * v.ndim
        sel[axis] = index
        return v.at[tuple(sel)].set(src.astype(v.dtype))

    return apply_op("select_scatter", fn, x, values)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    """Write ``value`` into the strided slice (reference manipulation.py
    slice_scatter)."""
    def fn(v, src):
        sel = [_py_slice(None)] * v.ndim
        for ax, s_, e_, st in zip(axes, starts, ends, strides):
            sel[ax] = _py_slice(s_, e_, st)
        return v.at[tuple(sel)].set(src.astype(v.dtype))

    return apply_op("slice_scatter", fn, x, value)


# --- numpy-style stack family (reference manipulation.py:2100-2360) ---
def _as_tensors(x):
    return [t if isinstance(t, Tensor) else Tensor(np.asarray(t)) for t in x]


def hstack(x, name=None):
    """Stack along axis 1 (axis 0 for 1-D inputs) — reference
    python/paddle/tensor/manipulation.py:2100 (np.hstack semantics)."""
    tensors = [atleast_1d(t) for t in _as_tensors(x)]
    axis = 0 if all(t._data.ndim == 1 for t in tensors) else 1
    return concat(tensors, axis=axis)


def vstack(x, name=None):
    """Stack along axis 0 after promoting 1-D rows to (1, N) — reference
    python/paddle/tensor/manipulation.py:2161 (np.vstack semantics)."""
    return concat([atleast_2d(t) for t in _as_tensors(x)], axis=0)


row_stack = vstack


def dstack(x, name=None):
    """Stack along the third axis, promoting to 3-D first — reference
    python/paddle/tensor/manipulation.py:2210 (np.dstack semantics)."""
    return concat([atleast_3d(t) for t in _as_tensors(x)], axis=2)


def column_stack(x, name=None):
    """Stack 1-D tensors as columns of a 2-D result — reference
    python/paddle/tensor/manipulation.py:2276 (np.column_stack semantics)."""
    cols = [reshape(t, [-1, 1]) if t._data.ndim < 2 else t
            for t in _as_tensors(x)]
    return concat(cols, axis=1)


def cast(x, dtype, name=None):
    """paddle.cast: dtype conversion as a differentiable op (reference
    python/paddle/tensor/manipulation.py cast). The in-place spellings
    (cast_, masked_scatter_, ...) live in tensor/inplace.py."""
    return x.astype(dtype)


def tolist(x, name=None):
    """paddle.tolist: nested python list of the tensor's values (reference
    tensor/manipulation.py tolist)."""
    return x.tolist()
