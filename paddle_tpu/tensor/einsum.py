"""einsum (paddle.einsum parity; python/paddle/tensor/einsum.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..autograd.engine import apply_op


def einsum(equation, *operands, name=None):
    return apply_op("einsum", lambda *ops: jnp.einsum(equation, *ops), *operands)
