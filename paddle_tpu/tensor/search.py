"""Search / sort / sampling-index ops.

Parity: python/paddle/tensor/search.py. Ops with data-dependent output shapes
(nonzero, unique, masked_select) are eager-only, matching the reference's note
that these break static graphs too.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..autograd.engine import apply_op
from .tensor import Tensor


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def fn(v):
        if axis is None:
            out = jnp.argmax(v.reshape(-1))
            return out.reshape((1,) * v.ndim) if keepdim else out
        out = jnp.argmax(v, axis=axis)
        return jnp.expand_dims(out, axis) if keepdim else out

    return apply_op("argmax", fn, x).astype(dtype)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def fn(v):
        if axis is None:
            out = jnp.argmin(v.reshape(-1))
            return out.reshape((1,) * v.ndim) if keepdim else out
        out = jnp.argmin(v, axis=axis)
        return jnp.expand_dims(out, axis) if keepdim else out

    return apply_op("argmin", fn, x).astype(dtype)


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def fn(v):
        idx = jnp.argsort(v, axis=axis, stable=stable, descending=descending)
        return idx.astype(jnp.int64)

    return apply_op("argsort", fn, x)


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def fn(v):
        return jnp.sort(v, axis=axis, descending=descending, stable=stable)

    return apply_op("sort", fn, x)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())

    def fn(v):
        ax = -1 if axis is None else axis
        moved = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(moved, k)
        else:
            vals, idx = jax.lax.top_k(-moved, k)
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype(jnp.int64), -1, ax)

    return apply_op("topk", fn, x)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def fn(v):
        sorted_v = jnp.sort(v, axis=axis)
        sorted_i = jnp.argsort(v, axis=axis)
        vals = jnp.take(sorted_v, k - 1, axis=axis)
        idx = jnp.take(sorted_i, k - 1, axis=axis).astype(jnp.int64)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx

    return apply_op("kthvalue", fn, x)


def mode(x, axis=-1, keepdim=False, name=None):
    def fn(v):
        sorted_v = jnp.sort(v, axis=axis)
        n = v.shape[axis]
        # mode = value with the longest run in sorted order
        moved = jnp.moveaxis(sorted_v, axis, -1)
        eq = moved[..., 1:] == moved[..., :-1]
        run = jnp.concatenate([jnp.zeros_like(moved[..., :1], dtype=jnp.int32),
                               jnp.cumsum(eq, axis=-1, dtype=jnp.int32)], axis=-1)
        # reset cumulative count at run boundaries
        reset = jnp.where(eq, 0, jnp.arange(1, n, dtype=jnp.int32))
        start = jax.lax.associative_scan(jnp.maximum, jnp.concatenate(
            [jnp.zeros_like(moved[..., :1], dtype=jnp.int32), reset], axis=-1), axis=-1)
        length = jnp.arange(n, dtype=jnp.int32) - start
        best = jnp.argmax(length, axis=-1)
        vals = jnp.take_along_axis(moved, best[..., None], axis=-1)[..., 0]
        idx_sorted = jnp.moveaxis(jnp.argsort(v, axis=axis), axis, -1)
        idx = jnp.take_along_axis(idx_sorted, best[..., None], axis=-1)[..., 0].astype(jnp.int64)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx

    return apply_op("mode", fn, x)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply_op("where", lambda c, a, b: jnp.where(c, a, b), condition, x, y)


def where_(condition, x, y, name=None):
    from .manipulation import _inplace

    return _inplace(x, where(condition, x, y))


def nonzero(x, as_tuple=False):
    arr = np.asarray(x._data)
    idx = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i.astype(np.int64))[:, None]) for i in idx)
    return Tensor(jnp.asarray(np.stack(idx, axis=1).astype(np.int64)))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"

    def fn(seq, v):
        if seq.ndim == 1:
            out = jnp.searchsorted(seq, v, side=side)
        else:
            out = jax.vmap(lambda s, w: jnp.searchsorted(s, w, side=side))(
                seq.reshape(-1, seq.shape[-1]), v.reshape(-1, v.shape[-1])
            ).reshape(v.shape)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)

    return apply_op("searchsorted", fn, sorted_sequence, values)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    arr = np.asarray(x._data)
    res = np.unique(arr, return_index=True, return_inverse=True, return_counts=True, axis=axis)
    vals, index, inverse, counts = res
    outs = [Tensor(jnp.asarray(vals))]
    if return_index:
        outs.append(Tensor(jnp.asarray(index.astype(np.int64))))
    if return_inverse:
        outs.append(Tensor(jnp.asarray(inverse.astype(np.int64))))
    if return_counts:
        outs.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return outs[0] if len(outs) == 1 else tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    arr = np.asarray(x._data)
    if axis is None:
        arr = arr.reshape(-1)
        axis = 0
    moved = np.moveaxis(arr, axis, 0)
    keep = np.ones(moved.shape[0], dtype=bool)
    if moved.shape[0] > 1:
        flat = moved.reshape(moved.shape[0], -1)
        keep[1:] = np.any(flat[1:] != flat[:-1], axis=1)
    vals = np.moveaxis(moved[keep], 0, axis)
    outs = [Tensor(jnp.asarray(vals))]
    if return_inverse:
        inverse = np.cumsum(keep) - 1
        outs.append(Tensor(jnp.asarray(inverse.astype(np.int64))))
    if return_counts:
        idx = np.nonzero(keep)[0]
        counts = np.diff(np.append(idx, moved.shape[0]))
        outs.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return outs[0] if len(outs) == 1 else tuple(outs)


def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None):
    arr = np.asarray(input._data)
    lo, hi = (float(arr.min()), float(arr.max())) if min == 0 and max == 0 else (min, max)
    w = np.asarray(weight._data) if weight is not None else None
    hist, _ = np.histogram(arr, bins=bins, range=(lo, hi), weights=w, density=density)
    return Tensor(jnp.asarray(hist if density or w is not None else hist.astype(np.int64)))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    arr = np.asarray(x._data)
    w = np.asarray(weights._data) if weights is not None else None
    hist, edges = np.histogramdd(arr, bins=bins, range=ranges, density=density, weights=w)
    return Tensor(jnp.asarray(hist)), [Tensor(jnp.asarray(e)) for e in edges]


def bincount(x, weights=None, minlength=0, name=None):
    arr = np.asarray(x._data)
    w = np.asarray(weights._data) if weights is not None else None
    return Tensor(jnp.asarray(np.bincount(arr, weights=w, minlength=minlength)))


def index_fill_(x, index, axis, value, name=None):
    from .manipulation import _inplace, index_fill

    return _inplace(x, index_fill(x, index, axis, value))
