"""Module-level in-place op spellings (``paddle.abs_(x)`` etc.).

The reference exports every Tensor in-place method as a top-level function
too (python/paddle/__init__.py __all__: abs_, acos_, ... trunc_). The Tensor
methods are generated in tensor/__init__.py (_INPLACE_BASES); this module
lifts each one to a module function so the top-level surface matches.
"""
from __future__ import annotations

from .tensor import Tensor

# every name here must exist as a Tensor method by the time the wrapper is
# CALLED (binding is late), which tensor/__init__.py guarantees at import
_INPLACE_NAMES = """
abs_ acos_ acosh_ addmm_ asin_ asinh_ atan_ atanh_ bitwise_and_
bitwise_left_shift_ bitwise_not_ bitwise_or_ bitwise_right_shift_
bitwise_xor_ cast_ ceil_ clip_ copysign_ cos_ cosh_ cumprod_ cumsum_
digamma_ divide_ equal_ erf_ erfinv_ exp_ expm1_ floor_ floor_divide_
floor_mod_ frac_ gammaln_ gcd_ greater_equal_ greater_than_ hypot_ i0_
index_add_ index_put_ lcm_ ldexp_ lerp_ less_equal_ less_than_ lgamma_
log10_ log1p_ log2_ log_ logical_and_ logical_not_ logical_or_
logical_xor_ logit_ masked_fill_ masked_scatter_ mod_ multigammaln_
multiply_ nan_to_num_ neg_ not_equal_ polygamma_ pow_ reciprocal_
remainder_ renorm_ round_ rsqrt_ scale_ sigmoid_ sin_ sinh_ sqrt_
square_ subtract_ t_ tan_ tanh_ tril_ triu_ trunc_ where_ zero_
""".split()

__all__ = list(_INPLACE_NAMES)


def _make_module_inplace(method_name):
    def fn(x, *args, **kwargs):
        return getattr(x, method_name)(*args, **kwargs)

    fn.__name__ = method_name
    fn.__qualname__ = method_name
    fn.__doc__ = (f"In-place variant: ``paddle.{method_name}(x, ...)`` == "
                  f"``x.{method_name}(...)`` (rebinds x's data in place).")
    return fn


for _n in _INPLACE_NAMES:
    globals()[_n] = _make_module_inplace(_n)
del _n
