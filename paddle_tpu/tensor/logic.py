"""Comparison / logical / bitwise ops.

Parity: python/paddle/tensor/logic.py.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..autograd.engine import apply_op, make_op
from .tensor import Tensor

equal = make_op("equal", jnp.equal)
not_equal = make_op("not_equal", jnp.not_equal)
greater_than = make_op("greater_than", jnp.greater)
greater_equal = make_op("greater_equal", jnp.greater_equal)
less_than = make_op("less_than", jnp.less)
less_equal = make_op("less_equal", jnp.less_equal)

logical_and = make_op("logical_and", jnp.logical_and)
logical_or = make_op("logical_or", jnp.logical_or)
logical_xor = make_op("logical_xor", jnp.logical_xor)
logical_not = make_op("logical_not", jnp.logical_not)

bitwise_and = make_op("bitwise_and", jnp.bitwise_and)
bitwise_or = make_op("bitwise_or", jnp.bitwise_or)
bitwise_xor = make_op("bitwise_xor", jnp.bitwise_xor)
bitwise_not = make_op("bitwise_not", jnp.bitwise_not)
bitwise_invert = bitwise_not
bitwise_left_shift = make_op("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = make_op("bitwise_right_shift", jnp.right_shift)


def equal_all(x, y, name=None) -> Tensor:
    return apply_op(
        "equal_all",
        lambda a, b: jnp.asarray(a.shape == b.shape) & jnp.all(a == b)
        if a.shape == b.shape
        else jnp.asarray(False),
        x,
        y,
    )


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None) -> Tensor:
    return apply_op(
        "isclose", lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), x, y
    )


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None) -> Tensor:
    return apply_op(
        "allclose", lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), x, y
    )


def is_empty(x, name=None) -> Tensor:
    return Tensor(jnp.asarray(x._data.size == 0))


# --- dtype predicates (reference tensor/attribute.py parity) ---------------

def is_complex(x):
    return jnp.issubdtype(x._data.dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(x._data.dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(x._data.dtype, jnp.integer)
