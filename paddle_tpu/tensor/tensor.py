"""The eager Tensor: a define-by-run handle over a jax.Array.

Parity target: paddle.Tensor (reference: paddle/phi/api/include/tensor.h:82 +
~300 python-patched methods, python/paddle/base/dygraph/tensor_patch_methods.py:78).
The TPU-native design keeps the handle thin: data is an immutable jax.Array
(possibly sharded across a Mesh — that's what makes it a "DistTensor"), and
autograd state lives on the handle. Most methods are bound by the op modules
via ``register_tensor_method``.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework import dtype as dtype_mod
from ..framework.place import CPUPlace, Place, TPUPlace, _expected_place


class _PrintOptions:
    """Process-wide tensor print options (reference tensor/to_string.py:25)."""

    precision = 8
    threshold = 1000
    edgeitems = 3
    linewidth = 80
    sci_mode = False


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Set Tensor printing options (reference tensor/to_string.py:35)."""
    for name, value, kind in (("precision", precision, int),
                              ("threshold", threshold, int),
                              ("edgeitems", edgeitems, int),
                              ("linewidth", linewidth, int),
                              ("sci_mode", sci_mode, bool)):
        if value is not None:
            if not isinstance(value, kind):
                raise TypeError(
                    f"set_printoptions: {name} must be {kind.__name__}, "
                    f"got {type(value).__name__}")
            setattr(_PrintOptions, name, value)


def _format_array(arr) -> str:
    o = _PrintOptions
    kwargs = dict(precision=o.precision, threshold=o.threshold,
                  edgeitems=o.edgeitems, linewidth=o.linewidth,
                  suppress=not o.sci_mode)
    if o.sci_mode and arr.dtype.kind in "fc":
        def _sci(v):
            return np.format_float_scientific(v, precision=o.precision)

        kwargs["formatter"] = {
            "float_kind": _sci,
            "complex_kind": lambda v: f"{_sci(v.real)}+{_sci(v.imag)}j",
        }
        kwargs.pop("suppress")
    with np.printoptions(**kwargs):
        return str(arr)


def _coerce_data(data, dtype=None):
    if isinstance(data, Tensor):
        data = data._data
    if isinstance(data, jax.ShapeDtypeStruct):
        # lazy-init placeholder (nn/initializer/lazy_init.py): abstract aval,
        # shape/dtype queries work, compute raises until .initialize()
        return data
    if isinstance(data, (jax.Array, jax.core.Tracer)):
        if dtype is not None:
            want = dtype_mod.to_jax_dtype(dtype)
            if data.dtype != want:
                data = data.astype(want)
        return data
    arr = np.asarray(data)
    if dtype is not None:
        arr = arr.astype(dtype_mod.to_jax_dtype(dtype))
    elif arr.dtype == np.float64:
        # Match the framework default dtype for python floats/np float64 input.
        from ..framework import config

        arr = arr.astype(dtype_mod.to_jax_dtype(config.get_default_dtype()))
    return jnp.asarray(arr)


class Tensor:
    __slots__ = (
        "_data",
        "stop_gradient",
        "_grad_node",
        "_out_index",
        "grad",
        "name",
        "persistable",
        "_hooks",
        "_hook_counter",
        "_placements",
        "__weakref__",
        "__dict__",
    )

    def __init__(self, data, dtype=None, place=None, stop_gradient: bool = True, name=None):
        self._data = _coerce_data(data, dtype)
        self.stop_gradient = bool(stop_gradient)
        self._grad_node = None
        self._out_index = 0
        self.grad = None
        # Monotonic counter, not id(): names must be stable across process
        # restarts (optimizer state_dict keys are derived from them).
        self.name = name or f"generated_tensor_{_next_name_index()}"
        self.persistable = False
        self._hooks = {}
        self._hook_counter = 0
        self._placements = None  # set for DistTensor (distributed.auto_parallel)

    # --- basic properties ---
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    ndimension = lambda self: self._data.ndim
    dim = ndimension

    @property
    def size(self):
        return int(self._data.size)

    @property
    def dtype(self) -> dtype_mod.DType:
        return dtype_mod.convert_dtype(self._data.dtype)

    @property
    def place(self) -> Place:
        data = self._data
        if isinstance(data, jax.core.Tracer):
            return _expected_place()
        try:
            dev = list(data.devices())[0]
        except Exception:
            return _expected_place()
        return CPUPlace(dev.id) if dev.platform == "cpu" else TPUPlace(dev.id)

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    @property
    def is_dist(self) -> bool:
        return self._placements is not None

    @property
    def T(self):
        # paddle semantics: reverse ALL axes (numpy-style), not just the last two.
        from ..autograd.engine import apply_op

        if self.ndim < 2:
            return self
        return apply_op("transpose_all", lambda v: v.T, self)

    @property
    def mT(self):
        from ..autograd.engine import apply_op

        return apply_op("mT", lambda v: jnp.swapaxes(v, -1, -2), self)

    # (.real()/.imag() are bound as methods by tensor/__init__.py, matching
    #  paddle's method spelling rather than torch's property spelling.)

    # --- conversions ---
    def numpy(self) -> np.ndarray:
        if isinstance(self._data, jax.ShapeDtypeStruct):
            raise RuntimeError(
                f"Tensor {self.name!r} was created under LazyGuard and has no "
                "value yet — call .initialize() (or lazy_init.materialize on "
                "the layer) before reading it")
        return np.asarray(self._data)

    def __array__(self, dtype=None):
        arr = self.numpy()
        return arr.astype(dtype) if dtype is not None else arr

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def astype(self, dtype):
        from ..autograd.engine import apply_op

        want = dtype_mod.to_jax_dtype(dtype)
        return apply_op("cast", lambda x: x.astype(want), self)

    cast = astype

    def cpu(self):
        out = Tensor(jax.device_put(self._data, jax.devices("cpu")[0]))
        out.stop_gradient = self.stop_gradient
        return out

    def to(self, *args, **kwargs):
        t = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, (str, Place)) and not isinstance(a, str) or (
                isinstance(a, str) and a.split(":")[0] in ("cpu", "tpu", "gpu", "xpu")
            ):
                continue  # device moves are no-ops inside one backend
            try:
                t = t.astype(a)
            except (TypeError, ValueError):
                pass
        return t

    # --- autograd surface ---
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        from ..autograd.backward import run_backward

        run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def detach(self) -> "Tensor":
        import jax

        # lax.stop_gradient in addition to the tape-level flag: under an
        # outer jax transformation (to_static / functional training steps the
        # tape is off for), detach must cut the jax graph too — otherwise
        # grads silently flow through "detached" values.
        out = Tensor(jax.lax.stop_gradient(self._data), stop_gradient=True)
        out._placements = self._placements
        return out

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from ..autograd.engine import apply_op

        return apply_op("clone", lambda x: x + 0, self)

    def register_hook(self, hook):
        self._hook_counter += 1
        hook_id = self._hook_counter
        self._hooks[hook_id] = hook

        class _Handle:
            def remove(inner):
                self._hooks.pop(hook_id, None)

        return _Handle()

    def clear_grad(self, set_to_zero: bool = False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad._data))
        else:
            self.grad = None

    clear_gradient = clear_grad

    def retain_grads(self):
        # Non-leaf grad retention: install a hook that stores the grad.
        if self.is_leaf:
            return

        def _store(g):
            self.grad = g.detach() if isinstance(g, Tensor) else Tensor(g)
            return None

        self.register_hook(_store)

    # --- mutation (functional under the hood; autograd-safe) ---
    def set_value(self, value):
        new = _coerce_data(value, None)
        if tuple(new.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch: {tuple(new.shape)} vs {tuple(self._data.shape)}"
            )
        if new.dtype != self._data.dtype:
            new = new.astype(self._data.dtype)
        self._data = new
        return self

    def copy_(self, other, *args):
        return self.set_value(other)

    def fill_(self, value):
        self._data = jnp.full_like(self._data, value)
        return self

    def zero_(self):
        return self.fill_(0)

    # --- python protocol ---
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __bool__(self):
        return bool(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __float__(self):
        return float(self.numpy())

    def __index__(self):
        return int(self.numpy())

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        grad_part = "" if self.stop_gradient else ", stop_gradient=False"
        if isinstance(self._data, jax.ShapeDtypeStruct):
            return (
                f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
                f"{grad_part}, lazy=uninitialized (LazyGuard))"
            )
        if isinstance(self._data, jax.core.Tracer):
            return (
                f"Tensor(shape={self.shape}, dtype={self.dtype.name}{grad_part}, "
                f"traced={self._data})"
            )
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
            f"place={self.place}{grad_part},\n       "
            f"{_format_array(np.asarray(self.numpy()))})"
        )

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return repr(self)

    def __dlpack__(self, *a, **k):
        return self._data.__dlpack__(*a, **k)

    # element_size / nbytes
    def element_size(self):
        return self.dtype.itemsize

    @property
    def nbytes(self):
        return self.size * self.element_size()

    def numel(self):
        return self.size

    def is_contiguous(self):
        return True

    def contiguous(self):
        return self

    def pin_memory(self):
        return self

    def cuda(self, *a, **k):
        return self

    def value(self):
        return self

    def get_tensor(self):
        return self

    def _is_initialized(self):
        return True

    # Distributed surface (filled by paddle_tpu.distributed):
    @property
    def placements(self):
        return self._placements

    @property
    def process_mesh(self):
        if self._placements is None:
            return None
        from ..distributed.auto_parallel.api import _mesh_of

        return _mesh_of(self)


_name_counter = [0]
_param_counter = [0]


def _next_name_index() -> int:
    _name_counter[0] += 1
    return _name_counter[0]


def register_tensor_method(name: str, fn):
    """Bind a function as a Tensor method (tensor_patch_methods parity)."""
    setattr(Tensor, name, fn)


class Parameter(Tensor):
    """A trainable Tensor (paddle.base.framework.EagerParamBase parity)."""

    def __init__(self, data, dtype=None, trainable: bool = True, name=None):
        if name is None:
            # Deterministic creation-order names (param_0, param_1, ...):
            # rebuilding the same model in a fresh process reproduces them, so
            # optimizer state_dict keys survive checkpoints.
            name = f"param_{_param_counter[0]}"
            _param_counter[0] += 1
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable
        self._lazy_init = None  # (init_fn, shape, dtype) under LazyGuard

    def initialize(self):
        """Materialize a lazily-created parameter (reference EagerParamBase
        initialize under LazyGuard). No-op if already materialized."""
        if self._lazy_init is None:
            return self
        init, shape, dtype = self._lazy_init
        self._lazy_init = None
        self._data = _coerce_data(init(shape, dtype), None)
        return self

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, value):
        self.stop_gradient = not value

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()
