"""Random sampling ops.

Parity: python/paddle/tensor/random.py. All draws consume keys from the global
default_generator (framework/random.py) so seeding/reproducibility matches
paddle.seed semantics. Keys are passed through ``apply_op`` as ``RngKey``
arguments (not closed over), so jit tracing threads them as inputs and the
static recorder replaces them with per-run rng slots — an ``Executor.run``
replay re-draws like the reference's gaussian_random/uniform_random ops do
per execution (phi/kernels/gpu/gaussian_kernel.cu seed handling).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..autograd.engine import apply_op
from ..framework import config
from ..framework import dtype as dtype_mod
from ..framework.random import default_generator, rng_arg
from .creation import _shape_list
from .tensor import Tensor


def _resolve(dtype):
    if dtype is None:
        return dtype_mod.to_jax_dtype(config.get_default_dtype())
    return dtype_mod.to_jax_dtype(dtype)


def rand(shape, dtype=None, name=None) -> Tensor:
    shape, jdt = _shape_list(shape), _resolve(dtype)
    return apply_op(
        "uniform", lambda key: jax.random.uniform(key, shape, jdt), rng_arg())


def randn(shape, dtype=None, name=None) -> Tensor:
    shape, jdt = _shape_list(shape), _resolve(dtype)
    return apply_op(
        "gaussian", lambda key: jax.random.normal(key, shape, jdt), rng_arg())


def standard_normal(shape, dtype=None, name=None) -> Tensor:
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None) -> Tensor:
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        def fn(m, s, key):
            out_shape = np.broadcast_shapes(jnp.shape(m), jnp.shape(s))
            return jax.random.normal(key, out_shape) * s + m

        return apply_op("gaussian", fn, mean, std, rng_arg())
    out_shape = _shape_list(shape) if shape is not None else []
    return apply_op(
        "gaussian",
        lambda key: jax.random.normal(key, out_shape) * std + mean,
        rng_arg())


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None) -> Tensor:
    shape, jdt = _shape_list(shape), _resolve(dtype)
    karg = rng_arg() if seed == 0 else jax.random.key(seed)
    return apply_op(
        "gaussian",
        lambda key: jax.random.normal(key, shape, jdt) * std + mean, karg)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None) -> Tensor:
    shape, jdt = _shape_list(shape), _resolve(dtype)
    karg = rng_arg() if seed == 0 else jax.random.key(seed)
    return apply_op(
        "uniform",
        lambda key: jax.random.uniform(key, shape, jdt, minval=min, maxval=max),
        karg)


def randint(low=0, high=None, shape=[1], dtype="int64", name=None) -> Tensor:
    if high is None:
        low, high = 0, low
    shape, jdt = _shape_list(shape), dtype_mod.to_jax_dtype(dtype)
    return apply_op(
        "randint",
        lambda key: jax.random.randint(key, shape, low, high).astype(jdt),
        rng_arg())


def randint_like(x, low=0, high=None, dtype=None, name=None) -> Tensor:
    if high is None:
        low, high = 0, low
    want = dtype_mod.to_jax_dtype(dtype) if dtype is not None else x._data.dtype
    shape = x._data.shape
    return apply_op(
        "randint",
        lambda key: jax.random.randint(key, shape, low, high).astype(want),
        rng_arg())


def randperm(n, dtype="int64", name=None) -> Tensor:
    jdt = dtype_mod.to_jax_dtype(dtype)
    return apply_op(
        "randperm",
        lambda key: jax.random.permutation(key, n).astype(jdt), rng_arg())


def shuffle(x, axis=0):
    return apply_op(
        "shuffle",
        lambda v, key: jax.random.permutation(key, v, axis=axis),
        x, rng_arg())


def bernoulli(x, name=None) -> Tensor:
    return apply_op(
        "bernoulli",
        lambda p, key: jax.random.bernoulli(
            key, p.astype(jnp.float32)).astype(p.dtype),
        x, rng_arg())


def bernoulli_(x, p=0.5, name=None):
    key = default_generator.next_key()
    x._data = jax.random.bernoulli(key, p, x._data.shape).astype(x._data.dtype)
    return x


def poisson(x, name=None) -> Tensor:
    return apply_op(
        "poisson",
        lambda lam, key: jax.random.poisson(
            key, lam.astype(jnp.float32)).astype(lam.dtype),
        x, rng_arg())


def multinomial(x, num_samples=1, replacement=False, name=None) -> Tensor:
    key = default_generator.next_key()

    def fn(probs):
        p = probs / jnp.sum(probs, axis=-1, keepdims=True)
        if probs.ndim == 1:
            return jax.random.choice(
                key, probs.shape[-1], (num_samples,), replace=replacement, p=p
            ).astype(jnp.int64)
        keys = jax.random.split(key, probs.shape[0])
        return jnp.stack(
            [
                jax.random.choice(k, probs.shape[-1], (num_samples,), replace=replacement, p=pp)
                for k, pp in zip(keys, p)
            ]
        ).astype(jnp.int64)

    return Tensor(fn(x._data))


def rand_like(x, dtype=None, name=None):
    want = dtype_mod.to_jax_dtype(dtype) if dtype is not None else x._data.dtype
    shape = x._data.shape
    return apply_op(
        "uniform", lambda key: jax.random.uniform(key, shape, want), rng_arg())


def randn_like(x, dtype=None, name=None):
    want = dtype_mod.to_jax_dtype(dtype) if dtype is not None else x._data.dtype
    shape = x._data.shape
    return apply_op(
        "gaussian", lambda key: jax.random.normal(key, shape, want), rng_arg())


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    key = default_generator.next_key()
    x._data = jax.random.uniform(key, x._data.shape, x._data.dtype, minval=min, maxval=max)
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    key = default_generator.next_key()
    x._data = jax.random.normal(key, x._data.shape, x._data.dtype) * std + mean
    return x


def exponential_(x, lam=1.0, name=None):
    key = default_generator.next_key()
    x._data = (jax.random.exponential(key, x._data.shape) / lam).astype(x._data.dtype)
    return x


def binomial(count, prob, name=None):
    def fn(n, p, key):
        # jax 0.4.37's binomial sampler builds weak-typed float constants
        # that promote to f64 under jax_enable_x64 while the operand stays
        # f32 (lax.clamp dtype mismatch); sampling in the x64-matched dtype
        # keeps its internals consistent in both eager and jit.
        calc = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        return jax.random.binomial(
            key, n.astype(calc), p.astype(calc)).astype(jnp.int64)

    return apply_op("binomial", fn, count, prob, rng_arg())


def top_p_sampling(x, ps, threshold=None, topp_seed=None, seed=-1,
                   k=0, mode="truncated", return_top=False, name=None):
    """Nucleus (top-p) sampling (reference: phi top_p_sampling kernel).

    x [bsz, vocab] probabilities, ps [bsz] per-row cutoff. Keeps the
    smallest prefix of the descending-sorted probs whose mass reaches p,
    renormalizes, samples one token per row. Returns (scores [bsz, 1],
    ids [bsz, 1])."""
    if threshold is not None or k not in (0, None) or mode != "truncated" \
            or return_top:
        raise NotImplementedError(
            "top_p_sampling: only the default truncated top-p mode is "
            "implemented (threshold/k/mode/return_top unsupported)")
    karg = (jax.random.key(seed) if seed not in (-1, None)
            else rng_arg())

    def fn(probs, p, key):
        sort_idx = jnp.argsort(-probs, axis=-1)
        sorted_p = jnp.take_along_axis(probs, sort_idx, axis=-1)
        cum = jnp.cumsum(sorted_p, axis=-1)
        # keep tokens whose PRECEDING mass < p (always keep the first)
        keep = (cum - sorted_p) < p[:, None]
        trunc = jnp.where(keep, sorted_p, 0.0)
        trunc = trunc / jnp.sum(trunc, axis=-1, keepdims=True)
        pick = jax.random.categorical(key, jnp.log(trunc + 1e-30), axis=-1)
        ids = jnp.take_along_axis(sort_idx, pick[:, None], axis=-1)
        scores = jnp.take_along_axis(probs, ids, axis=-1)
        return scores, ids.astype(jnp.int64)

    return apply_op("top_p_sampling", fn, x, ps, karg)


def standard_gamma(x, name=None) -> Tensor:
    """Sample Gamma(alpha=x, scale=1) elementwise (reference random.py:219).

    Differentiable w.r.t. the concentration via jax.random.gamma's implicit
    reparameterization (same property the reference's kernel exposes).
    """
    if not x.dtype.is_floating:
        raise TypeError(
            f"standard_gamma expects a floating dtype, got {x.dtype.name}")

    def fn(v, key):
        # sample at >= f32 precision; half dtypes round-trip through f32
        calc = v.dtype if v.dtype == jnp.float64 else jnp.float32
        return jax.random.gamma(key, v.astype(calc)).astype(v.dtype)

    return apply_op("standard_gamma", fn, x, rng_arg())


def cauchy_(x, loc=0, scale=1, name=None):
    """Fill ``x`` in place with Cauchy(loc, scale) samples (reference
    creation.py:2842)."""
    key = default_generator.next_key()
    x._data = (jax.random.cauchy(key, x._data.shape) * scale + loc).astype(
        x._data.dtype)
    return x


def geometric_(x, probs, name=None):
    """Fill ``x`` in place with Geometric(probs) samples (reference
    creation.py:2876).

    Reference parity: the raw CONTINUOUS inversion ``log(u) / log1p(-p)``
    — an Exponential(rate=-log(1-p)) variate whose ceiling would be the
    integer trial count. The reference returns the un-ceiled values, so a
    discrete support {1, 2, ...} here (the previous ceil+clamp) diverged
    from it; ``ceil`` the result for the textbook discrete geometric.
    """
    from .tensor import Tensor as _T

    p = probs._data if isinstance(probs, _T) else jnp.asarray(probs)
    if np.any(np.asarray(p) <= 0) or np.any(np.asarray(p) > 1):
        raise ValueError("geometric_: probs must be in (0, 1]")
    key = default_generator.next_key()
    u = jax.random.uniform(key, x._data.shape, jnp.float32,
                           minval=jnp.finfo(jnp.float32).tiny, maxval=1.0)
    # p == 1: log1p(-1) = -inf gives ratio +0.0 — the degenerate
    # success-on-first-trial case collapses to 0, matching the reference
    samples = jnp.log(u) / jnp.log1p(-p)
    x._data = samples.astype(x._data.dtype)
    return x


def check_shape(shape):
    """Validate a shape argument before fill_constant-style creation ops
    (reference base/data_feeder.py check_shape, exported as paddle.check_shape)."""
    from .tensor import Tensor as _T

    if isinstance(shape, _T):
        if shape.dtype.name not in ("int32", "int64"):
            raise TypeError(
                "Shape tensor dtype must be int32 or int64, got "
                f"{shape.dtype.name}")
        return
    for ele in shape:
        if not isinstance(ele, _T):
            if not isinstance(ele, (int, np.integer)):
                raise TypeError(
                    "All elements in ``shape`` must be integers when it's a "
                    "list or tuple")
            if ele < 0:
                raise ValueError(
                    "All elements in ``shape`` must be positive when it's a "
                    "list or tuple")
