"""Random sampling ops.

Parity: python/paddle/tensor/random.py. All draws consume keys from the global
default_generator (framework/random.py) so seeding/reproducibility matches
paddle.seed semantics, and jit tracing can thread keys as inputs.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..autograd.engine import apply_op
from ..framework import config
from ..framework import dtype as dtype_mod
from ..framework.random import default_generator
from .creation import _shape_list
from .tensor import Tensor


def _resolve(dtype):
    if dtype is None:
        return dtype_mod.to_jax_dtype(config.get_default_dtype())
    return dtype_mod.to_jax_dtype(dtype)


def rand(shape, dtype=None, name=None) -> Tensor:
    key = default_generator.next_key()
    return Tensor(jax.random.uniform(key, _shape_list(shape), _resolve(dtype)))


def randn(shape, dtype=None, name=None) -> Tensor:
    key = default_generator.next_key()
    return Tensor(jax.random.normal(key, _shape_list(shape), _resolve(dtype)))


def standard_normal(shape, dtype=None, name=None) -> Tensor:
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None) -> Tensor:
    key = default_generator.next_key()
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        out_shape = np.broadcast_shapes(
            np.shape(m) if not isinstance(m, jax.Array) else m.shape,
            np.shape(s) if not isinstance(s, jax.Array) else s.shape,
        )
        return Tensor(jax.random.normal(key, out_shape) * s + m)
    shape = _shape_list(shape) if shape is not None else []
    return Tensor(jax.random.normal(key, shape) * std + mean)


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None) -> Tensor:
    key = default_generator.next_key() if seed == 0 else jax.random.key(seed)
    return Tensor(jax.random.normal(key, _shape_list(shape), _resolve(dtype)) * std + mean)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None) -> Tensor:
    key = default_generator.next_key() if seed == 0 else jax.random.key(seed)
    return Tensor(
        jax.random.uniform(key, _shape_list(shape), _resolve(dtype), minval=min, maxval=max)
    )


def randint(low=0, high=None, shape=[1], dtype="int64", name=None) -> Tensor:
    if high is None:
        low, high = 0, low
    key = default_generator.next_key()
    return Tensor(
        jax.random.randint(key, _shape_list(shape), low, high).astype(
            dtype_mod.to_jax_dtype(dtype)
        )
    )


def randint_like(x, low=0, high=None, dtype=None, name=None) -> Tensor:
    if high is None:
        low, high = 0, low
    key = default_generator.next_key()
    want = dtype_mod.to_jax_dtype(dtype) if dtype is not None else x._data.dtype
    return Tensor(jax.random.randint(key, x._data.shape, low, high).astype(want))


def randperm(n, dtype="int64", name=None) -> Tensor:
    key = default_generator.next_key()
    return Tensor(jax.random.permutation(key, n).astype(dtype_mod.to_jax_dtype(dtype)))


def shuffle(x, axis=0):
    key = default_generator.next_key()
    return apply_op("shuffle", lambda v: jax.random.permutation(key, v, axis=axis), x)


def bernoulli(x, name=None) -> Tensor:
    key = default_generator.next_key()
    return apply_op(
        "bernoulli",
        lambda p: jax.random.bernoulli(key, p.astype(jnp.float32)).astype(p.dtype),
        x,
    )


def bernoulli_(x, p=0.5, name=None):
    key = default_generator.next_key()
    x._data = jax.random.bernoulli(key, p, x._data.shape).astype(x._data.dtype)
    return x


def poisson(x, name=None) -> Tensor:
    key = default_generator.next_key()
    return apply_op(
        "poisson", lambda lam: jax.random.poisson(key, lam.astype(jnp.float32)).astype(lam.dtype), x
    )


def multinomial(x, num_samples=1, replacement=False, name=None) -> Tensor:
    key = default_generator.next_key()

    def fn(probs):
        p = probs / jnp.sum(probs, axis=-1, keepdims=True)
        if probs.ndim == 1:
            return jax.random.choice(
                key, probs.shape[-1], (num_samples,), replace=replacement, p=p
            ).astype(jnp.int64)
        keys = jax.random.split(key, probs.shape[0])
        return jnp.stack(
            [
                jax.random.choice(k, probs.shape[-1], (num_samples,), replace=replacement, p=pp)
                for k, pp in zip(keys, p)
            ]
        ).astype(jnp.int64)

    return Tensor(fn(x._data))


def rand_like(x, dtype=None, name=None):
    key = default_generator.next_key()
    want = dtype_mod.to_jax_dtype(dtype) if dtype is not None else x._data.dtype
    return Tensor(jax.random.uniform(key, x._data.shape, want))


def randn_like(x, dtype=None, name=None):
    key = default_generator.next_key()
    want = dtype_mod.to_jax_dtype(dtype) if dtype is not None else x._data.dtype
    return Tensor(jax.random.normal(key, x._data.shape, want))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    key = default_generator.next_key()
    x._data = jax.random.uniform(key, x._data.shape, x._data.dtype, minval=min, maxval=max)
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    key = default_generator.next_key()
    x._data = jax.random.normal(key, x._data.shape, x._data.dtype) * std + mean
    return x


def exponential_(x, lam=1.0, name=None):
    key = default_generator.next_key()
    x._data = (jax.random.exponential(key, x._data.shape) / lam).astype(x._data.dtype)
    return x


def binomial(count, prob, name=None):
    key = default_generator.next_key()
    return apply_op(
        "binomial",
        lambda n, p: jax.random.binomial(key, n.astype(jnp.float32), p.astype(jnp.float32)).astype(jnp.int64),
        count,
        prob,
    )
