"""TensorArray API: create_array / array_write / array_read / array_length.

Reference: python/paddle/tensor/array.py (dynamic mode: a plain python list;
static mode: a LOD_TENSOR_ARRAY variable backed by phi TensorArray,
paddle/phi/core/tensor_array.h). TPU-native: the dynamic-mode list IS the
representation everywhere — under trace-based to_static / the record-replay
Program, list indices are python ints at trace time (XLA has no growable
array type; bounded loops that need gradients scan over a stacked axis
instead, which is what ``lax.scan`` gives the converted control flow).
"""
from __future__ import annotations

import numpy as np

from .tensor import Tensor

__all__ = ["array_length", "array_read", "array_write", "create_array"]


def _as_index(i) -> int:
    if isinstance(i, Tensor):
        return int(np.asarray(i.numpy()).reshape(-1)[0])
    return int(i)


def create_array(dtype="float32", initialized_list=None):
    """New TensorArray (a python list of Tensors).

    ``initialized_list`` seeds the array (reference create_array
    initialized_list arg)."""
    arr = []
    if initialized_list is not None:
        for v in initialized_list:
            if not isinstance(v, Tensor):
                raise TypeError(
                    f"initialized_list items must be Tensors, got {type(v)}")
            arr.append(v)
    return arr


def array_write(x, i, array=None):
    """Write ``x`` at index ``i``; appends when ``i == len(array)``.

    Returns the array (reference semantics: the written-to array)."""
    if array is None:
        array = create_array()
    if not isinstance(array, list):
        raise TypeError("array must be a TensorArray (python list)")
    idx = _as_index(i)
    if idx < 0 or idx > len(array):
        raise IndexError(
            f"array_write index {idx} out of range for length {len(array)}")
    if idx == len(array):
        array.append(x)
    else:
        array[idx] = x
    return array


def array_read(array, i):
    """Read the Tensor at index ``i``."""
    if not isinstance(array, list):
        raise TypeError("array must be a TensorArray (python list)")
    idx = _as_index(i)
    if idx < 0 or idx >= len(array):
        raise IndexError(
            f"array_read index {idx} out of range for length {len(array)}")
    return array[idx]


def array_length(array):
    """Length of the array as a python int (dynamic-mode reference returns
    the same)."""
    if not isinstance(array, list):
        raise TypeError("array must be a TensorArray (python list)")
    return len(array)
