"""Tensor creation ops.

Parity: python/paddle/tensor/creation.py in the reference.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..autograd.engine import apply_op
from ..framework import config
from ..framework import dtype as dtype_mod
from .tensor import Tensor


def _default_dtype():
    return dtype_mod.to_jax_dtype(config.get_default_dtype())


def _resolve(dtype, default=None):
    if dtype is None:
        return default if default is not None else _default_dtype()
    return dtype_mod.to_jax_dtype(dtype)


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in shape.numpy()]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s._data if isinstance(s, Tensor) else s) for s in shape]


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    if isinstance(data, Tensor):
        out = data.astype(dtype) if dtype is not None else data.clone()
        out.stop_gradient = stop_gradient
        return out
    t = Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)
    return t


def zeros(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.zeros(_shape_list(shape), _resolve(dtype)))


def ones(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.ones(_shape_list(shape), _resolve(dtype)))


def full(shape, fill_value, dtype=None, name=None) -> Tensor:
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = np.bool_
        elif isinstance(fill_value, int):
            dtype = np.int64
        else:
            dtype = _default_dtype()
    return Tensor(jnp.full(_shape_list(shape), fill_value, _resolve(dtype)))


def empty(shape, dtype=None, name=None) -> Tensor:
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.zeros(x._data.shape, _resolve(dtype, x._data.dtype)))


def ones_like(x, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.ones(x._data.shape, _resolve(dtype, x._data.dtype)))


def full_like(x, fill_value, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.full(x._data.shape, fill_value, _resolve(dtype, x._data.dtype)))


def empty_like(x, dtype=None, name=None) -> Tensor:
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None) -> Tensor:
    def val(v):
        return v.item() if isinstance(v, Tensor) else v

    start, end, step = val(start), val(end), val(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = (
            np.int64
            if all(isinstance(v, (int, np.integer)) for v in (start, end, step))
            else _default_dtype()
        )
    return Tensor(jnp.arange(start, end, step, _resolve(dtype)))


def linspace(start, stop, num, dtype=None, name=None) -> Tensor:
    def val(v):
        return v.item() if isinstance(v, Tensor) else v

    return Tensor(jnp.linspace(val(start), val(stop), int(val(num)), dtype=_resolve(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.logspace(start, stop, int(num), base=base, dtype=_resolve(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_resolve(dtype)))


def diag(x, offset=0, padding_value=0, name=None) -> Tensor:
    if padding_value != 0 and x.ndim == 1:
        def fn(v):
            d = jnp.diag(v, k=offset)
            mask = jnp.eye(d.shape[0], d.shape[1], k=offset, dtype=bool)
            return jnp.where(mask, d, jnp.asarray(padding_value, d.dtype))

        return apply_op("diag", fn, x)
    return apply_op("diag", lambda v: jnp.diag(v, k=offset), x)


def diagflat(x, offset=0, name=None) -> Tensor:
    return apply_op("diagflat", lambda v: jnp.diagflat(v, k=offset), x)


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None) -> Tensor:
    def fn(v):
        n = v.shape[-1]
        m = n + abs(offset)
        out = jnp.zeros(v.shape[:-1] + (m, m), v.dtype)
        idx = jnp.arange(n)
        rows = idx + (-offset if offset < 0 else 0)
        cols = idx + (offset if offset > 0 else 0)
        out = out.at[..., rows, cols].set(v)
        return jnp.moveaxis(jnp.moveaxis(out, -2, dim1), -1, dim2)

    return apply_op("diag_embed", fn, x)


def tril(x, diagonal=0, name=None) -> Tensor:
    return apply_op("tril", lambda v: jnp.tril(v, k=diagonal), x)


def triu(x, diagonal=0, name=None) -> Tensor:
    return apply_op("triu", lambda v: jnp.triu(v, k=diagonal), x)


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.stack([jnp.asarray(r), jnp.asarray(c)]).astype(dtype_mod.to_jax_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.stack([jnp.asarray(r), jnp.asarray(c)]).astype(dtype_mod.to_jax_dtype(dtype)))


def meshgrid(*args, **kwargs):
    tensors = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    outs = apply_op("meshgrid", lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")), *tensors)
    return list(outs)


def assign(x, output=None) -> Tensor:
    src = x if isinstance(x, Tensor) else Tensor(np.asarray(x))
    out = apply_op("assign", lambda v: v + 0 if jnp.issubdtype(v.dtype, jnp.number) else v, src)
    if output is not None:
        output._data = out._data
        output._grad_node = out._grad_node
        output._out_index = out._out_index
        return output
    return out


def clone(x, name=None) -> Tensor:
    return x.clone()


def numel(x, name=None) -> Tensor:
    return Tensor(jnp.asarray(x._data.size, jnp.int64))


def rank(x) -> Tensor:
    return Tensor(jnp.asarray(x._data.ndim, jnp.int32))


def shape(x) -> Tensor:
    return Tensor(jnp.asarray(x._data.shape, jnp.int32))


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)


def complex(real, imag, name=None) -> Tensor:
    return apply_op("complex", lambda r, i: jax.lax.complex(r, i), real, imag)


def as_complex(x, name=None) -> Tensor:
    return apply_op("as_complex", lambda v: jax.lax.complex(v[..., 0], v[..., 1]), x)


def as_real(x, name=None) -> Tensor:
    return apply_op("as_real", lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1), x)


import jax  # noqa: E402  (used by complex ops above)
