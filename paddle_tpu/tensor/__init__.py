"""Assemble the Tensor surface: bind op functions as methods + operators.

Parity: python/paddle/base/dygraph/tensor_patch_methods.py:78 (method
monkey-patching) and python/paddle/tensor/__init__.py's method tables.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..autograd.engine import apply_op
from . import array, creation, einsum, linalg, logic, manipulation, math, random, search, stat
from .tensor import Parameter, Tensor, register_tensor_method
from .array import array_length, array_read, array_write, create_array
from .selected_rows import SelectedRows, merge_selected_rows

__all__ = [
    "Tensor",
    "Parameter",
    "SelectedRows",
    "array",
    "array_length",
    "array_read",
    "array_write",
    "create_array",
    "creation",
    "math",
    "manipulation",
    "merge_selected_rows",
    "logic",
    "linalg",
    "search",
    "stat",
    "random",
    "einsum",
]


# --- indexing ---
def _convert_index(idx):
    if isinstance(idx, Tensor):
        if idx.dtype.is_bool:
            return np.asarray(idx._data)  # dynamic-shape mask: eager only
        return idx._data
    if isinstance(idx, (list, np.ndarray)):
        return jnp.asarray(np.asarray(idx))
    if isinstance(idx, tuple):
        return tuple(_convert_index(i) for i in idx)
    return idx


def _getitem(self, idx):
    cidx = _convert_index(idx)
    return apply_op("getitem", lambda v: v[cidx], self)


def _setitem(self, idx, value):
    cidx = _convert_index(idx)
    if isinstance(value, Tensor):
        out = apply_op(
            "setitem", lambda v, val: v.at[cidx].set(val.astype(v.dtype)), self, value
        )
    else:
        val = value

        def fn(v):
            return v.at[cidx].set(jnp.asarray(val).astype(v.dtype))

        out = apply_op("setitem", fn, self)
    manipulation._inplace(self, out)


register_tensor_method("__getitem__", _getitem)
register_tensor_method("__setitem__", _setitem)


# --- arithmetic operators ---
def _swap(fn):
    return lambda self, other: fn(other if isinstance(other, Tensor) else Tensor(_np_scalar(other, self)), self)


def _np_scalar(value, like: Tensor):
    arr = np.asarray(value)
    if arr.dtype == np.float64 and like.dtype.is_floating:
        arr = arr.astype(like.dtype.np_dtype)
    if arr.dtype == np.int64 and like.dtype.is_floating:
        arr = arr.astype(like.dtype.np_dtype)
    return arr


def _scalar_op(fn):
    def method(self, other):
        if isinstance(other, (int, float, bool, complex, np.ndarray, np.generic)):
            other = Tensor(_np_scalar(other, self))
        elif not isinstance(other, Tensor):
            return NotImplemented
        return fn(self, other)

    return method


_OPERATORS = {
    "__add__": math.add,
    "__radd__": math.add,
    "__sub__": math.subtract,
    "__mul__": math.multiply,
    "__rmul__": math.multiply,
    "__truediv__": math.divide,
    "__floordiv__": math.floor_divide,
    "__mod__": math.mod,
    "__pow__": math.pow,
    "__matmul__": math.matmul,
    "__and__": logic.bitwise_and,
    "__or__": logic.bitwise_or,
    "__xor__": logic.bitwise_xor,
    "__eq__": logic.equal,
    "__ne__": logic.not_equal,
    "__lt__": logic.less_than,
    "__le__": logic.less_equal,
    "__gt__": logic.greater_than,
    "__ge__": logic.greater_equal,
}
for name, fn in _OPERATORS.items():
    register_tensor_method(name, _scalar_op(fn))

register_tensor_method("__rsub__", _swap(math.subtract))
register_tensor_method("__rtruediv__", _swap(math.divide))
register_tensor_method("__rfloordiv__", _swap(math.floor_divide))
register_tensor_method("__rmod__", _swap(math.mod))
register_tensor_method("__rpow__", _swap(math.pow))
register_tensor_method("__rmatmul__", _swap(math.matmul))
register_tensor_method("__neg__", lambda self: math.neg(self))
register_tensor_method("__abs__", lambda self: math.abs(self))
register_tensor_method("__invert__", lambda self: logic.bitwise_not(self))


def _iadd(self, other):
    return manipulation._inplace(self, _scalar_op(math.add)(self, other))


def _isub(self, other):
    return manipulation._inplace(self, _scalar_op(math.subtract)(self, other))


def _imul(self, other):
    return manipulation._inplace(self, _scalar_op(math.multiply)(self, other))


def _idiv(self, other):
    return manipulation._inplace(self, _scalar_op(math.divide)(self, other))


register_tensor_method("__iadd__", _iadd)
register_tensor_method("__isub__", _isub)
register_tensor_method("__imul__", _imul)
register_tensor_method("__itruediv__", _idiv)


# --- bind free functions as methods ---
_METHOD_SOURCES = [
    (math, """add subtract multiply divide mod remainder floor_divide floor_mod pow
     matmul mm bmm dot mv addmm inner outer kron abs sqrt rsqrt square exp expm1 log
     log2 log10 log1p sin cos tan asin acos atan sinh cosh tanh asinh acosh atanh
     atan2 floor ceil trunc frac sign reciprocal neg erf erfinv lgamma digamma
     sigmoid logit round clip lerp nan_to_num scale maximum minimum fmax fmin hypot
     heaviside gcd lcm sum mean prod max min amax amin nansum nanmean logsumexp trace
     diagonal cumsum cumprod cummax cummin logcumsumexp diff isfinite isinf isnan all
     any count_nonzero real imag conj angle deg2rad rad2deg take stanh increment
     rint copysign isneginf isposinf isreal ldexp logaddexp nextafter exponent
     multiplex"""),
    (manipulation, """reshape reshape_ transpose transpose_ t moveaxis swapaxes
     flatten squeeze squeeze_ unsqueeze unsqueeze_ split chunk unbind unstack tile
     expand expand_as broadcast_to flip rot90 roll repeat_interleave gather gather_nd
     take_along_axis put_along_axis scatter scatter_ scatter_nd_add index_select
     index_sample index_add index_put index_fill masked_select masked_fill
     masked_fill_ masked_scatter slice strided_slice crop as_strided tensordot
     unfold view_as"""),
    (logic, """equal not_equal greater_than greater_equal less_than less_equal
     logical_and logical_or logical_xor logical_not bitwise_and bitwise_or
     bitwise_xor bitwise_not equal_all isclose allclose is_empty
     bitwise_left_shift bitwise_right_shift"""),
    (linalg, """norm dist cond cross cholesky cholesky_solve inv inverse det slogdet
     solve triangular_solve lstsq qr svd eig eigvals matrix_power matrix_rank pinv
     lu lu_unpack corrcoef"""),
    (search, """argmax argmin argsort sort topk kthvalue mode where nonzero
     searchsorted bucketize unique unique_consecutive histogram bincount"""),
    (stat, "std var median nanmedian quantile nanquantile"),
    (creation, "tril triu diag diagflat diag_embed numel"),
    (random, """bernoulli_ uniform_ normal_ exponential_ multinomial
     cauchy_ geometric_"""),
]

_METHOD_SOURCES += [
    (math, """frexp gammaln multigammaln signbit shard_index
     i0 i0e i1 i1e polygamma trapezoid cumulative_trapezoid renorm sgn
     vander"""),
    (manipulation, """atleast_1d atleast_2d atleast_3d
     broadcast_tensors concat stack tensor_split hsplit vsplit dsplit
     reverse diagonal_scatter select_scatter slice_scatter unflatten
     view"""),
    (creation, "as_complex as_real is_tensor"),
    (logic, "is_complex is_floating_point is_integer"),
    (linalg, """cdist cov eigvalsh multi_dot householder_product
     pca_lowrank"""),
    (search, "histogramdd"),
    (random, "top_p_sampling"),
]

for module, names in _METHOD_SOURCES:
    for n in names.split():
        fn = getattr(module, n)
        register_tensor_method(n, fn)


# signal transforms bind late (signal.py imports the tensor package)
def _stft_method(self, *a, **k):
    from ..signal import stft

    return stft(self, *a, **k)


def _istft_method(self, *a, **k):
    from ..signal import istft

    return istft(self, *a, **k)


register_tensor_method("stft", _stft_method)
register_tensor_method("istft", _istft_method)


# --- generated in-place variants (reference tensor_patch_methods: every
# elementwise op has an `op_` spelling that rebinds the handle) -------------
_INPLACE_BASES = [
    (math, """acos acosh asin asinh atan atanh ceil cos cosh cumprod cumsum
     digamma erfinv floor floor_divide floor_mod frac gcd hypot lcm ldexp
     lerp lgamma log log10 log1p log2 neg pow reciprocal round sigmoid sin
     sinh tan trunc copysign gammaln i0 renorm
     erf expm1 square logit multigammaln polygamma nan_to_num remainder
     addmm"""),
    (logic, """bitwise_and bitwise_or bitwise_xor bitwise_not
     bitwise_left_shift bitwise_right_shift logical_and logical_or
     logical_xor logical_not equal not_equal greater_equal greater_than
     less_equal less_than"""),
    (manipulation, "index_add index_put masked_scatter t"),
    (creation, "tril triu"),
]


def _make_inplace(fn):
    def method(self, *args, **kwargs):
        return manipulation._inplace(self, fn(self, *args, **kwargs))

    return method


for _mod, _names in _INPLACE_BASES:
    for _n in _names.split():
        _f = getattr(_mod, _n, None)
        if _f is not None:
            register_tensor_method(_n + "_", _make_inplace(_f))


def _cast_(self, dtype):
    return manipulation._inplace(self, self.cast(dtype))


register_tensor_method("cast_", _cast_)
register_tensor_method("add_n", math.add_n)
register_tensor_method("where_", search.where_)


def _zero_(self):
    # literal zeros, NOT v*0: IEEE inf*0 == nan would survive the reset
    return manipulation._inplace(
        self, apply_op("scale", lambda v: jnp.zeros_like(v), self))


register_tensor_method("zero_", _zero_)

# A few spelling aliases paddle exposes as methods.
register_tensor_method("mod_", lambda self, y, name=None: manipulation._inplace(self, math.mod(self, y)))
register_tensor_method("add_", lambda self, y, name=None: manipulation._inplace(self, _scalar_op(math.add)(self, y)))
register_tensor_method("subtract_", lambda self, y, name=None: manipulation._inplace(self, _scalar_op(math.subtract)(self, y)))
register_tensor_method("multiply_", lambda self, y, name=None: manipulation._inplace(self, _scalar_op(math.multiply)(self, y)))
register_tensor_method("divide_", lambda self, y, name=None: manipulation._inplace(self, _scalar_op(math.divide)(self, y)))
register_tensor_method("clip_", lambda self, min=None, max=None, name=None: manipulation._inplace(self, math.clip(self, min, max)))
register_tensor_method("scale_", lambda self, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None: manipulation._inplace(self, math.scale(self, scale, bias, bias_after_scale)))
register_tensor_method("exp_", lambda self, name=None: manipulation._inplace(self, math.exp(self)))
register_tensor_method("sqrt_", lambda self, name=None: manipulation._inplace(self, math.sqrt(self)))
register_tensor_method("rsqrt_", lambda self, name=None: manipulation._inplace(self, math.rsqrt(self)))
register_tensor_method("flatten_", lambda self, start_axis=0, stop_axis=-1, name=None: manipulation._inplace(self, manipulation.flatten(self, start_axis, stop_axis)))
register_tensor_method("tanh_", lambda self, name=None: manipulation._inplace(self, math.tanh(self)))
register_tensor_method("abs_", lambda self, name=None: manipulation._inplace(self, math.abs(self)))
