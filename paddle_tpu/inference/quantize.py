"""PTQ serving conversion — fp serving params -> weight-only quantized.

The round-10 bridge between the training-side quantization surface
(``paddle_tpu.quantization`` QuantConfig/PTQ, ``nn.quant.weight_quantize``)
and the serving stack: :func:`quantize_serving_params` turns the pytree
``models.gpt.serving_params`` extracts (or a loaded checkpoint restacked to
that schema) into a QUANTIZED pytree the serving jits consume directly —
each per-layer matmul weight stack ``[L, K, N]`` becomes
``{"q": int8 [L, K, N] | packed int4 [L, K/2, N], "s": [L, G, N]}`` and
the fused Pallas GEMM (``ops.pallas.quant_matmul``) dequantizes it
tile-by-tile inside the kernel.

What quantizes: the four decoder matmul weights (``wqkv``, ``wo``, ``w1``,
``w2``) — the HBM traffic a decode step is bound on. What stays fp:
biases, LayerNorm affines (tiny), the token/position embeddings and the
LM head (the logits matmul is precision-critical and the embedding table
doubles as a gather source). The per-tensor math routes through
``nn.quant.weight_quantize`` — the reference's PTQ weight path — so the
serving conversion and the QAT/PTQ drivers share one quantizer.

Wired through ``GPTConfig.weight_dtype`` ("int8"/"int4") +
``GPTConfig.weight_quant_group_size``: ``generate_paged`` and
``ServingPredictor`` quantize at params-extraction time, so a GPT
checkpoint serves quantized with a one-line config change.
"""
from __future__ import annotations

import jax.numpy as jnp

#: the per-layer stacks that quantize (the decode-bound matmul weights)
QUANT_LAYER_KEYS = ("wqkv", "wo", "w1", "w2")
#: the MoE expert stacks ([L, E, K, N] — quantize per expert; the ragged
#: grouped GEMM consumes {"q": [E, K, N], "s": [E, G, N]} slices)
MOE_QUANT_LAYER_KEYS = ("moe_w1", "moe_w2")


def _algo(weight_dtype: str) -> str:
    if weight_dtype not in ("int8", "int4"):
        raise ValueError(
            f"weight_dtype must be 'int8' or 'int4', got {weight_dtype!r}")
    return f"weight_only_{weight_dtype}"


def quantize_weight(w, weight_dtype="int8", group_size=-1):
    """Quantize ONE ``[K, N]`` weight through the nn.quant PTQ surface.
    Returns ``{"q": int8 [K, N] | packed [K/2, N], "s": [G, N]}`` (jnp
    arrays — ready to ride a serving pytree)."""
    from ..nn.quant import weight_quantize
    from ..tensor.tensor import Tensor

    t = w if isinstance(w, Tensor) else Tensor(jnp.asarray(w))
    q, s = weight_quantize(t, algo=_algo(weight_dtype),
                           group_size=group_size)
    s2 = s._data
    if s2.ndim == 1:
        s2 = s2.reshape(1, -1)
    return {"q": q._data, "s": s2.astype(jnp.float32)}


def _quantize_stack(stack, weight_dtype, group_size):
    """Quantize one ``[L, K, N]`` layer stack in a SINGLE batched pass:
    ``jax.vmap`` of the nn.quant quantizer body over the layer axis (one
    traced op per stack, not L eager dispatches + a restack)."""
    import functools

    import jax

    from ..nn.quant import _qmax, _weight_quantize_fn

    fn = functools.partial(
        _weight_quantize_fn, qmax=_qmax(_algo(weight_dtype)),
        int4=weight_dtype == "int4", group_size=group_size)
    if stack.ndim == 4:                            # MoE: [L, E, K, N]
        q, s = jax.vmap(jax.vmap(fn))(stack)
        if s.ndim == 3:                            # per-channel: [L, E, N]
            s = s[:, :, None, :]
        return {"q": q, "s": s.astype(jnp.float32)}
    q, s = jax.vmap(fn)(stack)
    if s.ndim == 2:                                # per-channel: [L, N]
        s = s[:, None, :]
    return {"q": q, "s": s.astype(jnp.float32)}


def quantize_serving_params(params, weight_dtype="int8", group_size=-1,
                            config=None):
    """Quantize a serving-params pytree (``models.gpt.serving_params``
    schema) for the fused weight-only GEMM path.

    ``config``: optional :class:`paddle_tpu.quantization.QuantConfig`
    whose ``add_name_config`` entries RESTRICT which layer stacks
    quantize (names from :data:`QUANT_LAYER_KEYS`); None quantizes all
    four. A config naming NONE of the serving keys raises — silently
    quantizing everything would invert the requested restriction.
    Returns a NEW pytree — fp leaves are shared, quantized stacks are
    fresh device arrays.
    """
    _algo(weight_dtype)  # validate early
    present = set(params["layers"])
    keys = (set(QUANT_LAYER_KEYS) | set(MOE_QUANT_LAYER_KEYS)) & present
    if config is not None:
        named = set(getattr(config, "_name_cfg", {}))
        keys = named & keys
        if not keys:
            raise ValueError(
                f"QuantConfig names {sorted(named)} match no serving "
                f"layer stack — restrict with names from "
                f"{sorted(QUANT_LAYER_KEYS + MOE_QUANT_LAYER_KEYS)}")
    out = dict(params)
    layers = dict(params["layers"])
    for key in sorted(keys):
        layers[key] = _quantize_stack(layers[key], weight_dtype, group_size)
    out["layers"] = layers
    return out


#: the row-parallel (K-sharded under the serving mp mesh) layer stacks
ROW_PARALLEL_KEYS = ("wo", "w2")


def assert_quant_shardable(layers, mp: int, weight_dtype=None) -> None:
    """Validate that the quantized stacks of a serving ``layers`` dict can
    shard over an ``mp``-way tensor-parallel mesh (round 11).

    Column stacks always shard (the output dim splits with its scales).
    Row stacks shard their K dim, so grouped scales must tile the mesh
    (``mp | groups``) — otherwise a chip's K shard would straddle a scale
    group and the fused kernel's local ``K/groups`` group size would lie.
    int4 is rejected outright: split-half nibble packing stores rows ``i``
    and ``K/2 + i`` in one byte, so a contiguous shard of the packed dim
    owns two INTERLEAVED half-ranges of K — not the contiguous head-major
    activation shard the row-parallel psum contract needs.
    """
    if mp <= 1:
        return
    quantized = any(isinstance(layers.get(k), dict)
                    for k in QUANT_LAYER_KEYS)
    if quantized and weight_dtype == "int4":
        raise ValueError(
            "int4 split-half packing interleaves the K rows of the "
            "row-parallel stacks — int4 weights serve single-chip only "
            "(use weight_dtype='int8' under an mp mesh)")
    for key in ROW_PARALLEL_KEYS:
        leaf = layers.get(key)
        if not isinstance(leaf, dict):
            continue
        groups = leaf["s"].shape[-2]
        if groups > 1 and groups % mp:
            raise ValueError(
                f"serving stack '{key}': {groups} scale groups are not "
                f"divisible by the mp mesh size {mp} — choose a "
                "weight_quant_group_size that makes the group count a "
                "multiple of mp")


def is_quantized_params(params) -> bool:
    """Whether a serving pytree carries quantized weight stacks."""
    return any(isinstance(params["layers"].get(k), dict)
               for k in QUANT_LAYER_KEYS + MOE_QUANT_LAYER_KEYS)


def serving_weight_bytes(params) -> int:
    """HBM bytes a decode step reads in WEIGHTS (per token batch): every
    per-layer stack leaf + the non-layer leaves — the quantity weight-only
    quantization shrinks (the bench's hbm-bytes-per-token numerator)."""
    total = 0

    def visit(leaf):
        nonlocal total
        if isinstance(leaf, dict):
            for v in leaf.values():
                visit(v)
        elif hasattr(leaf, "dtype"):
            total += leaf.size * jnp.dtype(leaf.dtype).itemsize

    visit(params)
    return int(total)
