"""Paged KV cache manager — the serving cache behind paged decode attention.

Reference shape: the vLLM-style block manager behind the reference's
``block_multihead_attention`` serving path, TPU-native: the cache is a POOL
of fixed-size pages ``[num_layers, num_pages, page_size, kv_heads,
head_dim]`` (one stacked array per K and V so the decode jit sees ONE
pytree leaf each), and each admitted sequence owns a list of pages through
a per-slot page table. Admission/eviction move pages between the free list
and slots without copying K/V — fragmentation-free continuous batching.

Split of responsibilities:

- **host side (this class)**: page free list, slot free list, admission
  (can the prompt + headroom fit?), per-step growth (allocate a page when a
  sequence crosses a page boundary), eviction. All O(pages) numpy/python —
  never inside a compiled program.
- **device side (pure functions below)**: the scatters that write prefill
  K/V and per-step decode K/V into the page pool. They are shape-stable
  jnp functions traced INTO the prefill/decode jits (models/gpt.py), so the
  cache arrays never round-trip through the host.

Page-table convention (shared with ops/pallas/paged_attention):
``page_table[slot, i]`` is the pool index of the slot's i-th page, ``-1``
when unallocated; ``seq_lens[slot]`` counts tokens already written (0 =
empty slot). Writes to unallocated/out-of-range positions are routed out of
bounds and dropped (``mode="drop"``) rather than corrupting page 0.

Round 9 adds PREFIX CACHING (vLLM automatic-prefix-caching shape, page
granularity): prompt pages are registered under a content CHAIN HASH
(page i's key folds page i-1's key, so a key names the whole prefix up to
and including that page) once their prefill lands. A later admission walks
its prompt's chain and attaches every matching page read-only
(refcount += 1) instead of re-prefilling it; the final page may match a
registered PARTIAL fill (the key records the token count). Refcounted
pages are PINNED (never reallocated); a registered page whose refcount
drops to 0 parks on an LRU and keeps serving hits until the free list runs
dry, at which point the LRU tail is evicted (unregistered) and reused.
Divergence is handled copy-on-write: a slot about to write into a page
with refcount >= 2 gets a fresh copy via :meth:`prepare_write` — the
device-side page copy is traced into the unified step (cow_src/cow_dst
lanes), so shared immutable pages are never mutated.

Round 21 adds the HOST TIER: a bounded host-DRAM buffer UNDER the HBM
pool. A zero-ref prefix page falling off the LRU no longer just drops —
its payload (K/V rows, int8 scale planes, partial tails included)
spills to the host keyed by the SAME sha1 chain key, checksummed at
spill time. A later admission (or export walk) whose chain breaks on
the device registry but continues in the host tier re-admits the
missing links through the batched import landing zone
(:meth:`KVCacheManager.import_prefix_pages` — ONE donated scatter per
K/V/scale plane per restore round, not a full pool copy per page) and
the normal match walk then pins them like never-evicted pages. Eviction
ordering is HBM -> host -> drop: the host tier runs its own LRU under
its byte budget, and a tier entry whose checksum fails at restore is
DETECTED, dropped and counted — degrading to a recompute, never
scattering corrupt bytes into the pool.
"""
from __future__ import annotations

import math
import zlib
from collections import OrderedDict

import numpy as np

import jax
import jax.numpy as jnp

from .faults import fault_point


def pages_needed(length: int, page_size: int) -> int:
    """Pages a ``length``-token sequence occupies (>= 1) — the ONE spelling
    of the ceil-div every pool-sizing site shares."""
    return math.ceil(max(length, 1) / page_size)


def chain_key(prev: bytes, tokens) -> bytes:
    """The prefix cache's sha1 content chain key: page i's key folds page
    i-1's, so one key names the whole prefix up to and including this
    page's tokens (count included — a 4-token partial and an 8-token full
    fill hash differently). Module-level because the key is a CONTRACT
    shared beyond one manager: the fleet router's prefix-affinity map
    (``inference/fleet_serving.py``) hashes prompts with the SAME chain so
    shared-prefix traffic lands on the replica whose pool already holds
    those pages — which is only sound because independently constructed
    managers (different replicas, different processes) derive identical
    keys from identical tokens (locked by tests/test_prefix_cache.py)."""
    import hashlib

    h = hashlib.sha1(prev)
    h.update(np.asarray(tokens, np.int64).tobytes())
    return h.digest()


def prompt_chain_keys(tokens, page_size: int) -> list[bytes]:
    """The chain keys of every FULL page of ``tokens``, shallowest first —
    the fleet router's affinity walk (deepest registered key wins, so the
    longest shared prefix decides the replica). Prompts shorter than one
    page have no stable page-granular identity: empty list."""
    keys: list[bytes] = []
    h = b""
    for i in range(0, len(tokens) - len(tokens) % int(page_size),
                   int(page_size)):
        h = chain_key(h, tokens[i:i + page_size])
        keys.append(h)
    return keys


def kv_cache_quantized(kv_cache_dtype) -> bool:
    """Map a ``kv_cache_dtype`` config value to the pool-quantization flag
    — the ONE validation every consumer (generate_paged, ServingPredictor)
    shares, so an unsupported value fails loudly instead of silently
    serving a full-precision cache."""
    if kv_cache_dtype in (None, "none"):
        return False
    if kv_cache_dtype == "int8":
        return True
    raise ValueError(
        f"kv_cache_dtype must be None or 'int8', got {kv_cache_dtype!r} "
        "(int4 KV is not supported — sub-byte pages would halve the "
        "scatter granularity; weight_dtype='int4' is the 4x lever)")


# ---------------------------------------------------------------------------
# device-side pure scatter helpers (traced into the prefill/decode jits)
# ---------------------------------------------------------------------------


def paged_write_tokens(pages, tok, page_table, positions, page_size):
    """Write ONE token per slot into the page pool (the decode-step write).

    pages: [num_pages, page_size, kv_heads, head_dim]; tok: [batch,
    kv_heads, head_dim]; page_table: [batch, pages_per_slot] int32;
    positions: [batch] int32 write position per slot (< 0 = inactive slot,
    dropped). Returns the updated pool.
    """
    num_pages = pages.shape[0]
    b = tok.shape[0]
    pos = jnp.maximum(positions, 0)
    pg = page_table[jnp.arange(b), pos // page_size]
    # inactive slots and unallocated (-1) entries route out of bounds
    pg = jnp.where((positions >= 0) & (pg >= 0), pg, num_pages)
    return pages.at[pg, pos % page_size].set(tok, mode="drop")


def paged_write_prefill(pages, seq, pages_for_slot, length, page_size):
    """Scatter one slot's prompt K/V into its pages (copy-on-prefill).

    pages: [num_pages, page_size, kv_heads, head_dim]; seq: [s_pad,
    kv_heads, head_dim] (positions >= length are padding and dropped);
    pages_for_slot: [pages_per_slot] int32 (-1 unallocated); length: scalar.
    """
    num_pages = pages.shape[0]
    s_pad = seq.shape[0]
    i = jnp.arange(s_pad)
    pg = pages_for_slot[jnp.minimum(i // page_size,
                                    pages_for_slot.shape[0] - 1)]
    pg = jnp.where((i < length) & (pg >= 0), pg, num_pages)
    return pages.at[pg, i % page_size].set(seq, mode="drop")


def _packed_dest(page_table, tok_slot, tok_pos, page_size, num_pages):
    """The packed-write scatter destination shared by the fp and quantized
    writes: per-token (page, row) with padding (< 0 slot/pos) and
    unallocated (-1) entries routed to the out-of-bounds ``num_pages``
    sentinel (``mode="drop"``). Returns ``(pg, row)``."""
    b = page_table.shape[0]
    slot_c = jnp.clip(tok_slot, 0, b - 1)
    pos = jnp.maximum(tok_pos, 0)
    pg = page_table[slot_c,
                    jnp.clip(pos // page_size, 0, page_table.shape[1] - 1)]
    valid = (tok_slot >= 0) & (tok_pos >= 0) & (pg >= 0)
    return jnp.where(valid, pg, num_pages), pos % page_size


def paged_write_packed(pages, toks, page_table, tok_slot, tok_pos,
                       page_size):
    """Write a PACKED token stream into the page pool in one scatter (the
    unified-step write: the step's dense dims run over the flat token
    budget, each token carrying its owning slot + absolute position).

    pages: [num_pages, page_size, kv_heads, head_dim]; toks: [budget,
    kv_heads, head_dim]; page_table: [batch, pages_per_slot] int32;
    tok_slot: [budget] int32 owning slot (< 0 = padding, dropped);
    tok_pos: [budget] int32 absolute write position. Returns the pool.
    """
    pg, row = _packed_dest(page_table, tok_slot, tok_pos, page_size,
                           pages.shape[0])
    return pages.at[pg, row].set(toks, mode="drop")


def paged_write_packed_quant(pages, scales, toks, page_table, tok_slot,
                             tok_pos, page_size):
    """Quantize-on-write for the int8 KV cache: the packed write
    (:func:`paged_write_packed`) with a per-token-per-head symmetric int8
    quantization fused in front of the scatter.

    pages: [num_pages, page_size, kv_heads, head_dim] **int8**; scales:
    [num_pages, page_size, kv_heads] fp32 (the per-page scale plane — page
    granularity keeps it travelling with the page through CoW copies,
    prefix sharing and eviction); toks: [budget, kv_heads, head_dim] float.
    Each token row quantizes against its own per-head absmax
    (``scale = absmax / 127``), so pages never need rescaling as later
    tokens land. Returns ``(pages, scales)``.
    """
    pg, row = _packed_dest(page_table, tok_slot, tok_pos, page_size,
                           pages.shape[0])
    tf = toks.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(tf), axis=-1)           # [budget, kv_heads]
    s = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(tf / s[..., None]), -127, 127).astype(jnp.int8)
    pages = pages.at[pg, row].set(q, mode="drop")
    scales = scales.at[pg, row].set(s.astype(scales.dtype), mode="drop")
    return pages, scales


def paged_write_packed_prequant(pages, scales, q_toks, s_toks, page_table,
                                tok_slot, tok_pos, page_size):
    """Scatter ALREADY-QUANTIZED packed K/V rows + their scale rows into
    the int8 pool — the round-16 megakernel write path: the fused layer
    kernel quantizes the new token's K/V inline in VMEM (the exact
    :func:`paged_write_packed_quant` formula) and emits int8 payloads
    ``q_toks [budget, kv_heads, head_dim]`` with per-row-per-head scales
    ``s_toks [budget, kv_heads]``; this is just the scatter half. Since
    round 22 the MIXED ragged rounds drive it too: the budget packs a
    VARIABLE 1..chunk rows per lane (a decode lane one row, a prefill-
    chunk lane several, pad rows ``tok_slot == -1``), so consecutive
    rows of one lane land at consecutive ``tok_pos`` — the drop-mode
    scatter is position-addressed and never cared how many rows a lane
    contributed. Returns ``(pages, scales)``.
    """
    pg, row = _packed_dest(page_table, tok_slot, tok_pos, page_size,
                           pages.shape[0])
    pages = pages.at[pg, row].set(q_toks.astype(pages.dtype), mode="drop")
    scales = scales.at[pg, row].set(s_toks.astype(scales.dtype),
                                    mode="drop")
    return pages, scales


def paged_copy_pages(pages, src, dst):
    """Copy-on-write page copies, traced into the unified step.

    pages: [num_layers, num_pages, page_size, kv_heads, head_dim] (the
    stacked pool as the jits see it); src/dst: [batch] int32 pool indices,
    ``dst == num_pages`` (the host's no-op sentinel) drops the copy. Each
    active lane duplicates one page across every layer.
    """
    num_pages = pages.shape[1]
    src_c = jnp.clip(src, 0, num_pages - 1)
    return pages.at[:, dst].set(pages[:, src_c], mode="drop")


def batched_import_rows(pages, vals, pg, row):
    """Land one restore round's token rows in ONE scatter — the round-21
    batched import/restore write (tpulint flagship: ``serving-tiered``).

    pages: ``[L, P, page_size, kv_heads, head_dim]`` (or a 4-D scale
    plane ``[L, P, page_size, kv_heads]``); vals: ``[L, R, kv_heads,
    head_dim]`` (resp. ``[L, R, kv_heads]``) — flat row ``r`` lands at
    ``pages[:, pg[r], row[r]]``. Padding rows carry ``pg == P`` (the
    out-of-bounds sentinel) and drop, so one power-of-two-padded trace
    serves every restore round of that width.
    """
    return pages.at[:, pg, row].set(vals, mode="drop")


#: the jitted batched-import entry point: the pool argument is DONATED —
#: a restore round updates the (potentially multi-GiB) pool in place
#: instead of materializing a second copy per plane. The 5-D K/V pools
#: and the 4-D scale planes each trace once per padded row width.
_batched_import_rows_jit = jax.jit(batched_import_rows,
                                   donate_argnums=(0,))


def _payload_crc(planes: dict) -> int:
    """The host-tier integrity checksum: one crc32 over every plane's
    bytes in plane-name order — computed at spill time, verified at
    restore (a corrupt stored payload must be DETECTED, never scattered
    into the device pool)."""
    crc = 0
    for name in sorted(planes):
        crc = zlib.crc32(planes[name].tobytes(), crc)
    return crc


# ---------------------------------------------------------------------------
# host-side manager
# ---------------------------------------------------------------------------


class KVCacheManager:
    """Owns the page pool + page table + free lists for one model.

    ``num_pages`` bounds total cached tokens (``num_pages * page_size``);
    ``max_batch`` bounds concurrent sequences (decode-step batch — the
    FIXED jit shape); ``max_seq_len`` bounds per-sequence length (page-table
    width). ``page_size=None`` consults the autotuned
    :func:`~paddle_tpu.ops.pallas.paged_attention.preferred_page_size`.
    """

    def __init__(self, num_layers, num_kv_heads, head_dim, *, num_pages,
                 max_batch, max_seq_len, page_size=None, num_q_heads=None,
                 dtype=jnp.float32, enable_prefix_cache=False,
                 quantize_kv=False, mesh=None, metrics=None,
                 host_tier_bytes=0):
        from ..ops.pallas.paged_attention import preferred_page_size

        if page_size is None:
            page_size = preferred_page_size(
                num_q_heads or num_kv_heads, num_kv_heads, head_dim, dtype)
        if mesh is not None and num_kv_heads % int(mesh.shape["mp"]):
            raise ValueError(
                f"the mp mesh size {int(mesh.shape['mp'])} must divide "
                f"kv heads {num_kv_heads} (pages shard by whole head)")
        self.num_layers = num_layers
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.max_batch = int(max_batch)
        self.max_seq_len = int(max_seq_len)
        self.pages_per_slot = math.ceil(self.max_seq_len / self.page_size)
        shape = (num_layers, self.num_pages, self.page_size,
                 num_kv_heads, head_dim)
        # int8 KV (round 10): pages store int8 with a per-page fp32 scale
        # plane [L, P, page_size, kv_heads] — the scale travels WITH its
        # page (CoW copies, prefix sharing, eviction all stay page-local).
        # ``dtype`` remains the COMPUTE dtype (page-size autotune key).
        self.quantize_kv = bool(quantize_kv)
        pool_dtype = jnp.int8 if self.quantize_kv else dtype
        self.k_pages = jnp.zeros(shape, pool_dtype)
        self.v_pages = jnp.zeros(shape, pool_dtype)
        if self.quantize_kv:
            sshape = (num_layers, self.num_pages, self.page_size,
                      num_kv_heads)
            self.k_scales = jnp.zeros(sshape, jnp.float32)
            self.v_scales = jnp.zeros(sshape, jnp.float32)
        else:
            self.k_scales = self.v_scales = None
        # round 11: under a serving mesh the pools (and scale planes) live
        # SHARDED on the head axis — each chip owns its heads' pages end
        # to end; the sharded serving jits return them sharded, so the
        # pool never materializes whole on one chip
        self.mesh = mesh
        if mesh is not None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            kv_sh = NamedSharding(mesh, P(None, None, None, "mp", None))
            self.k_pages = jax.device_put(self.k_pages, kv_sh)
            self.v_pages = jax.device_put(self.v_pages, kv_sh)
            if self.quantize_kv:
                sc_sh = NamedSharding(mesh, P(None, None, None, "mp"))
                self.k_scales = jax.device_put(self.k_scales, sc_sh)
                self.v_scales = jax.device_put(self.v_scales, sc_sh)
        # host-side bookkeeping (numpy; uploaded per step as small arrays).
        # the device views are REVISION-CACHED: every mutator bumps its
        # revision and the upload happens only when a view is stale — a
        # steady decode step whose lanes stay inside their pages re-serves
        # the same device page table with zero H2D traffic (round 13)
        self._page_table = np.full(
            (self.max_batch, self.pages_per_slot), -1, np.int32)
        self._seq_lens = np.zeros((self.max_batch,), np.int32)
        self._pt_rev = 0
        self._sl_rev = 0
        self._pt_dev: tuple[int, jnp.ndarray | None] = (-1, None)
        self._sl_dev: tuple[int, jnp.ndarray | None] = (-1, None)
        self._free_pages = list(range(self.num_pages - 1, -1, -1))  # pop()
        self._free_slots = list(range(self.max_batch - 1, -1, -1))
        # round 17: pages temporarily withheld from circulation (fault
        # injection's pool-pressure squeeze / reserved headroom) — out of
        # every free/available count until restored
        self._withheld: list[int] = []
        # prefix cache state: per-page slot refcounts, the content-key
        # registry, and the LRU of zero-ref registered pages (evictable,
        # still serving hits until reused)
        self.enable_prefix_cache = bool(enable_prefix_cache)
        self._refcount = np.zeros((self.num_pages,), np.int32)
        self._page_key: dict[int, bytes] = {}    # page -> chain key
        self._prefix_pages: dict[bytes, int] = {}  # chain key -> page
        self._lru: OrderedDict[int, None] = OrderedDict()
        # round 21: the HOST TIER under the HBM pool — spilled page
        # payloads keyed by chain key, LRU-ordered under a byte budget
        # (0 disables: evictions drop exactly like pre-21). Entries are
        # (ntok, planes dict of host numpy arrays, nbytes, crc32).
        self.host_tier_limit = int(host_tier_bytes or 0)
        if self.host_tier_limit < 0:
            raise ValueError(
                f"host_tier_bytes must be >= 0, got {host_tier_bytes}")
        self._host_tier: OrderedDict[
            bytes, tuple[int, dict, int, int]] = OrderedDict()
        self._host_tier_nbytes = 0
        # per-page registered token count — the spill path must know how
        # many rows of a page are REAL prefix payload (partial tails
        # spill exactly their fill, never padding rows)
        self._page_ntok: dict[int, int] = {}
        # round 15: pool telemetry — occupancy gauges + prefix/eviction/
        # CoW counters on the observability registry (the serving
        # predictor shares its registry so one snapshot covers the stack)
        from ..observability import MetricsRegistry

        self.metrics = metrics if metrics is not None else MetricsRegistry()
        if not self.metrics.enabled:
            # prefix_hit_tokens/prefix_query_tokens read through these
            # counters — a disabled registry silently zeroes them
            raise ValueError(
                "KVCacheManager requires an enabled metrics registry; "
                "the one passed is disabled")
        m = self.metrics
        self._m_pages_free = m.gauge(
            "kv_pages_free", "strictly-free pool pages")
        self._m_pages_evictable = m.gauge(
            "kv_pages_evictable", "zero-ref registered pages on the LRU")
        self._m_slots_free = m.gauge(
            "kv_slots_free", "unoccupied decode slots")
        self._m_prefix_hit = m.counter(
            "kv_prefix_hit_tokens", "admitted tokens served from the cache")
        self._m_prefix_query = m.counter(
            "kv_prefix_query_tokens", "admitted tokens queried")
        self._m_evictions = m.counter(
            "kv_prefix_evictions", "registered pages evicted off the LRU")
        self._m_cow = m.counter(
            "kv_cow_copies", "copy-on-write page copies prepared")
        self._m_trimmed = m.counter(
            "kv_pages_trimmed", "pages released by draft rollback")
        self._m_withheld = m.gauge(
            "kv_pages_withheld", "pages withheld from circulation")
        # round 21: host-tier instruments — registered unconditionally
        # (a disabled tier reads zeros) so the flat-snapshot schema is
        # identical with and without a tier
        self._m_tier_pages = m.gauge(
            "kv_tier_pages", "page payloads held in the host tier")
        self._m_tier_bytes = m.gauge(
            "kv_tier_bytes", "host-tier bytes in use")
        self._m_tier_spills = m.counter(
            "kv_tier_spills", "evicted pages spilled to the host tier")
        self._m_tier_spill_bytes = m.counter(
            "kv_tier_spill_bytes", "payload bytes written to the host "
            "tier by spills")
        self._m_tier_restores = m.counter(
            "kv_tier_restores", "host-tier pages re-admitted to the pool")
        self._m_tier_restore_bytes = m.counter(
            "kv_tier_restore_bytes", "payload bytes restored from the "
            "host tier")
        self._m_tier_lookups = m.counter(
            "kv_tier_lookups", "chain links probed against the host tier")
        self._m_tier_hits = m.counter(
            "kv_tier_hits", "host-tier probes that returned a verified "
            "payload")
        self._m_tier_evictions = m.counter(
            "kv_tier_evictions", "host-tier entries dropped by its own "
            "LRU (the HBM -> host -> drop ladder's last rung)")
        self._m_tier_spill_drops = m.counter(
            "kv_tier_spill_drops", "spills lost at the host_spill_drop "
            "seam")
        self._m_tier_corrupt = m.counter(
            "kv_tier_restore_corrupt", "host-tier payloads rejected by "
            "the restore checksum (detected, dropped, recomputed)")
        self._m_restore_scatters = m.counter(
            "kv_tier_restore_device_calls", "device scatter calls issued "
            "by batched imports (one per plane per round)")
        self._note_occupancy()

    def _note_occupancy(self) -> None:
        """Refresh the pool-occupancy gauges (called by every public
        mutator — page events per step are few, so three gauge sets are
        noise next to the allocation work itself)."""
        self._m_pages_free.set(len(self._free_pages))
        self._m_pages_evictable.set(len(self._lru))
        self._m_slots_free.set(len(self._free_slots))
        self._m_withheld.set(len(self._withheld))
        self._m_tier_pages.set(len(self._host_tier))
        self._m_tier_bytes.set(self._host_tier_nbytes)

    # -- back-compat metric reads (pre-round-15 attribute surface) ---------

    @property
    def prefix_hit_tokens(self) -> int:
        return int(self._m_prefix_hit.value)

    @property
    def prefix_query_tokens(self) -> int:
        return int(self._m_prefix_query.value)

    # -- capacity ----------------------------------------------------------

    @property
    def free_page_count(self) -> int:
        return len(self._free_pages)

    @property
    def available_page_count(self) -> int:
        """Pages an allocation may claim: truly free + evictable (zero-ref
        registered prefix pages on the LRU)."""
        return len(self._free_pages) + len(self._lru)

    @property
    def free_slot_count(self) -> int:
        return len(self._free_slots)

    @property
    def withheld_page_count(self) -> int:
        return len(self._withheld)

    def pages_needed(self, length: int) -> int:
        return pages_needed(length, self.page_size)

    def can_admit(self, prompt_len: int) -> bool:
        return (bool(self._free_slots)
                and prompt_len <= self.max_seq_len
                and self.pages_needed(prompt_len)
                <= self.available_page_count)

    def _alloc_page(self) -> int:
        """Claim one page: the free list first, then evict the LRU tail of
        the zero-ref registered pages (unregistering it — round 21: its
        payload spills to the host tier first instead of dropping)."""
        if self._free_pages:
            return self._free_pages.pop()
        if self._lru:
            page, _ = self._lru.popitem(last=False)   # oldest
            key = self._page_key.pop(page)
            del self._prefix_pages[key]
            ntok = self._page_ntok.pop(page, 0)
            self._spill_page(key, page, ntok)
            self._m_evictions.inc()
            return page
        raise RuntimeError("cache exhausted: no free or evictable pages")

    # -- host tier (round 21) ----------------------------------------------

    def _spill_page(self, key: bytes, page: int, ntok: int) -> bool:
        """Spill one evicted page's payload to the host tier (the middle
        rung of the HBM -> host -> drop eviction ladder). Content-
        addressed: a key already resident only refreshes its recency —
        identical tokens hash to identical keys, so the stored payload
        is already the right bytes. Host pressure evicts the tier's own
        LRU head (the final drop). Returns True when the payload is
        resident after the call."""
        if not self.host_tier_limit or not ntok \
                or not self.enable_prefix_cache:
            return False
        if key in self._host_tier:
            self._host_tier.move_to_end(key)
            return True
        if fault_point("host_spill_drop"):
            # the seam models a lost spill DMA / reclaimed host buffer:
            # the eviction proceeds, the tier just never sees the bytes
            # — a cache-effectiveness loss, counted, never an error
            self._m_tier_spill_drops.inc()
            return False
        planes = {name: np.array(a) for name, a in
                  self.read_page_payload(page, int(ntok)).items()}
        nbytes = sum(a.nbytes for a in planes.values())
        if nbytes > self.host_tier_limit:
            return False
        while self._host_tier_nbytes + nbytes > self.host_tier_limit:
            self._drop_tier_entry(next(iter(self._host_tier)))
            self._m_tier_evictions.inc()
        self._host_tier[key] = (int(ntok), planes, nbytes,
                                _payload_crc(planes))
        self._host_tier_nbytes += nbytes
        self._m_tier_spills.inc()
        self._m_tier_spill_bytes.inc(nbytes)
        return True

    def _drop_tier_entry(self, key: bytes) -> None:
        _, _, nbytes, _ = self._host_tier.pop(key)
        self._host_tier_nbytes -= nbytes

    def reserve_import_room(self, npages: int) -> bool:
        """Replenish the strictly-free list to ``npages`` by evicting
        LRU-tail zero-ref pages down the normal ladder (each one spills
        to the host tier before its slot frees — content-addressed, so
        a payload already resident costs a recency touch, not a copy).
        The import landing zones themselves NEVER evict (the locked
        round-20 contract: pressure returns None); this is the explicit
        room-making step the restore round and the pull destination run
        first. ``available_page_count`` is unchanged — pages move from
        the evictable rung to the free rung — so a reservation inside a
        soft admission probe mutates nothing the scheduler accounts.
        Returns True when the room exists after the call."""
        npages = int(npages)
        while len(self._free_pages) < npages and self._lru:
            page, _ = self._lru.popitem(last=False)   # oldest
            key = self._page_key.pop(page)
            del self._prefix_pages[key]
            ntok = self._page_ntok.pop(page, 0)
            self._spill_page(key, page, ntok)
            self._m_evictions.inc()
            self._free_pages.append(page)
        self._note_occupancy()
        return len(self._free_pages) >= npages

    def _tier_lookup(self, key: bytes):
        """Probe the host tier for one chain key, verifying the stored
        checksum before handing the payload out. The
        ``tier_restore_corrupt`` seam flips a stored byte first — the
        mismatch is DETECTED, the entry dropped and counted, and the
        probe degrades to a miss (the admission recomputes; corrupt
        bytes never reach the device pool). Returns ``(ntok, planes)``
        or None."""
        self._m_tier_lookups.inc()
        ent = self._host_tier.get(key)
        if ent is None:
            return None
        ntok, planes, nbytes, crc = ent
        if fault_point("tier_restore_corrupt"):
            flat = planes[min(planes)].reshape(-1).view(np.uint8)
            flat[flat.shape[0] // 2] ^= 0xFF
        if _payload_crc(planes) != crc:
            self._drop_tier_entry(key)
            self._m_tier_corrupt.inc()
            return None
        self._host_tier.move_to_end(key)
        self._m_tier_hits.inc()
        return ntok, planes

    def _tier_restore(self, tokens) -> int:
        """Walk ``tokens``'s chain and re-admit every link the device
        registry lost but the host tier still holds, so the match/export
        walk that follows sees them as ordinary registered pages. The
        walk mirrors :meth:`_match_prefix` exactly — full pages in chain
        order, then the longest partial tail at the stop position — and
        collects the WHOLE round's tier hits before landing them through
        :meth:`import_prefix_pages` (one donated scatter per plane).
        The round makes its own room first (:meth:`reserve_import_room`:
        LRU-tail pages evict DOWN the ladder — they spill to the tier,
        so room-making loses nothing — while this chain's resident
        links are touched to the MRU end so they are never the
        victims); the landing zone itself still claims strictly-free
        pages only, and under true pressure the round lands a prefix of
        itself with the rest staying resident in the tier. Restored
        entries STAY in
        the tier (content-addressed: a later re-eviction refreshes
        recency instead of re-copying). Returns pages restored."""
        if not self.host_tier_limit or not self._host_tier:
            return 0
        ps = self.page_size
        n = len(tokens)
        entries: list[tuple[bytes, int, dict]] = []
        pos = 0
        h = b""
        while pos + ps <= n:
            nxt = self._chain_key(h, tokens[pos:pos + ps])
            if nxt in self._prefix_pages:
                # touch the resident link: the room-making below evicts
                # from the LRU tail, and this chain's own device-held
                # links must not be the victims
                page = self._prefix_pages[nxt]
                if page in self._lru:
                    self._lru.move_to_end(page)
            else:
                ent = self._tier_lookup(nxt)
                if ent is None:
                    break
                entries.append((nxt, ent[0], ent[1]))
            pos += ps
            h = nxt
        for t in range(min(ps - 1, n - pos), 0, -1):
            nxt = self._chain_key(h, tokens[pos:pos + t])
            if nxt in self._prefix_pages:
                break                      # a deeper device tail wins
            ent = self._tier_lookup(nxt)
            if ent is not None:
                entries.append((nxt, ent[0], ent[1]))
                break
        if not entries:
            return 0
        # make room down the eviction ladder (colder pages spill to the
        # tier; best-effort — under true pressure the round lands a
        # prefix of itself and the rest stays resident in the tier)
        self.reserve_import_room(len(entries))
        restored = 0
        for (key, ntok, planes), status in zip(
                entries, self.import_prefix_pages(entries)):
            if status == "imported":
                restored += 1
                self._m_tier_restores.inc()
                self._m_tier_restore_bytes.inc(
                    sum(a.nbytes for a in planes.values()))
        return restored

    @property
    def host_tier_page_count(self) -> int:
        return len(self._host_tier)

    @property
    def host_tier_bytes_used(self) -> int:
        return int(self._host_tier_nbytes)

    @property
    def host_tier_occupancy(self) -> float:
        """Host-tier byte budget in use, 0..1 (0.0 when disabled)."""
        if not self.host_tier_limit:
            return 0.0
        return self._host_tier_nbytes / self.host_tier_limit

    @property
    def tier_hit_rate(self) -> float:
        """Fraction of host-tier probes that returned a verified
        payload (0.0 before any probe)."""
        lookups = int(self._m_tier_lookups.value)
        if not lookups:
            return 0.0
        return int(self._m_tier_hits.value) / lookups

    def _release_page(self, page: int) -> None:
        """Drop one slot's reference; a zero-ref page parks on the LRU if
        registered (it keeps serving prefix hits), else frees."""
        self._refcount[page] -= 1
        assert self._refcount[page] >= 0, f"refcount underflow on {page}"
        if self._refcount[page] == 0:
            if page in self._page_key:
                self._lru[page] = None        # MRU end
            else:
                self._free_pages.append(page)

    # -- admission / growth / eviction ------------------------------------

    def admit(self, prompt_len: int) -> int:
        """Claim a slot + the pages the prompt needs; returns the slot id.
        Raises RuntimeError when out of slots/pages (the scheduler checks
        :meth:`can_admit` and queues instead)."""
        if prompt_len > self.max_seq_len:
            raise RuntimeError(
                f"prompt of {prompt_len} tokens exceeds max_seq_len "
                f"{self.max_seq_len}")
        if not self._free_slots:
            raise RuntimeError("no free decode slots")
        need = self.pages_needed(prompt_len)
        if need > self.available_page_count:
            raise RuntimeError(
                f"cache exhausted: need {need} pages, "
                f"{self.available_page_count} free")
        slot = self._free_slots.pop()
        for i in range(need):
            page = self._alloc_page()
            self._page_table[slot, i] = page
            self._refcount[page] = 1
        self._seq_lens[slot] = prompt_len
        self._pt_rev += 1
        self._sl_rev += 1
        self._note_occupancy()
        return slot

    def ensure_capacity(self, slot: int, new_len: int) -> bool:
        """Allocate pages so ``slot`` can hold ``new_len`` tokens. Returns
        False (allocating nothing) when the pool cannot satisfy it — the
        scheduler then evicts or stalls the sequence."""
        if new_len > self.max_seq_len:
            return False
        have = int((self._page_table[slot] >= 0).sum())
        need = self.pages_needed(new_len)
        if need <= have:
            return True
        if need - have > self.available_page_count:
            return False
        for i in range(have, need):
            page = self._alloc_page()
            self._page_table[slot, i] = page
            self._refcount[page] = 1
        self._pt_rev += 1
        self._note_occupancy()
        return True

    def advance(self, slot: int, n: int = 1) -> None:
        self._seq_lens[slot] += n
        self._sl_rev += 1

    def draft_allowance(self, slot: int, reserve: int = 0) -> int:
        """Draft tokens ``slot`` may feed this step beyond its base
        decode token using only its own pages plus strictly-FREE pages,
        AFTER reserving the base token's own growth page, (when the
        write position is shared) its CoW destination, and ``reserve``
        further pages the caller has promised elsewhere (the scheduler
        passes the plain-token page needs of every OTHER slot still
        scheduled this step). Speculation is opportunistic: a rejected
        draft must never cost a registered prefix page its spot (LRU
        eviction) or preempt a running request — this is the claim the
        scheduler re-checks in its capacity loop right before
        allocating, so slots consuming the free list in the same step
        shrink the drafts instead of pushing ANY slot's allocation into
        the eviction/preemption paths a plain step would never enter.
        (Drafts inside already-reserved pages are always free: they
        cost no extra page.)"""
        written = int(self._seq_lens[slot])
        if written >= self.max_seq_len:
            return 0     # at the ceiling: the truncation-stop owns it
        have = int((self._page_table[slot] >= 0).sum())
        base_need = max(0, self.pages_needed(written + 1) - have)
        cow_need = 1 if self.needs_cow(slot, written) else 0
        spare = max(0, len(self._free_pages) - base_need - cow_need
                    - max(0, int(reserve)))
        cap = min((have + base_need + spare) * self.page_size,
                  self.max_seq_len)
        return max(0, cap - written - 1)

    def plain_step_page_need(self, slot: int, n_tokens: int) -> int:
        """Pages ``slot`` will claim this step to write ``n_tokens``
        plain (non-draft) tokens from its current length: growth pages
        plus a CoW destination when the first write position is shared —
        the per-slot reservation the scheduler charges against other
        slots' draft allowances."""
        written = int(self._seq_lens[slot])
        if written >= self.max_seq_len:
            return 0     # at the ceiling: the truncation-stop owns it
        have = int((self._page_table[slot] >= 0).sum())
        grow = max(0, self.pages_needed(
            min(written + max(1, n_tokens), self.max_seq_len)) - have)
        return grow + (1 if self.needs_cow(slot, written) else 0)

    def withhold_pages(self, n: int) -> int:
        """Take up to ``n`` strictly-FREE pages out of circulation (they
        leave every free/available count until :meth:`restore_withheld`)
        — the fault-injection pool-pressure squeeze. SINGLE-HOLDER: there
        is one withheld set and ``restore_withheld`` returns all of it,
        so two concurrent holders (e.g. a router headroom reservation
        alongside an armed squeeze) would release each other's pages.
        Never touches referenced or prefix-LRU pages, so sequence and
        registry state are unaffected. Returns how many were actually
        withheld."""
        take = min(max(0, int(n)), len(self._free_pages))
        for _ in range(take):
            self._withheld.append(self._free_pages.pop())
        if take:
            self._note_occupancy()
        return take

    def restore_withheld(self) -> int:
        """Return every withheld page to the free list (LIFO, restoring
        the pre-withhold pop order). Returns how many came back."""
        n = len(self._withheld)
        while self._withheld:
            self._free_pages.append(self._withheld.pop())
        if n:
            self._note_occupancy()
        return n

    def trim_pages(self, slot: int) -> int:
        """Release ``slot``'s pages beyond what ``seq_len`` needs — the
        host half of speculative-draft rollback. A spec step allocates for
        ``written + 1 + k`` tokens up front; when only ``m < k`` drafts
        are accepted, ``advance(slot, 1 + m)`` moves the valid watermark
        and this returns the over-allocated tail pages to the pool, so the
        page accounting is IDENTICAL to a never-speculated run (trimmed
        pages are always fresh refcount-1 allocations: shared/registered
        prefix pages live below the watermark by construction, and a
        shared tail was CoW'd by ``prepare_write`` before any draft KV
        landed in it). Rejected-draft K/V left INSIDE kept pages sits
        above ``seq_len`` — never read (the ragged kernel masks by
        context length) and overwritten by the next step's writes.
        Returns the number of pages released."""
        keep = self.pages_needed(int(self._seq_lens[slot]))
        have = int((self._page_table[slot] >= 0).sum())
        freed = 0
        # release high indices first: alloc pops the free-list tail, so
        # reverse-order release restores the exact pre-speculation order
        # (allocation is index-contiguous, so the scan is bounded by the
        # pages actually held — not the full page-table width)
        for i in range(have - 1, keep - 1, -1):
            page = int(self._page_table[slot, i])
            if page < 0:
                continue
            self._page_table[slot, i] = -1
            self._release_page(page)
            freed += 1
        if freed:
            self._pt_rev += 1
            self._m_trimmed.inc(freed)
            self._note_occupancy()
        return freed

    def rollback(self, slot: int, new_len: int) -> int:
        """Shrink ``slot``'s valid watermark to ``new_len`` tokens and
        release the pages beyond it (round 19: the draft KV pool's
        self-heal — a rejected draft's K/V, or a whole stale tail after a
        preemption replay diverged the context, rolls back to the longest
        still-valid prefix). ``new_len`` may be 0 (slot keeps its first
        page — the admission invariant every sequence holds). Only ever
        valid on pools whose pages are refcount-1 owned (the draft pool
        never shares/registers pages); returns the pages released."""
        new_len = max(0, int(new_len))
        if new_len > int(self._seq_lens[slot]):
            raise ValueError(
                f"rollback to {new_len} tokens past slot {slot}'s "
                f"watermark {int(self._seq_lens[slot])}")
        if new_len != int(self._seq_lens[slot]):
            self._seq_lens[slot] = new_len
            self._sl_rev += 1
        return self.trim_pages(slot)

    def free(self, slot: int) -> None:
        """Evict: drop the slot's page references (shared pages survive in
        other slots / the prefix LRU), park the slot."""
        for i in range(self.pages_per_slot):
            pg = int(self._page_table[slot, i])
            if pg >= 0:
                self._release_page(pg)
            self._page_table[slot, i] = -1
        self._seq_lens[slot] = 0
        self._pt_rev += 1
        self._sl_rev += 1
        self._free_slots.append(slot)
        self._note_occupancy()

    # -- prefix cache ------------------------------------------------------

    def _chain_key(self, prev: bytes, tokens) -> bytes:
        """Content chain key — delegates to the module-level
        :func:`chain_key` so the registry and the fleet router's affinity
        map hash the SAME chain (see that function's contract)."""
        return chain_key(prev, tokens)

    def _match_prefix(self, tokens):
        """Longest registered prefix of ``tokens`` at page granularity
        (the final page may match a partial fill). The returned
        ``matched_len`` is capped at ``len(tokens)-1`` so at least one
        token is left to feed (the cache stores K/V, not logits) — on a
        full-prompt hit the re-fed token overwrites its own identical K/V
        (deterministic in token+position), CoW-guarded when the page is
        shared. Returns (pages, matched_len)."""
        ps = self.page_size
        n = len(tokens)
        pages: list[int] = []
        matched = 0
        h = b""
        while matched + ps <= n:
            nxt = self._chain_key(h, tokens[matched:matched + ps])
            page = self._prefix_pages.get(nxt)
            if page is None:
                break
            pages.append(page)
            matched += ps
            h = nxt
        # partial tail: longest registered partial fill of the next page
        for t in range(min(ps - 1, n - matched), 0, -1):
            nxt = self._chain_key(h, tokens[matched:matched + t])
            page = self._prefix_pages.get(nxt)
            if page is not None:
                pages.append(page)
                matched += t
                break
        return pages, min(matched, n - 1)

    def admit_prefix(self, tokens, *, headroom=0, soft=False):
        """Admit a sequence whose context is ``tokens``: attach every
        registered prefix page read-only (refcount += 1), allocate fresh
        pages for the rest of the context, set the slot's written length to
        the matched token count. Returns ``(slot, cached_len)`` — the
        scheduler feeds ``tokens[cached_len:]`` through prefill chunks.

        ``headroom`` demands that many extra allocatable pages beyond the
        admission's own need (the scheduler's growth watermark). On
        pressure (or no free slot), ``soft=True`` returns None with
        NOTHING mutated instead of raising — the one owner of the
        can-this-fit accounting, so the check can never diverge from the
        allocation it guards. (Round 21: the host-tier restore that runs
        first is CACHE state, not admission state — it moves strictly-
        free pages onto the evictable LRU, leaving every availability
        count and the admission decision unchanged, so a soft None after
        a restore still mutated nothing the scheduler accounts.)
        """
        n = len(tokens)
        if n > self.max_seq_len:
            raise RuntimeError(
                f"prompt of {n} tokens exceeds max_seq_len "
                f"{self.max_seq_len}")
        if not self._free_slots:
            if soft:
                return None
            raise RuntimeError("no free decode slots")
        if self.enable_prefix_cache:
            # round 21: restore-aware admission — pull the chain's
            # host-tier survivors back into the registry so the match
            # walk below pins them like never-evicted pages
            self._tier_restore(tokens)
        shared, matched = (self._match_prefix(tokens)
                           if self.enable_prefix_cache else ([], 0))
        need_total = self.pages_needed(n)
        need_fresh = need_total - len(shared)
        # matched pages sitting on the LRU are about to be re-pinned by
        # THIS admission: they cannot also serve the fresh allocations
        lru_matched = sum(1 for p in shared if p in self._lru)
        available = self.available_page_count - lru_matched
        if need_fresh + headroom > available:
            if soft:
                return None
            raise RuntimeError(
                f"cache exhausted: need {need_fresh} pages, "
                f"{available} free")
        self._m_prefix_query.inc(n)
        self._m_prefix_hit.inc(matched)
        slot = self._free_slots.pop()
        for i, page in enumerate(shared):
            self._page_table[slot, i] = page
            if self._refcount[page] == 0:
                self._lru.pop(page, None)     # re-pinned off the LRU
            self._refcount[page] += 1
        for i in range(len(shared), need_total):
            page = self._alloc_page()
            self._page_table[slot, i] = page
            self._refcount[page] = 1
        self._seq_lens[slot] = matched
        self._pt_rev += 1
        self._sl_rev += 1
        self._note_occupancy()
        return slot, matched

    def register_prefix(self, slot: int, tokens, include_tail=True) -> None:
        """Register ``slot``'s pages holding ``tokens`` (a prefilled
        prompt, or its prefilled-so-far prefix) in the prefix registry:
        every full page, plus the partial tail when ``include_tail`` (only
        pass True once the WHOLE prompt has landed — a mid-prompt partial
        key would pin the page's one key slot on a transient fill). Pages
        already registered (or whose key another page already serves) are
        skipped — one page, one key — so progressive per-step calls are
        idempotent."""
        if not self.enable_prefix_cache:
            return
        ps = self.page_size
        h = b""
        pos = 0
        i = 0
        while pos < len(tokens):
            t = min(ps, len(tokens) - pos)
            if t < ps and not include_tail:
                break
            h = self._chain_key(h, tokens[pos:pos + t])
            page = int(self._page_table[slot, i])
            if page < 0:
                break
            if page not in self._page_key and h not in self._prefix_pages:
                self._page_key[page] = h
                self._prefix_pages[h] = page
                # the spill path needs the page's REAL fill (a partial
                # tail spills t rows, never page_size)
                self._page_ntok[page] = t
            pos += t
            i += 1

    @property
    def prefix_hit_rate(self) -> float:
        """Fraction of admitted context tokens served from the prefix
        cache (0.0 when nothing was admitted)."""
        if not self.prefix_query_tokens:
            return 0.0
        return self.prefix_hit_tokens / self.prefix_query_tokens

    # -- KV-page transfer surface (round 20) -------------------------------
    #
    # The export/import half of disaggregated prefill/decode
    # (inference/kv_transfer.py): a prefill replica's registered prompt
    # pages stream to the decode replica addressed by the SAME sha1
    # chain keys, land here as zero-ref registered LRU pages, and the
    # next admission's ``admit_prefix`` walk pins them exactly like
    # locally-prefilled pages — transferred pages serve hits
    # immediately, and a failed transfer unwinds to an accounting state
    # indistinguishable from a colocated run.

    def prefix_page_records(self, tokens):
        """The chain-keyed export walk: every REGISTERED page holding a
        prefix of ``tokens`` — full pages first, then the longest
        registered partial tail — as ``(chain_key, page, ntok)``
        records in chain order. Unlike :meth:`_match_prefix` there is
        no ``n - 1`` feed cap: the exporter ships every page it has
        (the RECEIVER's admission walk re-applies the cap). Stops at
        the first unregistered link (a partially-evicted chain exports
        its surviving prefix — the rest re-prefills colocated). Round
        21: the walk is restore-aware — links the HBM registry lost but
        the host tier kept are re-admitted first, so a cross-replica
        pull reaches THROUGH this replica's host tier with no transfer-
        layer changes."""
        if self.enable_prefix_cache:
            self._tier_restore(tokens)
        ps = self.page_size
        n = len(tokens)
        recs: list[tuple[bytes, int, int]] = []
        pos = 0
        h = b""
        while pos + ps <= n:
            nxt = self._chain_key(h, tokens[pos:pos + ps])
            page = self._prefix_pages.get(nxt)
            if page is None:
                break
            recs.append((nxt, page, ps))
            pos += ps
            h = nxt
        for t in range(min(ps - 1, n - pos), 0, -1):
            nxt = self._chain_key(h, tokens[pos:pos + t])
            page = self._prefix_pages.get(nxt)
            if page is not None:
                recs.append((nxt, page, t))
                break
        return recs

    def pin_page(self, page: int) -> None:
        """Take one extra reference on ``page`` (an in-flight transfer's
        eviction guard — a registered source page must stay put while
        its frames stream). Balanced by :meth:`unpin_page`."""
        if self._refcount[page] == 0:
            self._lru.pop(page, None)
        self._refcount[page] += 1
        self._note_occupancy()

    def unpin_page(self, page: int) -> None:
        self._release_page(page)
        self._note_occupancy()

    def read_page_payload(self, page: int, ntok: int) -> dict:
        """One page's transferable payload: the first ``ntok`` token
        rows of every layer's K/V (+ the int8 scale planes when the
        pool is quantized) as host numpy arrays — exactly the bytes a
        decode replica needs to serve this page bit-identically."""
        out = {"k": np.asarray(self.k_pages[:, page, :ntok]),
               "v": np.asarray(self.v_pages[:, page, :ntok])}
        if self.quantize_kv:
            out["ks"] = np.asarray(self.k_scales[:, page, :ntok])
            out["vs"] = np.asarray(self.v_scales[:, page, :ntok])
        return out

    def import_prefix_page(self, key: bytes, ntok: int, payload: dict):
        """Land one transferred page: allocate a pool page, write the
        payload rows, register ``key`` and park the page zero-ref on
        the LRU (it serves prefix hits immediately; the admission that
        consumes it pins it like any locally-prefilled page).

        Returns ``"imported"``, ``"present"`` (idempotent re-delivery:
        the key is already registered — a retransmitted frame is a
        no-op), or ``None`` when the pool has no allocatable page (the
        receiver's pressure signal — the transfer aborts and the router
        falls back to colocated prefill). Geometry/dtype mismatches are
        CONFIG errors between identically-built replicas: they raise.

        Cost note: each ``.at[].set`` below is an eager functional
        update — a full pool copy per plane per frame. It stays as the
        reference landing path (and the batched path's bit-identity
        oracle); round 21's :meth:`import_prefix_pages` is the batched
        spelling restore rounds and transfer ticks should ride."""
        if not self.enable_prefix_cache:
            raise RuntimeError(
                "import_prefix_page needs enable_prefix_cache=True "
                "(transferred pages land in the prefix registry)")
        if key in self._prefix_pages:
            return "present"
        self._validate_import(ntok, payload)
        if not self._free_pages:
            # transfers claim strictly-FREE pages only: an imported page
            # must never evict a registered page off the LRU (same
            # contract as draft allowances — opportunistic work never
            # costs a warm prefix its spot), which also keeps the
            # failed-transfer unwind exactly reversible
            return None
        page = self._free_pages.pop()
        self._refcount[page] = 0
        self.k_pages = self.k_pages.at[:, page, :ntok].set(payload["k"])
        self.v_pages = self.v_pages.at[:, page, :ntok].set(payload["v"])
        if self.quantize_kv:
            self.k_scales = self.k_scales.at[:, page, :ntok].set(
                payload["ks"])
            self.v_scales = self.v_scales.at[:, page, :ntok].set(
                payload["vs"])
        self._page_key[page] = key
        self._prefix_pages[key] = page
        self._page_ntok[page] = int(ntok)
        self._lru[page] = None                 # MRU end, zero-ref
        self._note_occupancy()
        return "imported"

    def _validate_import(self, ntok: int, payload: dict) -> None:
        """The import landing zone's geometry/dtype gate, shared by the
        per-page and batched paths. Mismatches are CONFIG errors
        between identically-built replicas: they raise."""
        if not (0 < int(ntok) <= self.page_size):
            raise ValueError(
                f"ntok must be in (0, {self.page_size}], got {ntok}")
        want = {"k", "v"} | ({"ks", "vs"} if self.quantize_kv else set())
        if set(payload) != want:
            raise ValueError(
                f"payload planes {sorted(payload)} do not match this "
                f"pool's {sorted(want)} (fp vs int8-KV replicas must be "
                "built identically)")
        shape = (self.num_layers, int(ntok), self.num_kv_heads,
                 self.head_dim)
        for name in ("k", "v"):
            a = payload[name]
            if tuple(a.shape) != shape or a.dtype != self.k_pages.dtype:
                raise ValueError(
                    f"plane '{name}' is {a.dtype}{tuple(a.shape)}, "
                    f"expected {self.k_pages.dtype}{shape}")
        if self.quantize_kv:
            for name in ("ks", "vs"):
                a = payload[name]
                if tuple(a.shape) != shape[:3] \
                        or a.dtype != self.k_scales.dtype:
                    raise ValueError(
                        f"plane '{name}' is {a.dtype}{tuple(a.shape)}, "
                        f"expected {self.k_scales.dtype}{shape[:3]}")

    def import_prefix_pages(self, entries):
        """The BATCHED landing zone (round 21): land a whole restore
        round / transfer tick of ``(key, ntok, payload)`` entries with
        ONE donated scatter per (K, V, scale) plane
        (:func:`batched_import_rows`) instead of the per-page path's
        eager full-pool copies. Registration semantics are exactly
        :meth:`import_prefix_page`'s — zero-ref LRU parking, strictly-
        free allocation, idempotent re-delivery — and the landed
        payloads are bit-identical to the per-page path (locked by
        tests/test_prefix_cache.py). Validation runs for EVERY entry
        before anything mutates. Returns a per-entry status list
        aligned with ``entries``: ``"imported"`` / ``"present"`` /
        ``None`` (pool pressure — once the free list dries mid-round,
        every later entry reads None)."""
        if not self.enable_prefix_cache:
            raise RuntimeError(
                "import_prefix_pages needs enable_prefix_cache=True "
                "(transferred pages land in the prefix registry)")
        entries = list(entries)
        for _, ntok, payload in entries:
            self._validate_import(ntok, payload)
        statuses: list = [None] * len(entries)
        landings = []       # (entry idx, key, ntok, payload, page)
        claimed: set[bytes] = set()
        for i, (key, ntok, payload) in enumerate(entries):
            if key in self._prefix_pages or key in claimed:
                statuses[i] = "present"
                continue
            if not self._free_pages:
                continue                       # stays None: pressure
            landings.append((i, key, int(ntok), payload,
                             self._free_pages.pop()))
            claimed.add(key)
        if not landings:
            return statuses
        self._scatter_landings(landings)
        for i, key, ntok, _, page in landings:
            self._refcount[page] = 0
            self._page_key[page] = key
            self._prefix_pages[key] = page
            self._page_ntok[page] = ntok
            self._lru[page] = None             # MRU end, zero-ref
            statuses[i] = "imported"
        self._note_occupancy()
        return statuses

    def _scatter_landings(self, landings) -> None:
        """Flatten one batch's (page, row) destinations and land every
        plane with a single donated device scatter. The flat row axis
        pads to a power of two (padding rows route to the ``num_pages``
        out-of-bounds sentinel and drop), so the jit traces per padded
        WIDTH, not per exact row count."""
        total = sum(ntok for _, _, ntok, _, _ in landings)
        cap = 1
        while cap < total:
            cap *= 2
        pg = np.full((cap,), self.num_pages, np.int32)
        row = np.zeros((cap,), np.int32)
        kv_shape = (self.num_layers, cap, self.num_kv_heads,
                    self.head_dim)
        vals = {"k": np.zeros(kv_shape, self.k_pages.dtype),
                "v": np.zeros(kv_shape, self.k_pages.dtype)}
        if self.quantize_kv:
            s_shape = kv_shape[:3]
            vals["ks"] = np.zeros(s_shape, self.k_scales.dtype)
            vals["vs"] = np.zeros(s_shape, self.k_scales.dtype)
        off = 0
        for _, _, ntok, payload, page in landings:
            pg[off:off + ntok] = page
            row[off:off + ntok] = np.arange(ntok, dtype=np.int32)
            for name in vals:
                vals[name][:, off:off + ntok] = payload[name]
            off += ntok
        pg = jnp.asarray(pg)
        row = jnp.asarray(row)
        for name, pool_attr in (("k", "k_pages"), ("v", "v_pages"),
                                ("ks", "k_scales"), ("vs", "v_scales")):
            if name not in vals:
                continue
            setattr(self, pool_attr, _batched_import_rows_jit(
                getattr(self, pool_attr), jnp.asarray(vals[name]), pg,
                row))
            self._m_restore_scatters.inc()

    def discard_imported_prefix(self, keys) -> int:
        """Unwind a failed transfer: unregister + free every page in
        ``keys`` that is still zero-ref (a page an admission already
        pinned is serving real traffic and stays). Pass the keys in
        REVERSE import order so the free list recovers its exact
        pre-transfer pop order — after the unwind the pool accounting
        is indistinguishable from a run where the transfer never
        happened. (Round 21: deliberately NO host-tier spill here — an
        unwind must leave no trace, and a half-transferred chain in the
        tier would be exactly such a trace.) Returns the pages freed."""
        dropped = 0
        for key in keys:
            page = self._prefix_pages.get(key)
            if page is None or int(self._refcount[page]) != 0:
                continue
            del self._prefix_pages[key]
            del self._page_key[page]
            self._page_ntok.pop(page, None)
            self._lru.pop(page, None)
            self._free_pages.append(page)
            dropped += 1
        if dropped:
            self._note_occupancy()
        return dropped

    # -- copy-on-write -----------------------------------------------------

    def needs_cow(self, slot: int, pos: int) -> bool:
        """True when writing position ``pos`` would touch a page some
        OTHER reference also holds (refcount >= 2) — the write must go to
        a private copy."""
        page = int(self._page_table[slot, pos // self.page_size])
        return page >= 0 and int(self._refcount[page]) >= 2

    def prepare_write(self, slot: int, pos: int):
        """Make ``slot``'s page at ``pos`` privately writable. Returns
        ``None`` when it already is, else ``(src, dst)`` pool indices for
        the device-side copy (:func:`paged_copy_pages`) the caller must
        thread through its next step. The shared source page keeps its
        registration and remaining references; the copy is owned."""
        i = pos // self.page_size
        page = int(self._page_table[slot, i])
        if page < 0 or int(self._refcount[page]) < 2:
            return None
        dst = self._alloc_page()
        self._refcount[dst] = 1
        self._page_table[slot, i] = dst
        self._pt_rev += 1
        self._refcount[page] -= 1   # >= 1 left: stays pinned, registered
        self._m_cow.inc()
        self._note_occupancy()
        return page, dst

    # -- device views ------------------------------------------------------

    def page_table_device(self) -> jnp.ndarray:
        # upload from a PRIVATE copy: the async engine mutates the live
        # numpy bookkeeping (advance/growth) right after dispatch, while
        # the dispatched step's H2D transfer may still be in flight — an
        # aliased buffer would race the device read
        rev, dev = self._pt_dev
        if rev != self._pt_rev:
            dev = jnp.asarray(self._page_table.copy())
            self._pt_dev = (self._pt_rev, dev)
        return dev

    def seq_lens_device(self) -> jnp.ndarray:
        rev, dev = self._sl_dev
        if rev != self._sl_rev:
            dev = jnp.asarray(self._seq_lens.copy())
            self._sl_dev = (self._sl_rev, dev)
        return dev

    def seq_len(self, slot: int) -> int:
        return int(self._seq_lens[slot])

    def slot_pages(self, slot: int) -> jnp.ndarray:
        return jnp.asarray(self._page_table[slot])

    def update_pages(self, k_pages, v_pages, k_scales=None,
                     v_scales=None) -> None:
        """Adopt the pools returned by a jitted prefill/decode step (scale
        planes too on the int8-KV path)."""
        self.k_pages = k_pages
        self.v_pages = v_pages
        if k_scales is not None:
            self.k_scales = k_scales
        if v_scales is not None:
            self.v_scales = v_scales
