"""Paged KV cache manager — the serving cache behind paged decode attention.

Reference shape: the vLLM-style block manager behind the reference's
``block_multihead_attention`` serving path, TPU-native: the cache is a POOL
of fixed-size pages ``[num_layers, num_pages, page_size, kv_heads,
head_dim]`` (one stacked array per K and V so the decode jit sees ONE
pytree leaf each), and each admitted sequence owns a list of pages through
a per-slot page table. Admission/eviction move pages between the free list
and slots without copying K/V — fragmentation-free continuous batching.

Split of responsibilities:

- **host side (this class)**: page free list, slot free list, admission
  (can the prompt + headroom fit?), per-step growth (allocate a page when a
  sequence crosses a page boundary), eviction. All O(pages) numpy/python —
  never inside a compiled program.
- **device side (pure functions below)**: the scatters that write prefill
  K/V and per-step decode K/V into the page pool. They are shape-stable
  jnp functions traced INTO the prefill/decode jits (models/gpt.py), so the
  cache arrays never round-trip through the host.

Page-table convention (shared with ops/pallas/paged_attention):
``page_table[slot, i]`` is the pool index of the slot's i-th page, ``-1``
when unallocated; ``seq_lens[slot]`` counts tokens already written (0 =
empty slot). Writes to unallocated/out-of-range positions are routed out of
bounds and dropped (``mode="drop"``) rather than corrupting page 0.
"""
from __future__ import annotations

import math

import numpy as np

import jax.numpy as jnp


def pages_needed(length: int, page_size: int) -> int:
    """Pages a ``length``-token sequence occupies (>= 1) — the ONE spelling
    of the ceil-div every pool-sizing site shares."""
    return math.ceil(max(length, 1) / page_size)


# ---------------------------------------------------------------------------
# device-side pure scatter helpers (traced into the prefill/decode jits)
# ---------------------------------------------------------------------------


def paged_write_tokens(pages, tok, page_table, positions, page_size):
    """Write ONE token per slot into the page pool (the decode-step write).

    pages: [num_pages, page_size, kv_heads, head_dim]; tok: [batch,
    kv_heads, head_dim]; page_table: [batch, pages_per_slot] int32;
    positions: [batch] int32 write position per slot (< 0 = inactive slot,
    dropped). Returns the updated pool.
    """
    num_pages = pages.shape[0]
    b = tok.shape[0]
    pos = jnp.maximum(positions, 0)
    pg = page_table[jnp.arange(b), pos // page_size]
    # inactive slots and unallocated (-1) entries route out of bounds
    pg = jnp.where((positions >= 0) & (pg >= 0), pg, num_pages)
    return pages.at[pg, pos % page_size].set(tok, mode="drop")


def paged_write_prefill(pages, seq, pages_for_slot, length, page_size):
    """Scatter one slot's prompt K/V into its pages (copy-on-prefill).

    pages: [num_pages, page_size, kv_heads, head_dim]; seq: [s_pad,
    kv_heads, head_dim] (positions >= length are padding and dropped);
    pages_for_slot: [pages_per_slot] int32 (-1 unallocated); length: scalar.
    """
    num_pages = pages.shape[0]
    s_pad = seq.shape[0]
    i = jnp.arange(s_pad)
    pg = pages_for_slot[jnp.minimum(i // page_size,
                                    pages_for_slot.shape[0] - 1)]
    pg = jnp.where((i < length) & (pg >= 0), pg, num_pages)
    return pages.at[pg, i % page_size].set(seq, mode="drop")


# ---------------------------------------------------------------------------
# host-side manager
# ---------------------------------------------------------------------------


class KVCacheManager:
    """Owns the page pool + page table + free lists for one model.

    ``num_pages`` bounds total cached tokens (``num_pages * page_size``);
    ``max_batch`` bounds concurrent sequences (decode-step batch — the
    FIXED jit shape); ``max_seq_len`` bounds per-sequence length (page-table
    width). ``page_size=None`` consults the autotuned
    :func:`~paddle_tpu.ops.pallas.paged_attention.preferred_page_size`.
    """

    def __init__(self, num_layers, num_kv_heads, head_dim, *, num_pages,
                 max_batch, max_seq_len, page_size=None, num_q_heads=None,
                 dtype=jnp.float32):
        from ..ops.pallas.paged_attention import preferred_page_size

        if page_size is None:
            page_size = preferred_page_size(
                num_q_heads or num_kv_heads, num_kv_heads, head_dim, dtype)
        self.num_layers = num_layers
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.max_batch = int(max_batch)
        self.max_seq_len = int(max_seq_len)
        self.pages_per_slot = math.ceil(self.max_seq_len / self.page_size)
        shape = (num_layers, self.num_pages, self.page_size,
                 num_kv_heads, head_dim)
        self.k_pages = jnp.zeros(shape, dtype)
        self.v_pages = jnp.zeros(shape, dtype)
        # host-side bookkeeping (numpy; uploaded per step as small arrays)
        self._page_table = np.full(
            (self.max_batch, self.pages_per_slot), -1, np.int32)
        self._seq_lens = np.zeros((self.max_batch,), np.int32)
        self._free_pages = list(range(self.num_pages - 1, -1, -1))  # pop()
        self._free_slots = list(range(self.max_batch - 1, -1, -1))

    # -- capacity ----------------------------------------------------------

    @property
    def free_page_count(self) -> int:
        return len(self._free_pages)

    @property
    def free_slot_count(self) -> int:
        return len(self._free_slots)

    def pages_needed(self, length: int) -> int:
        return pages_needed(length, self.page_size)

    def can_admit(self, prompt_len: int) -> bool:
        return (bool(self._free_slots)
                and prompt_len <= self.max_seq_len
                and self.pages_needed(prompt_len) <= len(self._free_pages))

    # -- admission / growth / eviction ------------------------------------

    def admit(self, prompt_len: int) -> int:
        """Claim a slot + the pages the prompt needs; returns the slot id.
        Raises RuntimeError when out of slots/pages (the scheduler checks
        :meth:`can_admit` and queues instead)."""
        if prompt_len > self.max_seq_len:
            raise RuntimeError(
                f"prompt of {prompt_len} tokens exceeds max_seq_len "
                f"{self.max_seq_len}")
        if not self._free_slots:
            raise RuntimeError("no free decode slots")
        need = self.pages_needed(prompt_len)
        if need > len(self._free_pages):
            raise RuntimeError(
                f"cache exhausted: need {need} pages, "
                f"{len(self._free_pages)} free")
        slot = self._free_slots.pop()
        for i in range(need):
            self._page_table[slot, i] = self._free_pages.pop()
        self._seq_lens[slot] = prompt_len
        return slot

    def ensure_capacity(self, slot: int, new_len: int) -> bool:
        """Allocate pages so ``slot`` can hold ``new_len`` tokens. Returns
        False (allocating nothing) when the pool cannot satisfy it — the
        scheduler then evicts or stalls the sequence."""
        if new_len > self.max_seq_len:
            return False
        have = int((self._page_table[slot] >= 0).sum())
        need = self.pages_needed(new_len)
        if need <= have:
            return True
        if need - have > len(self._free_pages):
            return False
        for i in range(have, need):
            self._page_table[slot, i] = self._free_pages.pop()
        return True

    def advance(self, slot: int, n: int = 1) -> None:
        self._seq_lens[slot] += n

    def free(self, slot: int) -> None:
        """Evict: return the slot's pages to the pool, park the slot."""
        for i in range(self.pages_per_slot):
            pg = int(self._page_table[slot, i])
            if pg >= 0:
                self._free_pages.append(pg)
            self._page_table[slot, i] = -1
        self._seq_lens[slot] = 0
        self._free_slots.append(slot)

    # -- device views ------------------------------------------------------

    def page_table_device(self) -> jnp.ndarray:
        return jnp.asarray(self._page_table)

    def seq_lens_device(self) -> jnp.ndarray:
        return jnp.asarray(self._seq_lens)

    def seq_len(self, slot: int) -> int:
        return int(self._seq_lens[slot])

    def slot_pages(self, slot: int) -> jnp.ndarray:
        return jnp.asarray(self._page_table[slot])

    def update_pages(self, k_pages, v_pages) -> None:
        """Adopt the pools returned by a jitted prefill/decode step."""
        self.k_pages = k_pages
        self.v_pages = v_pages
