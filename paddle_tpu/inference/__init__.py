"""paddle.inference parity: Config + Predictor over saved StableHLO.

Reference: python/paddle/inference Config/Predictor wrapping the C++
AnalysisPredictor (inference/api/analysis_predictor.h:100) — load model,
run IR analysis passes, zero-copy run. TPU-native serving path
(SURVEY.md §7.2 L9): artifacts are the serialized-StableHLO programs
written by ``paddle_tpu.jit.save`` / ``paddle_tpu.static.save_inference_model``;
"analysis passes" are XLA's compile pipeline at first run; zero-copy handles
are device arrays with host staging only at copy_from/to_cpu.
"""
from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np


class Config:
    """Predictor configuration (reference: paddle.inference.Config).
    ``prog_file``/``params_file`` accept the artifact prefix produced by
    jit.save / static.save_inference_model."""

    def __init__(self, prog_file: str | None = None,
                 params_file: str | None = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._prefix = prog_file
        self._device = "tpu"
        self._memory_pool_mb = None
        self._ir_optim = True
        self._glog_info = False

    def set_prog_file(self, path: str):
        self._prefix = path[:-len(".pdmodel")] if path.endswith(".pdmodel") else path

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"  # accelerator alias; compute stays on TPU

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device == "tpu"

    def switch_ir_optim(self, flag: bool = True):
        self._ir_optim = flag

    def disable_glog_info(self):
        self._glog_info = False

    def enable_memory_optim(self, flag: bool = True):
        pass  # XLA buffer assignment already does liveness-based reuse

    def summary(self) -> str:
        return (f"Config(prefix={self._prefix!r}, device={self._device}, "
                f"ir_optim={self._ir_optim})")


class Tensor_:
    """Input/output handle (reference: paddle.inference.Tensor — zero-copy
    handles onto executor buffers)."""

    def __init__(self, name: str):
        self._name = name
        self._array = None

    def name(self):
        return self._name

    def copy_from_cpu(self, data: np.ndarray):
        self._array = jnp.asarray(data)

    def reshape(self, shape):
        if self._array is not None:
            self._array = self._array.reshape(shape)

    def shape(self):
        return list(self._array.shape) if self._array is not None else []

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._array)


class Predictor:
    """Loads the artifact and runs the compiled program (reference:
    create_predictor -> AnalysisPredictor::Run)."""

    def __init__(self, config: Config):
        from jax import export as jax_export

        prefix = config._prefix
        if prefix is None:
            raise ValueError("Config needs a model path prefix")
        if not os.path.exists(prefix + ".pdmodel"):
            raise FileNotFoundError(prefix + ".pdmodel")
        with open(prefix + ".pdmodel", "rb") as f:
            self._exported = jax_export.deserialize(f.read())
        self._params = {n: jnp.asarray(a) for n, a in
                        np.load(prefix + ".pdiparams.npz").items()}
        with open(prefix + ".pdmeta", "rb") as f:
            self._meta = pickle.load(f)
        if "feed_names" in self._meta:  # static.save_inference_model artifact
            self._input_names = list(self._meta["feed_names"])
        else:  # jit.save artifact: positional specs
            self._input_names = [
                (s[2] or f"input_{i}")
                for i, s in enumerate(self._meta.get("specs", []))]
        self._inputs = {n: Tensor_(n) for n in self._input_names}
        n_out = len(self._exported.out_avals)
        self._output_names = [f"output_{i}" for i in range(n_out)]
        self._outputs = {n: Tensor_(n) for n in self._output_names}

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name: str) -> Tensor_:
        return self._inputs[name]

    def get_output_names(self):
        return list(self._output_names)

    def get_output_handle(self, name: str) -> Tensor_:
        return self._outputs[name]

    def run(self, inputs: list | None = None):
        """Execute. With ``inputs`` (list of numpy arrays, reference's
        Predictor.run(list) overload) returns the outputs directly."""
        if inputs is not None:
            for n, a in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(np.asarray(a))
        datas = [self._inputs[n]._array for n in self._input_names]
        if any(d is None for d in datas):
            missing = [n for n in self._input_names
                       if self._inputs[n]._array is None]
            raise RuntimeError(f"inputs not set: {missing}")
        outs = self._exported.call(self._params, *datas)
        for n, o in zip(self._output_names, outs):
            self._outputs[n]._array = o
        if inputs is not None:
            return [np.asarray(o) for o in outs]
        return None

    def clone(self):
        """Independent handles over the shared compiled program (reference:
        AnalysisPredictor::Clone gives each thread its own IO buffers)."""
        import copy

        c = copy.copy(self)
        c._inputs = {n: Tensor_(n) for n in self._input_names}
        c._outputs = {n: Tensor_(n) for n in self._output_names}
        return c


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def get_version() -> str:
    return "paddle-tpu-0.1"


PrecisionType = type("PrecisionType", (), {"Float32": 0, "Half": 1, "Int8": 2})
PlaceType = type("PlaceType", (), {"CPU": 0, "GPU": 1, "XPU": 2, "CUSTOM": 3})


def __getattr__(name):
    # round-7 serving subsystem: lazy so importing paddle_tpu.inference for
    # the StableHLO Predictor never pulls the models package
    lazy = {"ServingPredictor": ".serving", "Request": ".serving",
            "KVCacheManager": ".kv_cache",
            # round-18 fleet layer: router + fleet-side request handle
            "FleetRouter": ".fleet_serving",
            "FleetRequest": ".fleet_serving",
            # round-20 disaggregated prefill/decode KV-page wire
            "KVPageTransfer": ".kv_transfer",
            "TransferConfig": ".kv_transfer",
            "FrameError": ".kv_transfer",
            # round-17 resilience layer: SLO shedding + fault injection
            "SLOConfig": ".serving",
            "FaultPlan": ".faults",
            "InjectedFault": ".faults",
            # round-12 speculative decoding draft source (+ the round-19
            # model-based truncated-layer self-draft)
            "DraftProposer": ".draft",
            "ModelDraftProposer": ".draft",
            "ModelDraftEngine": ".draft",
            # round-10 quantized serving conversion
            "quantize_serving_params": ".quantize",
            "quantize_weight": ".quantize",
            "serving_weight_bytes": ".quantize"}
    if name in lazy:
        import importlib

        return getattr(importlib.import_module(lazy[name], __name__), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = ["Config", "Predictor", "Tensor_", "create_predictor",
           "get_version", "PrecisionType", "PlaceType",
           "ServingPredictor", "Request", "KVCacheManager",
           "FleetRouter", "FleetRequest",
           "KVPageTransfer", "TransferConfig", "FrameError",
           "SLOConfig", "FaultPlan", "InjectedFault",
           "DraftProposer", "ModelDraftProposer", "ModelDraftEngine",
           "quantize_serving_params", "quantize_weight",
           "serving_weight_bytes"]
