"""Crash-consistent KV-page streaming between serving replicas (round 20).

The wire layer of disaggregated prefill/decode
(``inference/fleet_serving.py``): a prefill replica runs a prompt
through the ordinary unified step, then its finished KV pages — int8
payloads PLUS their fp32 scale planes on the quantized pool, partial
tail pages included — stream to the decode replica the prefix-affinity
map names, where they land through the prefix-cache registry
(:meth:`KVCacheManager.import_prefix_page`) and immediately serve hits.
This module owns everything between the two pools, as a first-class
ROBUSTNESS layer:

- **Frames** (:func:`encode_frame` / :func:`decode_frame`) — one page
  per frame, addressed by the page's sha1 CHAIN KEY (the same content
  chain the prefix registries and the router's affinity map hash, so a
  frame is meaningful to any replica that derives the same chain) with
  the valid-token count (partial tails ship exactly their filled rows)
  and a CRC32 over the entire header+payload body. A corrupt frame is
  DETECTED at decode — :class:`FrameError` — never silently ingested.
- **Bounded in-flight window** — at most ``window`` unacked frames; a
  frame is acked when the receiver imports (or already holds) its key.
- **Per-frame timeout + exponential backoff + bounded retries** — a
  dropped frame retransmits after ``timeout_ticks * backoff**retries``
  scheduler ticks; a checksum-failed frame nacks and retransmits next
  tick; either way at most ``max_retries`` retransmits, then the whole
  transfer FAILS (the router's cue to fall back to colocated prefill).
- **Idempotent receive** — re-delivered frames are no-ops keyed by
  chain key (``"present"``), so retransmission can never double-land.
- **Crash-consistent teardown** — the source's pages are pinned for the
  transfer's lifetime (an LRU eviction mid-stream would ship a reused
  page); a transfer whose source or destination replica dies mid-stream
  fails immediately (the cache accessors return ``None`` for a DEAD
  replica — a crashed process's pool is unreadable, period); a FAILED
  transfer unwinds every page it imported
  (:meth:`KVCacheManager.discard_imported_prefix`, reverse order) so
  the decode-side free lists / refcounts / LRU / scale planes are
  indistinguishable from a run where the transfer never happened.

Fault seams (``inference/faults.py``, fired once per frame put on the
wire — fresh sends and retransmits alike): ``transfer_drop`` loses the
frame in flight (timeout recovery), ``transfer_corrupt`` flips a byte
of the encoded bytes before delivery (checksum recovery). Both are
RETURNING seams under the one-module-global-check disarmed contract.

The transfer never raises out of :meth:`KVPageTransfer.tick` — failure
is a STATE (``FAILED`` + ``failure`` reason), because the only caller
is the fleet router's tick loop and a request must degrade to the
colocated path, not crash the fleet.
"""
from __future__ import annotations

import struct
import zlib

import numpy as np

from .faults import fault_point

__all__ = ["FrameError", "TransferConfig", "KVPageTransfer",
           "encode_frame", "decode_frame", "SENDING", "DONE", "FAILED"]

#: transfer lifecycle states
SENDING, DONE, FAILED = "sending", "done", "failed"

_MAGIC = b"KVTX"
_VERSION = 1


class FrameError(RuntimeError):
    """A frame that failed to decode — truncation, bad magic/version,
    or a checksum mismatch. The receiver treats every one of these as
    wire corruption: detected, counted, never ingested."""


def encode_frame(key: bytes, ntok: int, planes: dict) -> bytes:
    """Serialize one page frame: ``magic | version | crc32(body) | body``
    where the body is the chain key, the valid-token count and every
    payload plane (name, dtype, shape, raw bytes) in sorted-name order.
    The CRC covers the ENTIRE body, so corruption anywhere — key,
    counts, shapes or payload — fails :func:`decode_frame`."""
    body = bytearray()
    body += struct.pack(">H", len(key)) + bytes(key)
    body += struct.pack(">IB", int(ntok), len(planes))
    for name in sorted(planes):
        a = np.ascontiguousarray(planes[name])
        nm = name.encode()
        dt = a.dtype.str.encode()
        raw = a.tobytes()
        body += struct.pack(">B", len(nm)) + nm
        body += struct.pack(">B", len(dt)) + dt
        body += struct.pack(">B", a.ndim)
        body += struct.pack(f">{a.ndim}I", *a.shape)
        body += struct.pack(">I", len(raw)) + raw
    crc = zlib.crc32(bytes(body)) & 0xFFFFFFFF
    return _MAGIC + struct.pack(">BI", _VERSION, crc) + bytes(body)


def decode_frame(buf: bytes):
    """Parse + verify one frame. Returns ``(key, ntok, planes)``;
    raises :class:`FrameError` on ANY malformation (the checksum is
    checked before a single body byte is interpreted)."""
    if len(buf) < 9 or buf[:4] != _MAGIC:
        raise FrameError("bad frame magic")
    version, crc = struct.unpack(">BI", buf[4:9])
    if version != _VERSION:
        raise FrameError(f"unknown frame version {version}")
    body = buf[9:]
    if (zlib.crc32(body) & 0xFFFFFFFF) != crc:
        raise FrameError("frame checksum mismatch")
    try:
        off = 0
        (klen,) = struct.unpack_from(">H", body, off)
        off += 2
        key = bytes(body[off:off + klen])
        off += klen
        ntok, nplanes = struct.unpack_from(">IB", body, off)
        off += 5
        planes = {}
        for _ in range(nplanes):
            (nlen,) = struct.unpack_from(">B", body, off)
            off += 1
            name = body[off:off + nlen].decode()
            off += nlen
            (dlen,) = struct.unpack_from(">B", body, off)
            off += 1
            dt = np.dtype(body[off:off + dlen].decode())
            off += dlen
            (ndim,) = struct.unpack_from(">B", body, off)
            off += 1
            shape = struct.unpack_from(f">{ndim}I", body, off)
            off += 4 * ndim
            (rlen,) = struct.unpack_from(">I", body, off)
            off += 4
            raw = body[off:off + rlen]
            off += rlen
            if len(raw) != rlen:
                raise FrameError("truncated frame payload")
            planes[name] = np.frombuffer(raw, dt).reshape(shape).copy()
        return key, int(ntok), planes
    except (struct.error, ValueError, UnicodeDecodeError) as e:
        # a frame that PASSED the checksum but fails to parse is still
        # wire corruption from the receiver's point of view (e.g. a
        # truncation that sheared the CRC'd region off entirely)
        raise FrameError(f"malformed frame body: {e}") from e


class TransferConfig:
    """Knobs of one KV-page stream. ``timeout_ticks`` and the retransmit
    backoff are in fleet SCHEDULER TICKS (the router drives transfers
    once per tick) — a dropped frame's k-th retransmit waits
    ``timeout_ticks * backoff**k`` ticks, and every frame retransmits at
    most ``max_retries`` times before the transfer fails."""

    def __init__(self, *, window=4, max_retries=3, timeout_ticks=2,
                 backoff=2.0):
        self.window = int(window)
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.max_retries = int(max_retries)
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, "
                             f"got {max_retries}")
        self.timeout_ticks = int(timeout_ticks)
        if self.timeout_ticks < 1:
            raise ValueError(f"timeout_ticks must be >= 1, "
                             f"got {timeout_ticks}")
        self.backoff = float(backoff)
        if self.backoff < 1.0:
            raise ValueError(f"backoff must be >= 1.0, got {backoff}")


class _Frame:
    """Sender-side in-flight record of one unacked frame."""

    __slots__ = ("seq", "retries", "resend_at")

    def __init__(self, seq: int):
        self.seq = seq
        self.retries = 0
        self.resend_at = 0     # tick at/after which to retransmit


class KVPageTransfer:
    """One chain-key-addressed page stream from a source cache to a
    destination cache.

    ``records`` is the export walk's ``[(chain_key, page, ntok)]``
    (:meth:`KVCacheManager.prefix_page_records`); ``src_cache_fn`` /
    ``dst_cache_fn`` return the live :class:`KVCacheManager` — or
    ``None`` once the owning replica is DEAD (a crashed process's pool
    is unreadable; the router binds these to the replica wrappers so a
    restart's FRESH cache can never be mistaken for the dead one's).
    The router drives :meth:`tick` once per scheduler round and reads
    ``state`` / ``failure`` / ``backlog``; ``instruments`` (a
    :class:`~paddle_tpu.observability.fleet.FleetInstruments`, optional)
    receives the frame/byte/retry/corruption counters.
    """

    def __init__(self, records, src_cache_fn, dst_cache_fn, *,
                 config=None, instruments=None, src_rid=-1, dst_rid=-1):
        if not records:
            raise ValueError("a transfer needs at least one page record")
        self.cfg = config if config is not None else TransferConfig()
        self.records = list(records)
        self._src_fn = src_cache_fn
        self._dst_fn = dst_cache_fn
        self.src_rid = int(src_rid)
        self.dst_rid = int(dst_rid)
        self.inst = instruments
        self.state = SENDING
        self.failure: str | None = None
        self.tick_now = 0
        self._cursor = 0                      # next fresh record index
        self._inflight: dict[int, _Frame] = {}
        self._acked: set[int] = set()
        self._imported: list[bytes] = []      # unwind list, import order
        self._pinned = False
        self.frames_sent = 0
        self.bytes_sent = 0
        self.retries = 0
        src = self._src_fn()
        if src is None:
            self._fail("source replica unreadable at transfer start")
            return
        # pin the source pages for the stream's lifetime: a zero-ref
        # registered page could otherwise be evicted (and its pool slot
        # REUSED) between two of its frames
        for _, page, _ in self.records:
            src.pin_page(page)
        self._pinned = True

    @property
    def backlog(self) -> int:
        """Frames not yet acked (queued + in flight) — the healthz
        ``transfer_backlog`` signal and the prefill routing penalty."""
        if self.state != SENDING:
            return 0
        return len(self.records) - len(self._acked)

    # -- teardown -----------------------------------------------------------

    def _unpin(self) -> None:
        if not self._pinned:
            return
        self._pinned = False
        src = self._src_fn()
        if src is None:
            return               # the pool died with its replica
        for _, page, _ in self.records:
            src.unpin_page(page)

    def _finish(self) -> str:
        self.state = DONE
        self._unpin()
        if self.inst is not None:
            self.inst.transfers_completed.inc()
        return self.state

    def _fail(self, reason: str) -> str:
        self.state = FAILED
        self.failure = reason
        # unwind: every page THIS transfer imported (still zero-ref)
        # leaves the destination registry, reverse import order, so the
        # decode-side accounting is indistinguishable from a run where
        # the transfer never happened
        dst = self._dst_fn()
        if dst is not None and self._imported:
            dst.discard_imported_prefix(reversed(self._imported))
        self._imported = []
        self._unpin()
        if self.inst is not None:
            self.inst.transfers_failed.inc()
        return self.state

    def abort(self, reason: str) -> None:
        """Router-side abort (replica death, deadline) — idempotent."""
        if self.state == SENDING:
            self._fail(reason)

    # -- the wire -----------------------------------------------------------

    def _timeout(self, retries: int) -> int:
        return max(1, int(self.cfg.timeout_ticks
                          * self.cfg.backoff ** retries))

    def _send(self, fr: _Frame, src, dst) -> None:
        """Put one frame on the wire: read the (pinned) source page AT
        SEND TIME, encode, pass the two wire seams, deliver, import,
        ack. Drop/corruption leave the frame in flight for the
        timeout/nack machinery; receiver pool pressure fails the whole
        transfer (the classic backpressure-to-fallback edge)."""
        key, page, ntok = self.records[fr.seq]
        buf = encode_frame(key, ntok, src.read_page_payload(page, ntok))
        self.frames_sent += 1
        self.bytes_sent += len(buf)
        if self.inst is not None:
            self.inst.transfer_frames.inc()
            self.inst.transfer_bytes.inc(len(buf))
        if fault_point("transfer_drop"):
            # lost in flight: no delivery, no ack — the per-frame
            # timeout owns recovery (exponential backoff per retry)
            if self.inst is not None:
                self.inst.transfer_drops.inc()
            fr.resend_at = self.tick_now + self._timeout(fr.retries)
            return
        if fault_point("transfer_corrupt"):
            b = bytearray(buf)
            b[len(b) // 2] ^= 0xFF
            buf = bytes(b)
        try:
            rkey, rntok, planes = decode_frame(buf)
        except FrameError:
            # DETECTED by the checksum — never ingested. Nack: the
            # sender retransmits next tick (no timeout wait: the
            # receiver told us, the wire didn't go quiet)
            if self.inst is not None:
                self.inst.transfer_corrupt.inc()
            fr.resend_at = self.tick_now + 1
            return
        got = dst.import_prefix_page(rkey, rntok, planes)
        if got is None:
            self._fail("receiver pool pressure: no free page for import")
            return
        if got == "imported":
            self._imported.append(rkey)
        self._inflight.pop(fr.seq, None)
        self._acked.add(fr.seq)
        if self.inst is not None:
            self.inst.transfer_tokens.inc(rntok)

    def tick(self) -> str:
        """One scheduler round of wire work: retransmit what timed out
        (bounded, backed off), then fill the window with fresh sends.
        Returns the transfer state; NEVER raises — failure is a state
        the router reads."""
        if self.state != SENDING:
            return self.state
        self.tick_now += 1
        src = self._src_fn()
        if src is None:
            return self._fail("source replica lost mid-stream")
        dst = self._dst_fn()
        if dst is None:
            return self._fail("destination replica lost mid-stream")
        for seq in sorted(self._inflight):
            fr = self._inflight.get(seq)
            if fr is None or fr.resend_at > self.tick_now:
                continue
            if fr.retries >= self.cfg.max_retries:
                return self._fail(
                    f"frame {seq} exhausted {self.cfg.max_retries} "
                    "retries")
            fr.retries += 1
            self.retries += 1
            if self.inst is not None:
                self.inst.transfer_retries.inc()
            self._send(fr, src, dst)
            if self.state != SENDING:
                return self.state
        while (self._cursor < len(self.records)
               and len(self._inflight) < self.cfg.window):
            fr = _Frame(self._cursor)
            self._cursor += 1
            self._inflight[fr.seq] = fr
            fr.resend_at = self.tick_now + self._timeout(0)
            self._send(fr, src, dst)
            if self.state != SENDING:
                return self.state
        if len(self._acked) == len(self.records):
            return self._finish()
        return self.state
