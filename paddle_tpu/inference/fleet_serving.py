"""Fault-tolerant multi-replica serving fleet (round 18).

"Millions of users means MANY predictors" (ROADMAP item 1): everything
below one :class:`~paddle_tpu.inference.serving.ServingPredictor` is
production-grade — this module is the fleet layer above it. A
:class:`FleetRouter` fronts N predictor replicas (each possibly mesh-TP)
and makes the headline property true: **replica failure is a routing
event, not an outage**.

Routing (admission-time placement, no per-token hop):

- **Prefix affinity** — the prompt hashes through the SAME sha1 chain
  keys the prefix cache computes (``kv_cache.chain_key``; one key per
  full page, page i folding page i-1). The router keeps a chain-key ->
  replica map; a submission walks its keys DEEPEST-first and lands on
  the replica that already served the longest shared prefix — so
  repeated-system-prompt traffic hits warm pages instead of re-prefilling
  on a random replica. The map is only sound because independently
  constructed :class:`~paddle_tpu.inference.kv_cache.KVCacheManager`
  instances derive identical keys from identical tokens (locked by
  tests/test_prefix_cache.py).
- **Power-of-two-choices fallback** — no affinity hit: two seeded-random
  admittable candidates are drawn and the one with the LOWER load score
  wins (the classic d=2 balancer: near-best-of-N balance at O(1) probes).
  The score reads :meth:`ServingPredictor.healthz` — queue + lanes
  occupied, KV pool occupancy, in-flight ring depth, TTFT-p99 EMA — the
  round-17 load-signal surface built for exactly this consumer.
- **Health gating** — a replica admits only while HEALTHY and its
  :meth:`~ServingPredictor.admission_verdict` is ``None``. The per-tick
  health refresh marks a replica UNHEALTHY while it is stalled or its
  ``healthz()["snapshot_age_s"]`` stamp is stale (a stuck replica stops
  stamping; a merely quiet one, still driven, does not); recovery flips
  it back. DRAINING (``drain()``/``resume()``, the operator surface)
  finishes in-flight work but admits nothing. When no healthy replica
  can admit, submissions queue at the router (``_unrouted``) unless
  healthy replicas exist and ALL of them shed — then the submission
  sheds terminally (fleet-level backpressure, same ``shed_*`` codes).

Failover (the crash-consistent half):

- A replica that raises out of its step — or stalls past
  ``dead_stall_ticks`` — is declared DEAD. Its process state is treated
  as UNREADABLE (a real crash leaves nothing to inspect): the router
  migrates every non-terminal request assigned to it using only what it
  already RECEIVED — the fleet-side ``output_ids`` merged from step
  results. The re-admit feeds ``prompt + received_outputs`` as the new
  context (already-emitted tokens are deduplicated by construction:
  resume from ``len(output_ids)``), carries the remaining output budget,
  and passes the ORIGINAL ``submit_time`` through
  ``add_request(submit_time=)`` so the request's absolute deadline never
  restarts. Greedy continuations are token-identical to an uninterrupted
  run; tokens a dead replica had dispatched but never reported are
  simply regenerated — never double-emitted, because a DEAD replica is
  never stepped or flushed again. Failovers are bounded:
  ``max_failovers`` migrations, then a terminal ``replica_lost`` FAILED
  record. A DEAD slot respawns a fresh predictor after ``restart_ticks``
  (its pages are gone, so its affinity-map entries are purged — routed
  prefixes rebuild warmth organically).

Disaggregated prefill/decode (round 20, ``prefill_replicas > 0``):

- **Roles.** The first ``prefill_replicas`` slots run PREFILL-role
  replicas; the rest are DECODE-role. A fresh submission whose prompt
  spans at least one page lands on the least-loaded healthy prefill
  replica first (p2c scored on the healthz load signals + the
  sender-side ``transfer_backlog``), runs its prompt through the
  ordinary unified step as a 1-token request (prefill chunks + the
  first generated token), and its registered prompt pages then STREAM
  to the decode replica the prefix-affinity map names — the replica
  that will keep serving that prefix — over the
  ``inference/kv_transfer.py`` wire: checksummed chain-key-addressed
  frames (int8-KV payloads ride with their fp32 scale planes; partial
  tails included), a bounded in-flight window, per-frame timeout +
  exponential backoff + bounded retries, idempotent receive. The
  decode admission's ``admit_prefix`` walk pins the imported pages
  exactly like locally-prefilled ones, so the decode replica never
  re-runs the prompt; its seeded sample stream continues bit-identically
  through the handoff (``add_request(sample_offset=)``).
- **Graceful degradation** — the headline robustness property: if no
  healthy prefill replica exists, the transfer exhausts its retries, a
  checksum fails terminally, the receiver has no free page, or either
  endpoint replica dies mid-stream, the request falls back to
  COLOCATED prefill on the decode fleet (today's path) — counted
  (``fleet_prefill_fallbacks``), never failed, and never charged
  against the failover budget: disaggregation existing must never cost
  a request its life. A FAILED transfer unwinds every page it imported,
  so the decode-side accounting (free lists, refcounts, LRU, scale
  planes) is indistinguishable from a colocated run after ANY fault.
- With ``prefill_replicas=0`` (the default) every replica is
  colocated-role and the router is bit-identical to round 18.

The chaos gate (tests/test_fleet_serving.py) extends round 17's
discipline to the fleet: a >= 1k-tick multi-replica churn with the
``replica_crash`` / ``replica_stall`` seams armed
(``inference/faults.py``) — and, disaggregated, the ``transfer_drop``
/ ``transfer_corrupt`` wire seams on top — where after EVERY tick the
fleet-wide invariant holds — submitted == finished + failed + live,
every request ends terminal exactly once, no token emitted twice, no
request lost, every FINISHED stream bit-identical to a fault-free
COLOCATED mirror — and with faults disarmed a single-replica fleet is
bit-identical to a bare ``ServingPredictor`` (and a disaggregated
fleet's emissions bit-identical, greedy and seeded-sampled, to the
colocated fleet's).
"""
from __future__ import annotations

from collections import deque

import numpy as np

from ..observability import FleetInstruments, monotonic, span
from .faults import fault_point
from .kv_cache import prompt_chain_keys
from .kv_transfer import DONE as T_DONE
from .kv_transfer import SENDING as T_SENDING
from .kv_transfer import KVPageTransfer, TransferConfig
from .serving import (FAILED, FINISHED, RUNNING, WAITING, ServingPredictor,
                      deadline_passed, stream_done)

#: replica lifecycle states (the fleet-side state machine; the
#: per-request one stays serving.py's WAITING/RUNNING/FINISHED/FAILED)
HEALTHY, UNHEALTHY, DRAINING, DEAD = ("healthy", "unhealthy", "draining",
                                      "dead")

__all__ = ["FleetRequest", "FleetRouter", "HEALTHY", "UNHEALTHY",
           "DRAINING", "DEAD"]


class FleetRequest:
    """The router-side request handle: fleet identity, the merged output
    stream (built ONLY from step/flush results the router actually
    received — the crash-consistency ledger), and the failover count.
    ``state`` follows serving.py's request states: WAITING while queued
    at the router, RUNNING once placed on a replica, then terminal
    FINISHED / FAILED (``error = {"code", "message"}``)."""

    _next_id = [0]

    def __init__(self, prompt_ids, max_new_tokens=32, eos_token_id=None,
                 temperature=0.0, top_k=0, top_p=1.0, seed=None,
                 deadline_s=None):
        self.fleet_id = FleetRequest._next_id[0]
        FleetRequest._next_id[0] += 1
        self.prompt_ids = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not self.prompt_ids:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = seed
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        if self.deadline_s is not None and self.deadline_s < 0:
            raise ValueError(f"deadline_s must be >= 0, got {deadline_s}")
        # the absolute-deadline anchor: every re-admit passes this stamp
        # through add_request(submit_time=) so the TTL never restarts
        self.submit_time = monotonic()
        self.output_ids: list[int] = []
        self.state = WAITING
        self.error: dict | None = None
        self.truncated = False
        self.replica_id: int | None = None   # current placement
        self.failover_count = 0
        self._inner = None                   # current inner Request
        # round 20 (disaggregation): the request's pipeline phase —
        # ``None`` on a colocated fleet (and for sub-page prompts that
        # never disaggregate), else "prefill" (running on a
        # prefill-role replica) -> "transfer" (KV pages streaming) ->
        # "decode" (on a decode replica; also the forced state after a
        # fallback — a degraded request never re-enters the prefill
        # stage). ``first_token_time`` stamps the first RECEIVED token
        # (the fleet-side TTFT the disagg bench leg gates).
        self.phase: str | None = None
        self.decode_rid: int | None = None
        self._transfer = None
        self.first_token_time: float | None = None
        # True once a prefill-role replica actually accepted this
        # request's prefill stage: from then on the fleet has spent
        # work on it, so later routing failures queue it instead of
        # shedding it (a submit-time degradation spent nothing and
        # stays shed-able — colocated-fleet parity under flood)
        self.prefill_spent = False
        # round 21: one cross-replica prefix pull per request, ever —
        # a failed pull (or a failover after a successful one) falls
        # back to colocated recompute instead of re-chasing pages
        # around a churning fleet
        self.pull_attempted = False

    @property
    def ttft(self) -> float | None:
        """Seconds from fleet submission to the first received token."""
        if self.first_token_time is None:
            return None
        return self.first_token_time - self.submit_time

    @property
    def done(self) -> bool:
        """Budget/eos satisfied by the RECEIVED stream — what failover
        consults before spending a re-admit on a complete request. The
        stop rule is serving.py's ``stream_done`` (one spelling: the
        dedup here must agree with the predictor's emission-drop rule)."""
        if self.truncated:
            return True
        return stream_done(self.output_ids, self.max_new_tokens,
                           self.eos_token_id)

    def past_deadline(self, now=None) -> bool:
        return deadline_passed(self.submit_time, self.deadline_s, now)


class _Replica:
    """One replica slot: the live predictor (``None`` while DEAD — a
    crashed process is unreadable), its fleet state, the inner-request
    -> fleet-request map, and the stall/restart tick counters."""

    __slots__ = ("rid", "sp", "state", "by_inner", "stall_ticks",
                 "stalled_for", "restart_in")

    def __init__(self, rid: int, sp: ServingPredictor):
        self.rid = rid
        self.sp = sp
        self.state = HEALTHY
        self.by_inner: dict[int, FleetRequest] = {}
        self.stall_ticks = 0     # ticks of hang still to serve
        self.stalled_for = 0     # consecutive ticks already hung
        self.restart_in = 0      # DEAD cooldown until respawn


class FleetRouter:
    """N ``ServingPredictor`` replicas behind one admission surface.

    ``submit()`` places a request (prefix-affinity, then
    power-of-two-choices on the healthz load signals, health-gated);
    ``tick()`` drives one fleet scheduler round — every live replica
    steps once, emissions merge into the fleet-side streams, terminal
    inner states sweep out, crashed/stalled replicas fail over;
    ``flush()`` drains the live replicas' in-flight rings. Replica
    construction kwargs forward to ``ServingPredictor`` via
    ``replica_kw`` (every replica is built identically — the fleet's
    page geometry must agree for the affinity keys to mean the same
    pages everywhere).
    """

    def __init__(self, model, num_replicas=2, *, seed=0, max_failovers=2,
                 stale_after_s=5.0, dead_stall_ticks=4, restart_ticks=1,
                 max_affinity_entries=1 << 16, metrics=None,
                 replica_kw=None, prefill_replicas=0, transfer=None,
                 min_transfer_tokens=None, prefix_pulls=False):
        self.num_replicas = int(num_replicas)
        if self.num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1, "
                             f"got {num_replicas}")
        # round 20: disaggregation — the first ``prefill_replicas``
        # slots take the prefill role; at least one decode replica must
        # remain (the decode fleet IS the fallback path, and a fleet
        # that can only prefill can never finish a request)
        self.prefill_replicas = int(prefill_replicas)
        if not 0 <= self.prefill_replicas < self.num_replicas:
            raise ValueError(
                f"prefill_replicas must be in [0, num_replicas), got "
                f"{prefill_replicas} of {num_replicas} (at least one "
                "decode replica must remain — it is the fallback path)")
        if transfer is not None and not isinstance(transfer,
                                                   TransferConfig):
            raise ValueError(f"transfer must be a TransferConfig or "
                             f"None, got {type(transfer).__name__}")
        self.transfer_cfg = (transfer if transfer is not None
                             else TransferConfig())
        # round 21: fleet-global tiered prefixes — a prefix miss on the
        # routed replica that hits on another replica (its pool OR its
        # host tier) becomes a KV-page pull over the transfer wire
        # instead of a recompute. Opt-in: pulls add a transfer phase in
        # front of the admission, so latency-sensitive small fleets can
        # keep the pre-21 place-and-recompute behavior.
        self.prefix_pulls = bool(prefix_pulls)
        self.max_failovers = int(max_failovers)
        if self.max_failovers < 0:
            raise ValueError(f"max_failovers must be >= 0, "
                             f"got {max_failovers}")
        self.stale_after_s = float(stale_after_s)
        if self.stale_after_s <= 0:
            # a non-positive threshold pins every replica UNHEALTHY
            # forever (snapshot_age_s >= 0 always) — a config typo must
            # fail loudly, not as a total routing outage
            raise ValueError(f"stale_after_s must be > 0, "
                             f"got {stale_after_s}")
        self.dead_stall_ticks = int(dead_stall_ticks)
        if self.dead_stall_ticks < 1:
            raise ValueError(f"dead_stall_ticks must be >= 1, "
                             f"got {dead_stall_ticks}")
        self.restart_ticks = max(1, int(restart_ticks))
        self._model = model
        self._replica_kw = dict(replica_kw or {})
        if "replica_id" in self._replica_kw:
            raise ValueError("replica_id is assigned by the router")
        if "role" in self._replica_kw:
            raise ValueError("role is assigned by the router "
                             "(prefill_replicas= decides the split)")
        # routing randomness (the two p2c probes) is seeded: a fleet run
        # is replayable from (seed, submission order, fault plan)
        self._rng = np.random.RandomState(seed)
        self.inst = FleetInstruments(metrics)
        if not self.inst.registry.enabled:
            # the fleet counters BACK fleet_accounting()/telemetry()
            # (the chaos gate's partition invariant and the bench line):
            # a disabled registry would silently report zeros — fail
            # loud, same contract as ServingPredictor's registry check
            raise ValueError(
                "FleetRouter requires an enabled metrics registry; "
                "the one passed is disabled")
        self.replicas = [_Replica(rid, self._spawn(rid))
                         for rid in range(self.num_replicas)]
        self.page_size = self.replicas[0].sp.cache.page_size
        self.max_seq_len = self.replicas[0].sp.max_seq_len
        # prompts below one page have no chain-key identity — nothing
        # addressable to transfer; they serve colocated even when
        # disaggregated (min_transfer_tokens may raise the bar further)
        self.min_transfer_tokens = max(
            self.page_size, int(min_transfer_tokens or 0))
        #: live KV-page streams: (transfer, fleet request, affinity hit)
        self._transfers: list[tuple] = []
        #: chain key -> replica id (the prefix-affinity map): insertion-
        #: ordered with re-registration refreshing recency, bounded by
        #: ``max_affinity_entries`` (oldest evicted — a cold entry only
        #: costs a p2c placement, never correctness), purged per replica
        #: on its death
        self._affinity: dict[bytes, int] = {}
        self.max_affinity_entries = int(max_affinity_entries)
        #: submissions with no admittable replica right now — retried at
        #: the top of every tick, deadline-swept at the router
        self._unrouted: deque[FleetRequest] = deque()
        #: fleet_id -> non-terminal request; terminal requests leave the
        #: router's working set (the caller keeps its handle, counters
        #: keep the history) — a long-lived router must not grow per
        #: request served
        self._live: dict[int, FleetRequest] = {}
        self.ticks = 0

    # -- construction / lifecycle ------------------------------------------

    def role_for(self, rid: int) -> str:
        """The fleet role of slot ``rid`` — a property of the SLOT, not
        the predictor instance, so a supervisor restart respawns the
        same role into the same slot."""
        if not self.prefill_replicas:
            return "colocated"
        return "prefill" if rid < self.prefill_replicas else "decode"

    def _spawn(self, rid: int) -> ServingPredictor:
        return ServingPredictor(self._model, replica_id=rid,
                                role=self.role_for(rid),
                                **self._replica_kw)

    def _decode_reps(self) -> list[_Replica]:
        """The replicas user submissions decode on (every replica when
        colocated) — the ONLY replicas the affinity map and the p2c
        fallback ever name."""
        return [r for r in self.replicas
                if self.role_for(r.rid) != "prefill"]

    def _prefill_reps(self) -> list[_Replica]:
        return [r for r in self.replicas
                if self.role_for(r.rid) == "prefill"]

    def _rep(self, rid: int) -> _Replica:
        for rep in self.replicas:
            if rep.rid == rid:
                return rep
        raise KeyError(f"no replica {rid}")

    def drain(self, rid: int) -> None:
        """Operator drain: the replica finishes its in-flight work but
        admits nothing until :meth:`resume`. DEAD replicas stay dead."""
        rep = self._rep(rid)
        if rep.state != DEAD:
            rep.state = DRAINING

    def resume(self, rid: int) -> None:
        rep = self._rep(rid)
        if rep.state == DRAINING:
            rep.state = HEALTHY

    def kill_replica(self, rid: int, reason="operator_kill") -> None:
        """Declare a replica lost NOW (the operator/chaos surface — the
        ``replica_crash`` fault seam lands on the same path)."""
        rep = self._rep(rid)
        if rep.state != DEAD:
            self._crash(rep, RuntimeError(
                f"replica {rid} declared lost: {reason}"))

    # -- routing ------------------------------------------------------------

    def _admittable(self, rep: _Replica) -> bool:
        return (rep.state == HEALTHY and rep.stall_ticks == 0
                and rep.sp.admission_verdict() is None)

    def _load_score(self, rep: _Replica) -> float:
        """The p2c comparison key, off the healthz snapshot: occupied
        lanes + backlog dominate, pool occupancy breaks near-ties, the
        in-flight ring depth and the TTFT-p99 EMA push away from a
        replica that is already running hot."""
        hz = rep.sp.healthz()
        return (hz["waiting"] + hz["running"] + hz["pool_occupancy"]
                + 0.25 * hz["inflight_steps"]
                + 0.001 * hz["ttft_p99_ema_ms"])

    def _affinity_walk(self, keys, ok, exclude=()):
        """THE deepest-chain-key-wins affinity walk (longest shared
        prefix decides the replica), shared by decode placement and
        transfer-destination picks so the two can never diverge on
        affinity semantics; ``ok`` is the caller's per-replica
        eligibility predicate. None on no eligible registered key."""
        for k in reversed(keys):
            rid = self._affinity.get(k)
            if rid is not None and rid not in exclude:
                rep = self._rep(rid)
                if ok(rep):
                    return rep
        return None

    def _pick_replica(self, keys, exclude=()):
        """(replica, affinity_hit) for one placement given the context's
        chain keys; replica is None when nothing admittable exists.
        Affinity first — DEEPEST registered chain key wins (longest
        shared prefix) — then two seeded candidates scored by load."""
        rep = self._affinity_walk(keys, self._admittable, exclude)
        if rep is not None:
            return rep, True
        cands = [r for r in self._decode_reps()
                 if r.rid not in exclude and self._admittable(r)]
        return self._p2c(cands, self._load_score), False

    def _p2c(self, cands, score):
        """THE power-of-two-choices draw (two seeded candidates, lower
        score wins, rid tie-break), shared by decode and prefill picks
        so the sampling policy can never diverge. None on no
        candidates."""
        if not cands:
            return None
        if len(cands) > 2:
            i, j = self._rng.choice(len(cands), size=2, replace=False)
            cands = [cands[int(i)], cands[int(j)]]
        return min(cands, key=lambda r: (score(r), r.rid))

    def _healthy_verdicts(self):
        """The shed decision's evidence: the admission verdicts of every
        HEALTHY, un-stalled DECODE replica (None entries mean 'would
        admit') — prefill replicas never hold user submissions, so
        their SLOs never decide a fleet shed."""
        return [r.sp.admission_verdict() for r in self._decode_reps()
                if r.state == HEALTHY and r.stall_ticks == 0]

    def _pick_prefill(self):
        """The least-loaded healthy prefill replica (p2c like the
        decode fallback, with the sender-side transfer backlog as an
        extra penalty — a replica still streaming pages out is a worse
        place for new prefill work); None when no prefill replica can
        admit (the colocated-fallback cue)."""
        cands = [r for r in self._prefill_reps() if self._admittable(r)]
        return self._p2c(cands, lambda r: (
            self._load_score(r) + 0.1 * r.sp.transfer_backlog))

    def _try_route(self, freq: FleetRequest) -> bool:
        """Place one request (initial submit or failover re-admit).
        Returns True when it landed on a replica; False leaves it either
        queued at the router (no healthy capacity — transient) or
        terminally shed (healthy replicas exist but every one of them
        sheds — fleet backpressure, not an outage)."""
        # round 20: a fresh page-spanning submission on a disaggregated
        # fleet prefills on a dedicated prefill replica first; if no
        # prefill replica can admit RIGHT NOW, it degrades to colocated
        # prefill on the decode fleet immediately (counted as a
        # fallback) — disaggregation may never delay or fail a request
        if self._wants_disagg(freq):
            prep = self._pick_prefill()
            if prep is not None and self._admit_prefill_on(freq, prep):
                return True
            freq.phase = "decode"
            self.inst.prefill_fallbacks.inc()
        # the context (and so its chain keys) is fixed for the whole
        # placement attempt: hash once, not per race-retry iteration
        keys = prompt_chain_keys(freq.prompt_ids + freq.output_ids,
                                 self.page_size)
        exclude: set[int] = set()
        while True:
            rep, hit = self._pick_replica(keys, exclude)
            if rep is None:
                verdicts = self._healthy_verdicts()
                # SLO shedding is backpressure on NEW ARRIVALS: a
                # request the fleet already accepted (a failover victim,
                # anything with received tokens, or a round-20 fallback
                # the fleet already spent PREFILL work on) queues
                # through the transient instead — discarding accepted
                # in-flight work because a crash landed during a
                # backlog spike would turn one replica's failure into
                # request loss. A submit-time disagg degradation spent
                # nothing yet and stays shed-able (colocated parity —
                # the unrouted queue must not grow unboundedly under a
                # flood just because prefill capacity was busy).
                fresh = (freq.failover_count == 0 and not freq.output_ids
                         and not freq.prefill_spent)
                if (fresh and verdicts
                        and all(v is not None for v in verdicts)):
                    self.inst.shed.inc()
                    self._fail(freq, "shed_" + verdicts[0],
                               f"every healthy replica sheds "
                               f"({verdicts[0]})")
                else:
                    freq.state = WAITING
                    self._unrouted.append(freq)
                return False
            # round 21: before a miss recomputes, try pulling the
            # prefix's pages off the replica that owns them (the
            # affinity map knows) — the request parks in a transfer
            # phase and admits where the pages land
            if not hit and self._maybe_pull(freq, rep, keys):
                return True
            if self._admit_on(freq, rep, keys, hit):
                return True
            # the verdict raced between the gate and the admission (the
            # inner SLO shed it): try the other replicas before queueing
            exclude.add(rep.rid)

    def _wants_disagg(self, freq: FleetRequest) -> bool:
        """Is this placement the prefill stage of a disaggregated
        request? Only a FRESH first placement qualifies: failover
        victims, fallbacks (phase forced to "decode") and sub-page
        prompts (no chain-key identity to address frames by) all serve
        colocated."""
        return (self.prefill_replicas > 0 and freq.phase is None
                and not freq.output_ids
                and len(freq.prompt_ids) >= self.min_transfer_tokens)

    def _admit_prefill_on(self, freq: FleetRequest, rep: _Replica) -> bool:
        """Place the PREFILL stage: a 1-token inner request (prefill
        chunks + the first generated token) on a prefill-role replica.
        The handoff to the decode fleet happens when it finishes
        (:meth:`_handoff`); prefill placements never register affinity
        entries — the map names only replicas that will keep serving
        the prefix."""
        inner = rep.sp.add_request(
            freq.prompt_ids, 1, freq.eos_token_id,
            temperature=freq.temperature, top_k=freq.top_k,
            top_p=freq.top_p, seed=freq.seed,
            deadline_s=freq.deadline_s, submit_time=freq.submit_time)
        if inner.state == FAILED:
            return False
        freq._inner = inner
        freq.replica_id = rep.rid
        freq.state = RUNNING
        freq.phase = "prefill"
        freq.prefill_spent = True
        rep.by_inner[inner.req_id] = freq
        self.inst.prefill_routed.inc()
        return True

    def _admit_on(self, freq: FleetRequest, rep: _Replica, keys,
                  hit: bool) -> bool:
        remaining = freq.max_new_tokens - len(freq.output_ids)
        inner = rep.sp.add_request(
            freq.prompt_ids + freq.output_ids, remaining,
            freq.eos_token_id, temperature=freq.temperature,
            top_k=freq.top_k, top_p=freq.top_p, seed=freq.seed,
            deadline_s=freq.deadline_s, submit_time=freq.submit_time,
            # received tokens ride the new context as prompt: the
            # sample-key fold continues at the received count, so a
            # seeded stream crosses failover AND the disaggregated
            # handoff bit-identically (round 20)
            sample_offset=len(freq.output_ids))
        if inner.state == FAILED:
            return False
        freq._inner = inner
        freq.replica_id = rep.rid
        freq.state = RUNNING
        if freq.phase is not None:
            freq.phase = "decode"
        rep.by_inner[inner.req_id] = freq
        self.inst.routed.inc()
        if hit:
            self.inst.affinity_hits.inc()
        for k in keys:
            if k in self._affinity:
                del self._affinity[k]        # refresh recency
            elif len(self._affinity) >= self.max_affinity_entries:
                self._affinity.pop(next(iter(self._affinity)))
            self._affinity[k] = rep.rid
        return True

    @property
    def affinity_hit_rate(self) -> float:
        return self.inst.affinity_hit_rate

    # -- submission ---------------------------------------------------------

    def submit(self, prompt_ids, max_new_tokens=32, eos_token_id=None,
               temperature=0.0, top_k=0, top_p=1.0, seed=None,
               deadline_s=None) -> FleetRequest:
        """Admit one request into the fleet. Returns the fleet-side
        handle; a terminal-FAILED return means the fleet shed it (every
        healthy replica's SLO said no)."""
        freq = FleetRequest(prompt_ids, max_new_tokens, eos_token_id,
                            temperature=temperature, top_k=top_k,
                            top_p=top_p, seed=seed, deadline_s=deadline_s)
        # validate against the fleet-wide ceiling BEFORE any accounting:
        # a caller error must raise HERE (same contract as add_request),
        # never later out of tick() when a deferred route finally lands
        # on a replica — and never leave a phantom live request behind
        if len(freq.prompt_ids) > self.max_seq_len:
            raise ValueError(
                f"prompt of {len(freq.prompt_ids)} tokens exceeds "
                f"max_seq_len {self.max_seq_len}")
        self._live[freq.fleet_id] = freq
        self.inst.submitted.inc()
        if self._unrouted:
            # requests are already queued at the router: a new arrival
            # goes BEHIND them (FIFO — routing it now would let it claim
            # capacity freed since the last tick ahead of older work)
            freq.state = WAITING
            self._unrouted.append(freq)
        else:
            self._try_route(freq)
        return freq

    # -- terminal paths -----------------------------------------------------

    def _finish(self, freq: FleetRequest) -> None:
        freq.state = FINISHED
        freq.replica_id = None
        freq._inner = None
        freq._transfer = None
        self._live.pop(freq.fleet_id, None)
        self.inst.finished.inc()

    def _fail(self, freq: FleetRequest, code: str, message) -> None:
        freq.state = FAILED
        freq.error = {"code": code, "message": str(message)[:300]}
        freq.replica_id = None
        freq._inner = None
        freq._transfer = None
        self._live.pop(freq.fleet_id, None)
        self.inst.failed.inc()
        self.inst.fail_reasons.labels(reason=code).inc()

    # -- failure domain -----------------------------------------------------

    def _crash(self, rep: _Replica, exc) -> None:
        """Declare ``rep`` lost: its process state is unreadable from
        here on (never stepped, never flushed — nothing it had in flight
        can ever be double-reported), its affinity entries are purged
        (the pages died with it), and every non-terminal request it held
        migrates using only the fleet-side received streams."""
        self.inst.crashes.inc()
        rep.state = DEAD
        rep.sp = None
        rep.stall_ticks = 0
        rep.stalled_for = 0
        rep.restart_in = self.restart_ticks
        self._affinity = {k: r for k, r in self._affinity.items()
                          if r != rep.rid}
        victims = sorted(rep.by_inner.values(), key=lambda f: f.fleet_id)
        rep.by_inner = {}
        for freq in victims:
            if freq.state in (FINISHED, FAILED):
                continue
            if freq.phase == "prefill":
                # round 20: losing the prefill replica mid-prompt only
                # loses PREFILL work — the decode path never started.
                # Colocated fallback owns it, and it never burns the
                # failover budget (disaggregation existing must never
                # cost a request its bounded migrations)
                self._fallback(freq, "prefill replica lost mid-stream")
                continue
            self._failover(freq, exc)
        # transfers whose endpoints died abort on their next drive (the
        # replica-bound cache accessors read None for a DEAD slot) —
        # nothing to do here, and nothing of the dead pool is ever read

    def _failover(self, freq: FleetRequest, exc) -> None:
        """Migrate one request off a lost replica: resume from the
        received ``len(output_ids)``, original deadline carried, bounded
        by ``max_failovers`` before a terminal ``replica_lost``."""
        freq._inner = None
        freq.replica_id = None
        if freq.done:
            # the received stream already satisfies the contract: the
            # lost replica only owed us its retirement bookkeeping
            self._finish(freq)
            return
        freq.failover_count += 1
        if freq.failover_count > self.max_failovers:
            self._fail(freq, "replica_lost",
                       f"lost its replica {freq.failover_count} times "
                       f"({len(freq.output_ids)} tokens received); "
                       f"last: {exc!r}")
            return
        # counted only when a migration actually happens (a finished-in-
        # place or bound-exhausted victim is not a migration)
        self.inst.failovers.inc()
        self._try_route(freq)

    def _restart(self, rep: _Replica) -> None:
        """A fresh predictor into a DEAD slot (the supervisor restarting
        the pod): empty pools, same geometry, same replica id. The whole
        wrapper is replaced — `_Replica.__init__` is the one place that
        knows a fresh replica's state."""
        self.replicas[self.replicas.index(rep)] = _Replica(
            rep.rid, self._spawn(rep.rid))
        self.inst.restarts.inc()

    # -- round 20: the prefill -> decode handoff ----------------------------

    def _cache_fn(self, rep: _Replica):
        """A crash-consistent accessor for ``rep``'s cache: reads None
        once the slot is DEAD or the wrapper was replaced by a
        supervisor restart — a transfer must never read a dead pool,
        and a restart's FRESH cache must never be mistaken for it."""
        def fn():
            if rep.state == DEAD or rep.sp is None \
                    or rep not in self.replicas:
                return None
            return rep.sp.cache
        return fn

    def _pick_transfer_dst(self, freq: FleetRequest):
        """The decode replica the pages stream TO: the affinity map
        first (the replica that will keep serving this prefix), else
        the least-loaded LIVE decode replica. Deliberately NOT gated on
        the admission verdict — a transient queue-full must not abandon
        a transfer (the pages land, the decode admission rides the
        normal unrouted backpressure afterwards); only DEAD/DRAINING
        replicas are off the table. None only when every decode replica
        is dead/draining."""
        def live(r):
            return r.state not in (DEAD, DRAINING) and r.sp is not None

        keys = prompt_chain_keys(freq.prompt_ids + freq.output_ids,
                                 self.page_size)
        rep = self._affinity_walk(keys, live)
        if rep is not None:
            return rep, True
        cands = [r for r in self._decode_reps() if live(r)]
        if not cands:
            return None, False
        return min(cands, key=lambda r: (self._load_score(r), r.rid)), False

    def _handoff(self, freq: FleetRequest, rep: _Replica) -> None:
        """The prefill stage finished: export the prompt's registered
        pages off the prefill replica and stream them to the decode
        replica the affinity map names. Every unhappy path here is a
        FALLBACK, never a failure."""
        freq._inner = None
        freq.replica_id = None
        if freq.done:
            # budget 1 (or eos on the first token): the received stream
            # already satisfies the contract — nothing to hand off
            self._finish(freq)
            return
        records = (rep.sp.cache.prefix_page_records(freq.prompt_ids)
                   if rep.sp is not None else [])
        if not records:
            self._fallback(freq, "no transferable pages registered on "
                                 "the prefill replica")
            return
        dst, hit = self._pick_transfer_dst(freq)
        if dst is None:
            self._fallback(freq, "no live decode replica at handoff")
            return
        # started counts BEFORE construction so a transfer that fails
        # to open (unreadable source) keeps started >= completed+failed
        self.inst.transfers_started.inc()
        t = KVPageTransfer(
            records, self._cache_fn(rep), self._cache_fn(dst),
            config=self.transfer_cfg, instruments=self.inst,
            src_rid=rep.rid, dst_rid=dst.rid)
        if t.state != T_SENDING:
            self._fallback(freq, t.failure or "transfer failed to open")
            return
        freq.phase = "transfer"
        freq.decode_rid = dst.rid
        freq._transfer = t
        self._transfers.append((t, freq, hit, "handoff"))

    def _maybe_pull(self, freq: FleetRequest, dst: _Replica,
                    keys) -> bool:
        """Round 21: the routed replica ``dst`` misses this context's
        prefix, but the affinity map names another replica that owns it
        — open a KV-page pull over the transfer wire instead of
        recomputing. The source's export walk is restore-aware, so a
        prefix that slid into the OWNER's host tier still serves the
        pull. One attempt per request; every unhappy path degrades to
        the ordinary recompute placement (counted, never failed).
        Returns True when the request parked in the transfer phase."""
        if not self.prefix_pulls or freq.pull_attempted or not keys:
            return False
        ctx = freq.prompt_ids + freq.output_ids
        if len(ctx) < self.min_transfer_tokens:
            return False

        def owns(r):
            # DRAINING replicas are ideal pull sources (their warm
            # prefixes are about to be lost); only a DEAD replica's
            # pool is unreadable
            return (r.state != DEAD and r.sp is not None
                    and r.rid != dst.rid)

        src = self._affinity_walk(keys, owns)
        if src is None:
            return False
        records = src.sp.cache.prefix_page_records(ctx)
        if not records \
                or sum(r[2] for r in records) < self.min_transfer_tokens:
            return False
        # make room on the destination BEFORE opening the stream: the
        # import landing zone never evicts (the locked pressure
        # contract), so a saturated pool must shed its coldest zero-ref
        # pages down the eviction ladder first — if the room is not
        # there, recompute instead of opening a doomed transfer
        if not dst.sp.cache.reserve_import_room(len(records)):
            return False
        freq.pull_attempted = True
        # started counts BEFORE construction (same contract as
        # _handoff: started >= completed + failed always holds)
        self.inst.transfers_started.inc()
        self.inst.pulls_started.inc()
        t = KVPageTransfer(
            records, self._cache_fn(src), self._cache_fn(dst),
            config=self.transfer_cfg, instruments=self.inst,
            src_rid=src.rid, dst_rid=dst.rid)
        if t.state != T_SENDING:
            self.inst.pull_fallbacks.inc()
            return False                 # admit normally: recompute
        freq.phase = "transfer"
        freq.state = RUNNING
        freq.decode_rid = dst.rid
        freq._transfer = t
        self._transfers.append((t, freq, False, "pull"))
        return True

    def _complete_handoff(self, freq: FleetRequest, hit: bool) -> None:
        """Every page landed: admit the decode stage where the pages
        now live. If the pinned destination became unadmittable while
        the pages streamed, normal decode routing owns the request —
        the imported pages stay registered, so a later same-prefix
        admission still hits them."""
        freq._transfer = None
        freq.phase = "decode"
        rep = self._rep(freq.decode_rid)
        keys = prompt_chain_keys(freq.prompt_ids + freq.output_ids,
                                 self.page_size)
        if (rep.state != DEAD and rep.sp is not None
                and self._admittable(rep)
                and self._admit_on(freq, rep, keys, hit)):
            return
        self._try_route(freq)

    def _fallback(self, freq: FleetRequest, why: str) -> None:
        """Graceful degradation — the round-20 headline: the request
        serves COLOCATED on the decode fleet (today's path), counted
        but never failed and never charged a failover. ``why`` is
        telemetry-only: degradation is invisible to the caller beyond
        latency."""
        if freq.state in (FINISHED, FAILED):
            return          # racing a terminal request is not a degradation
        freq._transfer = None
        freq._inner = None
        freq.replica_id = None
        freq.phase = "decode"
        self.inst.prefill_fallbacks.inc()
        if freq.done:
            self._finish(freq)
            return
        self._try_route(freq)

    def _pull_fallback(self, freq: FleetRequest, why: str) -> None:
        """A cross-replica pull died on the wire: re-route the request
        for ordinary colocated recompute. Mirrors :meth:`_fallback` but
        charges the round-21 pull counter, NOT ``prefill_fallbacks`` —
        the disagg bench's fault-free-fallbacks-stay-zero gate must not
        see pull weather. ``why`` is telemetry-only."""
        if freq.state in (FINISHED, FAILED):
            return
        freq._transfer = None
        freq._inner = None
        freq.replica_id = None
        freq.phase = "decode"
        self.inst.pull_fallbacks.inc()
        if freq.done:
            self._finish(freq)
            return
        self._try_route(freq)

    def _drive_transfers(self) -> None:
        """One tick of wire work for every live transfer (prefill
        handoffs AND round-21 prefix pulls), plus the transfer-phase
        deadline sweep (a request streaming its pages is on no replica
        — nobody else's TTL sweep covers it) and the sender-side
        backlog stamps the healthz surface reads."""
        if self._transfers:
            now = monotonic()
            live = []
            for t, freq, hit, kind in self._transfers:
                if freq.state in (FINISHED, FAILED):
                    t.abort("fleet request terminal")
                    continue
                if freq.past_deadline(now):
                    t.abort("deadline exceeded mid-transfer")
                    self.inst.deadline_misses.inc()
                    self._fail(freq, "deadline_exceeded",
                               f"transfer-phase request past its "
                               f"{freq.deadline_s}s deadline")
                    continue
                state = t.tick()
                if state == T_SENDING:
                    live.append((t, freq, hit, kind))
                elif state == T_DONE:
                    if kind == "pull":
                        self.inst.pulls_completed.inc()
                    self._complete_handoff(freq, hit)
                elif kind == "pull":
                    self._pull_fallback(freq,
                                        t.failure or "pull failed")
                else:
                    self._fallback(freq, t.failure or "transfer failed")
            self._transfers = live
        backlog: dict[int, int] = {}
        for t, *_ in self._transfers:
            backlog[t.src_rid] = backlog.get(t.src_rid, 0) + t.backlog
        for rep in self._prefill_reps():
            if rep.sp is not None:
                rep.sp.transfer_backlog = backlog.get(rep.rid, 0)
        self.inst.transfer_backlog.set(sum(backlog.values()))

    # -- the tick -----------------------------------------------------------

    def _step_replica(self, rep: _Replica, produced: dict) -> None:
        """One replica's scheduler round inside the fleet tick, with the
        round-18 fault seams in front of it. A stalled replica makes no
        progress (its snapshot goes stale; past ``dead_stall_ticks`` the
        router escalates to a crash); a crashed one fails over."""
        if rep.stall_ticks > 0:
            rep.stall_ticks -= 1
            rep.stalled_for += 1
            if rep.stalled_for >= self.dead_stall_ticks:
                self._crash(rep, RuntimeError(
                    f"replica {rep.rid} stalled for {rep.stalled_for} "
                    "consecutive ticks — declared lost"))
            return
        rep.stalled_for = 0
        stall = fault_point("replica_stall")
        if stall:
            self.inst.stalls.inc()
            rep.stall_ticks = int(stall) - 1   # this tick is the first
            rep.stalled_for = 1
            return
        try:
            fault_point("replica_crash")
            out = rep.sp.step()
        except Exception as exc:
            # a replica crash is a ROUTING EVENT: the fleet recovery owns
            # every exception here (the replica's own round-17 machinery
            # already retried anything retryable before raising)
            self._crash(rep, exc)
            return
        self._merge(rep, out, produced)
        self._sweep(rep)

    def tick(self) -> dict[int, list[int]]:
        """One fleet scheduler round. Returns ``{fleet_id: [tokens]}``
        received this round, in emission order."""
        self.ticks += 1
        self.inst.ticks.inc()
        produced: dict[int, list[int]] = {}
        with span("fleet_tick"):
            self._sweep_unrouted()
            for rep in self.replicas:
                if rep.state == DEAD:
                    rep.restart_in -= 1
                    if rep.restart_in <= 0:
                        self._restart(rep)
                    continue
                self._step_replica(rep, produced)
            # round 20: one tick of KV-page wire work (new handoffs
            # created by the sweeps above send their first window NOW)
            self._drive_transfers()
            self._refresh_health()
        self.inst.live_replicas.set(
            sum(1 for r in self.replicas if r.state != DEAD))
        self.inst.unrouted.set(len(self._unrouted))
        return produced

    def flush(self) -> dict[int, list[int]]:
        """Drain every live replica's in-flight ring and sweep terminal
        states. A stalled replica cannot be drained — its deferred
        emissions land once the stall expires (keep ticking)."""
        produced: dict[int, list[int]] = {}
        for rep in self.replicas:
            if rep.state == DEAD or rep.stall_ticks > 0:
                continue
            self._merge(rep, rep.sp.flush(), produced)
            self._sweep(rep)
        return produced

    def has_work(self) -> bool:
        return bool(self._live)

    # -- merge / sweep ------------------------------------------------------

    def _merge(self, rep: _Replica, out: dict, produced: dict) -> None:
        """Land one replica's step/flush results into the fleet-side
        streams — the ONLY writer of ``FleetRequest.output_ids``, so the
        received ledger is exactly what failover resumes from."""
        for inner_id, toks in out.items():
            freq = rep.by_inner.get(inner_id)
            if freq is None or freq.state in (FINISHED, FAILED):
                continue
            landed = 0
            for tok in toks:
                if freq.done:
                    break   # guard: never exceed the fleet-side contract
                freq.output_ids.append(int(tok))
                produced.setdefault(freq.fleet_id, []).append(int(tok))
                landed += 1
            if landed:
                if freq.first_token_time is None:
                    freq.first_token_time = monotonic()
                self.inst.tokens.labels(replica=str(rep.rid)).inc(landed)

    def _sweep(self, rep: _Replica) -> None:
        """Propagate terminal inner states to the fleet requests. An
        inner request FINISHED by count with values still in flight
        (async deferral) stays mapped until its pending tokens land —
        finishing the fleet request early would drop its tail."""
        for inner_id in list(rep.by_inner):
            freq = rep.by_inner[inner_id]
            inner = freq._inner
            if inner is None or inner.req_id != inner_id:
                del rep.by_inner[inner_id]   # stale mapping (migrated)
                continue
            if inner.state == FINISHED and inner._pending_n == 0:
                del rep.by_inner[inner_id]
                freq.truncated = freq.truncated or inner.truncated
                if freq.phase == "prefill":
                    # round 20: the prefill stage retired — the fleet
                    # request is NOT done, its pages hand off to the
                    # decode fleet now
                    self._handoff(freq, rep)
                else:
                    self._finish(freq)
            elif inner.state == FAILED:
                del rep.by_inner[inner_id]
                if (freq.phase == "prefill"
                        and inner.error["code"] != "deadline_exceeded"):
                    # round 20: an intra-replica failure of the PREFILL
                    # stage (pool exhaustion, retry exhaustion, a raced
                    # shed) is not the request's failure — the
                    # colocated path may still serve it. Deadlines stay
                    # global: an expired request is expired everywhere.
                    self._fallback(
                        freq, f"prefill stage failed "
                              f"({inner.error['code']})")
                    continue
                # an intra-replica terminal verdict (deadline, pool
                # exhaustion, retry exhaustion, shed) is the REQUEST's
                # failure, not the replica's — it propagates, it does
                # not fail over (a deadline miss is global; the rest
                # would recur on any identically-sized replica)
                self._fail(freq, inner.error["code"],
                           inner.error["message"])

    # -- router-side queue maintenance --------------------------------------

    def _sweep_unrouted(self) -> None:
        """Retry placement for requests queued at the router, failing
        the ones past their deadline first (the router-level TTL — an
        unrouted request never reaches a predictor's own sweep)."""
        if not self._unrouted:
            return
        now = monotonic()
        pending = list(self._unrouted)
        self._unrouted.clear()
        for freq in pending:
            if freq.state in (FINISHED, FAILED):
                continue
            if freq.past_deadline(now):
                self.inst.deadline_misses.inc()
                self._fail(freq, "deadline_exceeded",
                           f"unrouted past its {freq.deadline_s}s "
                           "deadline (no admittable replica)")
                continue
            # re-queues itself via _try_route when still unplaceable
            self._try_route(freq)

    def _refresh_health(self) -> None:
        """The health gate's per-tick refresh: HEALTHY <-> UNHEALTHY off
        the stall state and the healthz staleness stamp. DRAINING and
        DEAD are sticky (operator / supervisor transitions)."""
        for rep in self.replicas:
            if rep.state in (DEAD, DRAINING):
                continue
            stale = (rep.stall_ticks > 0
                     or rep.sp.healthz()["snapshot_age_s"]
                     > self.stale_after_s)
            rep.state = UNHEALTHY if stale else HEALTHY

    # -- observability ------------------------------------------------------

    def telemetry(self) -> dict[str, float]:
        """Flat snapshot of the fleet registry (the bench ``telemetry``
        object). Per-replica serving registries stay per-replica —
        :meth:`replica_healthz` is the per-replica surface."""
        return self.inst.snapshot_flat()

    def replica_healthz(self) -> list[dict]:
        """Per-replica health: the fleet state machine's view joined
        with each live replica's own ``healthz()`` snapshot."""
        out = []
        for rep in self.replicas:
            row = {"replica_id": rep.rid, "fleet_state": rep.state,
                   "role": self.role_for(rep.rid),
                   "stall_ticks": rep.stall_ticks,
                   "assigned": len(rep.by_inner)}
            if rep.sp is not None:
                row["healthz"] = rep.sp.healthz()
            out.append(row)
        return out

    def fleet_accounting(self) -> dict[str, int]:
        """The partition the chaos gate asserts after every tick:
        ``submitted == finished + failed + live`` (and the counters
        agree with the request objects)."""
        snap = self.telemetry()
        return {
            "submitted": int(snap["fleet_requests_submitted"]),
            "finished": int(snap["fleet_requests_finished"]),
            "failed": int(snap["fleet_requests_failed"]),
            "live": len(self._live),
        }
