"""N-gram / prompt-lookup draft proposer for speculative decoding.

Round 12: the host-side half of the draft–verify–accept loop. Each request
owns one :class:`DraftProposer`; the serving scheduler feeds it the
request's context ids (prompt + generated so far — exactly what the
scheduler already tracks for preemption replay) and asks for up to ``k``
draft tokens per decode step. The unified step then verifies the drafts in
one ragged pass (1 + k query rows for the lane, per-row causal limits) and
the fused accept epilogue keeps the longest matching prefix plus one bonus
token — see ``models/gpt.py build_unified_step(spec_k=...)``.

Proposal scheme (prompt-lookup decoding, arxiv 2402.xxxx shape): find the
longest trailing n-gram of the context (``max_ngram`` down to 1) that also
occurred EARLIER in the context, preferring the MOST RECENT earlier match,
and copy the tokens that followed it. Lookups chain: copied tokens extend a
virtual context and the lookup repeats until ``k`` drafts are gathered or
no match remains — a period-1 repetition (the common greedy-decode
attractor) therefore fills all ``k`` slots from a single-token match.

The index is incremental and DETERMINISTIC in the context: n-grams ending
strictly before the last context token map to their latest start position,
extended as the context grows (``_synced`` high-water mark). A preemption
replay re-feeds the identical context, so the table — and every proposal —
replays identically (the same property the seeded sample streams rely on).

Adaptive k: acceptance feedback (``update(proposed, accepted)``) drives an
EMA; the effective ``k`` scales monotonically with the EMA down to 0
(plain decode — speculation priced off when the workload doesn't repeat).
While backed off to 0, a cooldown of plain-decode steps re-arms a probe so
a workload that turns repetitive later gets re-tried.
"""
from __future__ import annotations

__all__ = ["DraftProposer"]


class DraftProposer:
    """Per-request n-gram draft source with adaptive speculation length.

    ``max_k``: the ceiling on drafts per step (the unified step's build
    geometry — the scheduler may clamp lower per step for budget/capacity).
    ``max_ngram``: longest trailing n-gram tried first. ``alpha``: EMA
    weight of the newest acceptance sample. ``min_ema``: EMA below which
    speculation disables (k = 0). ``retry_after``: plain-decode steps spent
    disabled before the EMA re-arms to ``probe_ema``.
    """

    def __init__(self, max_k: int, *, max_ngram: int = 3, alpha: float = 0.5,
                 min_ema: float = 0.2, retry_after: int = 16,
                 probe_ema: float = 0.5):
        if max_k < 1:
            raise ValueError(f"max_k must be >= 1, got {max_k}")
        if max_ngram < 1:
            raise ValueError(f"max_ngram must be >= 1, got {max_ngram}")
        self.max_k = int(max_k)
        self.max_ngram = int(max_ngram)
        self.alpha = float(alpha)
        self.min_ema = float(min_ema)
        self.retry_after = int(retry_after)
        self.probe_ema = float(probe_ema)
        self._ema = 1.0          # optimistic start: speculate until priced
        self._cool = 0
        # n-gram (as tuple) -> latest start position, over context n-grams
        # ending STRICTLY before the last token (the tail n-gram itself must
        # never shadow its earlier occurrences)
        self._index: dict[tuple, int] = {}
        self._synced = 0         # context positions whose n-grams are indexed

    # -- adaptive k --------------------------------------------------------

    @property
    def k(self) -> int:
        """Current speculation length, monotone in the acceptance EMA:
        full ``max_k`` at EMA 1.0, 0 (plain decode) below ``min_ema``."""
        if self._ema < self.min_ema:
            return 0
        return min(self.max_k, int(self._ema * (self.max_k + 1)))

    def update(self, proposed: int, accepted: int) -> None:
        """Feed one decode step's outcome. ``proposed == 0`` (nothing
        drafted — disabled, no match, or no budget) leaves the EMA alone
        but ticks the re-arm cooldown while disabled."""
        if proposed <= 0:
            if self.k == 0:
                self._cool += 1
                if self._cool >= self.retry_after:
                    self._ema = self.probe_ema
                    self._cool = 0
            return
        accepted = max(0, min(int(accepted), int(proposed)))
        self._ema = ((1.0 - self.alpha) * self._ema
                     + self.alpha * (accepted / proposed))
        self._cool = 0

    # -- the n-gram table --------------------------------------------------

    def _sync(self, context) -> None:
        """Index the n-grams of ``context`` ending at positions <= len-2
        (monotone high-water mark: a preemption replay with the identical
        context is a no-op)."""
        n_ctx = len(context)
        # positions are n-gram END indices; the final token's n-grams stay
        # out so the tail lookup finds its latest EARLIER occurrence
        for end in range(self._synced, n_ctx - 1):
            for n in range(1, self.max_ngram + 1):
                start = end - n + 1
                if start < 0:
                    break
                self._index[tuple(context[start:end + 1])] = start
        self._synced = max(self._synced, n_ctx - 1)

    def propose(self, context, budget: int) -> list[int]:
        """Up to ``min(self.k, budget)`` draft tokens continuing
        ``context``. Empty when the context is too short (< 2 tokens), the
        adaptive k backed off, or no trailing n-gram recurs."""
        k = min(self.k, int(budget))
        if k <= 0 or len(context) < 2:
            return []
        self._sync(context)
        drafts: list[int] = []
        v = list(context)
        # chained-lookup overlay: n-grams ending inside the drafted
        # extension (later than anything in the main index, so it wins)
        overlay: dict[tuple, int] = {}

        def extend_overlay(upto):
            # index n-grams ending at position upto-2 (the new interior)
            end = upto - 2
            for n in range(1, self.max_ngram + 1):
                start = end - n + 1
                if start < 0:
                    break
                overlay[tuple(v[start:end + 1])] = start

        while len(drafts) < k:
            match = None
            for n in range(min(self.max_ngram, len(v) - 1), 0, -1):
                key = tuple(v[-n:])
                p = overlay.get(key, self._index.get(key))
                if p is not None and p + n < len(v):
                    match = (p, n)
                    break
            if match is None:
                break
            p, n = match
            take = v[p + n:p + n + (k - len(drafts))]
            if not take:
                break
            for t in take:
                drafts.append(t)
                v.append(t)
                extend_overlay(len(v))
        return drafts
