"""N-gram / prompt-lookup draft proposer for speculative decoding.

Round 12: the host-side half of the draft–verify–accept loop. Each request
owns one :class:`DraftProposer`; the serving scheduler feeds it the
request's context ids (prompt + generated so far — exactly what the
scheduler already tracks for preemption replay) and asks for up to ``k``
draft tokens per decode step. The unified step then verifies the drafts in
one ragged pass (1 + k query rows for the lane, per-row causal limits) and
the fused accept epilogue keeps the longest matching prefix plus one bonus
token — see ``models/gpt.py build_unified_step(spec_k=...)``.

Proposal scheme (prompt-lookup decoding, arxiv 2402.xxxx shape): find the
longest trailing n-gram of the context (``max_ngram`` down to 1) that also
occurred EARLIER in the context, preferring the MOST RECENT earlier match,
and copy the tokens that followed it. Lookups chain: copied tokens extend a
virtual context and the lookup repeats until ``k`` drafts are gathered or
no match remains — a period-1 repetition (the common greedy-decode
attractor) therefore fills all ``k`` slots from a single-token match.

The index is incremental and DETERMINISTIC in the context: n-grams ending
strictly before the last context token map to their latest start position,
extended as the context grows (``_synced`` high-water mark). A preemption
replay re-feeds the identical context, so the table — and every proposal —
replays identically (the same property the seeded sample streams rely on).

Adaptive k: acceptance feedback (``update(proposed, accepted)``) drives an
EMA; the effective ``k`` scales monotonically with the EMA down to 0
(plain decode — speculation priced off when the workload doesn't repeat).
While backed off to 0, a cooldown of plain-decode steps re-arms a probe so
a workload that turns repetitive later gets re-tried.

Round 19 adds the MODEL-BASED draft source: :class:`ModelDraftProposer`
(the same adaptive-k EMA surface, per request) backed by a shared
:class:`ModelDraftEngine` — a truncated-layer SELF-DRAFT of the serving
model (the first ``spec_draft_layers`` layers of the SAME
``serving_params`` stack, shared embeddings/LM head — see
``models/gpt.py draft_serving_params``) running as its own small
fixed-shape unified-step jit over a DEDICATED paged-KV pool. Unlike the
n-gram table, the model drafter accepts on non-repetitive text: its
proposal IS (approximately) what the target would emit, so acceptance
tracks truncation quality instead of workload repetitiveness. The engine
batches every proposing lane into ONE k-step decode chain per scheduler
round, chained DEVICE-SIDE through the unified step's feedback carry
(intermediate draft tokens never materialize on the host — one sync per
round lands all of them), and keeps its pool crash-consistent with
preemption replay by self-healing: each lane records the token ids it
fed (``_fed``), and a proposal first rolls the draft KV back to the
longest prefix of the lane's CURRENT context it already holds
(``KVCacheManager.rollback``) — a preemption replay, a rejected draft
tail, a clamped proposal or a dropped in-flight step all reconcile
through the same one comparison.
"""
from __future__ import annotations

__all__ = ["DraftProposer", "ModelDraftProposer", "ModelDraftEngine"]


class DraftProposer:
    """Per-request n-gram draft source with adaptive speculation length.

    ``max_k``: the ceiling on drafts per step (the unified step's build
    geometry — the scheduler may clamp lower per step for budget/capacity).
    ``max_ngram``: longest trailing n-gram tried first. ``alpha``: EMA
    weight of the newest acceptance sample. ``min_ema``: EMA below which
    speculation disables (k = 0). ``retry_after``: plain-decode steps spent
    disabled before the EMA re-arms to ``probe_ema``.
    """

    def __init__(self, max_k: int, *, max_ngram: int = 3, alpha: float = 0.5,
                 min_ema: float = 0.2, retry_after: int = 16,
                 probe_ema: float = 0.5):
        if max_k < 1:
            raise ValueError(f"max_k must be >= 1, got {max_k}")
        if max_ngram < 1:
            raise ValueError(f"max_ngram must be >= 1, got {max_ngram}")
        self.max_k = int(max_k)
        self.max_ngram = int(max_ngram)
        self.alpha = float(alpha)
        self.min_ema = float(min_ema)
        self.retry_after = int(retry_after)
        self.probe_ema = float(probe_ema)
        self._ema = 1.0          # optimistic start: speculate until priced
        self._cool = 0
        # n-gram (as tuple) -> latest start position, over context n-grams
        # ending STRICTLY before the last token (the tail n-gram itself must
        # never shadow its earlier occurrences)
        self._index: dict[tuple, int] = {}
        self._synced = 0         # context positions whose n-grams are indexed

    # -- adaptive k --------------------------------------------------------

    @property
    def k(self) -> int:
        """Current speculation length, monotone in the acceptance EMA:
        full ``max_k`` at EMA 1.0, 0 (plain decode) below ``min_ema``."""
        if self._ema < self.min_ema:
            return 0
        return min(self.max_k, int(self._ema * (self.max_k + 1)))

    def update(self, proposed: int, accepted: int) -> None:
        """Feed one decode step's outcome. ``proposed == 0`` (nothing
        drafted — disabled, no match, or no budget) leaves the EMA alone
        but ticks the re-arm cooldown while disabled."""
        if proposed <= 0:
            if self.k == 0:
                self._cool += 1
                if self._cool >= self.retry_after:
                    self._ema = self.probe_ema
                    self._cool = 0
            return
        accepted = max(0, min(int(accepted), int(proposed)))
        self._ema = ((1.0 - self.alpha) * self._ema
                     + self.alpha * (accepted / proposed))
        self._cool = 0

    # -- the n-gram table --------------------------------------------------

    def _sync(self, context) -> None:
        """Index the n-grams of ``context`` ending at positions <= len-2
        (monotone high-water mark: a preemption replay with the identical
        context is a no-op)."""
        n_ctx = len(context)
        # positions are n-gram END indices; the final token's n-grams stay
        # out so the tail lookup finds its latest EARLIER occurrence
        for end in range(self._synced, n_ctx - 1):
            for n in range(1, self.max_ngram + 1):
                start = end - n + 1
                if start < 0:
                    break
                self._index[tuple(context[start:end + 1])] = start
        self._synced = max(self._synced, n_ctx - 1)

    def propose(self, context, budget: int) -> list[int]:
        """Up to ``min(self.k, budget)`` draft tokens continuing
        ``context``. Empty when the context is too short (< 2 tokens), the
        adaptive k backed off, or no trailing n-gram recurs."""
        k = min(self.k, int(budget))
        if k <= 0 or len(context) < 2:
            return []
        self._sync(context)
        drafts: list[int] = []
        v = list(context)
        # chained-lookup overlay: n-grams ending inside the drafted
        # extension (later than anything in the main index, so it wins)
        overlay: dict[tuple, int] = {}

        def extend_overlay(upto):
            # index n-grams ending at position upto-2 (the new interior)
            end = upto - 2
            for n in range(1, self.max_ngram + 1):
                start = end - n + 1
                if start < 0:
                    break
                overlay[tuple(v[start:end + 1])] = start

        while len(drafts) < k:
            match = None
            for n in range(min(self.max_ngram, len(v) - 1), 0, -1):
                key = tuple(v[-n:])
                p = overlay.get(key, self._index.get(key))
                if p is not None and p + n < len(v):
                    match = (p, n)
                    break
            if match is None:
                break
            p, n = match
            take = v[p + n:p + n + (k - len(drafts))]
            if not take:
                break
            for t in take:
                drafts.append(t)
                v.append(t)
                extend_overlay(len(v))
        return drafts


class ModelDraftProposer(DraftProposer):
    """Per-request adaptive-k state for the MODEL-BASED draft source.

    The same ``k``/``update`` EMA-backoff surface as the n-gram proposer
    (so the scheduler's adaptive clamps, cooldown re-probe and
    preemption-replay persistence apply unchanged); proposals come from
    the shared :class:`ModelDraftEngine` instead of an n-gram table. The
    serving scheduler batches every proposing lane into one engine call
    per round — :meth:`propose` is the single-lane convenience spelling
    of the same thing.
    """

    def __init__(self, max_k: int, engine: "ModelDraftEngine", req_id,
                 **kw):
        super().__init__(max_k, **kw)
        self._engine = engine
        self._req_id = req_id

    def propose(self, context, budget: int) -> list[int]:
        k = min(self.k, int(budget))
        if k <= 0 or not len(context):
            return []
        return self._engine.propose(
            {0: (self._req_id, list(context), k)}).get(0, [])


class ModelDraftEngine:
    """The shared truncated-layer self-draft pass behind every
    :class:`ModelDraftProposer` of one predictor.

    Owns the DEDICATED draft KV pool (a :class:`KVCacheManager` with
    ``draft_layers`` layers — same page machinery, same int8-KV support,
    same head sharding under a serving mesh) and two fixed-shape builds
    of the truncated stack: a CATCH-UP geometry (``models/gpt.py
    build_draft_step`` at ``chunk`` tokens per lane per call — replaying
    context the pool does not hold yet) and, since round 22, the FUSED
    CHAIN (``models/gpt.py build_draft_chain``): the whole k-step
    autoregressive proposal pass as one jit — a device-side ``lax.scan``
    whose step 1 feeds each lane's live last context token and steps
    2..k feed the previous step's greedy argmax, so the intermediate
    draft tokens never touch the host and a speculative round costs ONE
    draft dispatch (+ the target's verify step). With ``mega`` on, the
    chain's layer blocks run the persistent mega kernels of
    ``ops/pallas/mega_decode`` at chunk-1 geometry.

    Crash consistency / preemption replay: per request the engine records
    the exact token ids it fed (``fed``). Every proposal starts by
    rolling the draft KV back to the longest common prefix of ``fed`` and
    the lane's CURRENT context (capped at ``len(context) - 1`` so the
    chain's first feed is always the live last token) — rejected drafts,
    clamped proposals, preemption replays and dropped in-flight steps all
    self-heal through that one comparison, with no commit protocol
    against the target's accept results. Draft capacity is opportunistic
    like the drafts themselves: a lane the pool cannot hold is evicted
    (oldest-proposer first) or simply proposes nothing this round.
    """

    def __init__(self, config, params, draft_layers: int, *, page_size,
                 chunk, max_batch, max_seq_len, num_pages=None,
                 use_kernel=None, kv_quant=False, mesh=None, dtype=None,
                 on_launch=None, max_k=None, mega=None):
        from ..models.gpt import (build_draft_step, draft_config,
                                  draft_serving_params)
        from ..observability import MetricsRegistry
        from .kv_cache import KVCacheManager, pages_needed

        import jax.numpy as jnp
        import numpy as np

        self.draft_layers = int(draft_layers)
        dcfg = draft_config(config, self.draft_layers)  # validates depth
        # slice off the UNSHARDED extraction; under a serving mesh the
        # truncated stacks re-shard with the draft config (same Megatron
        # layout, head-major qkv permute included)
        self.params = draft_serving_params(params, self.draft_layers)
        if mesh is not None:
            from ..models.gpt import shard_serving_params

            self.params = shard_serving_params(self.params, mesh, dcfg)
        self.chunk = int(chunk)
        self.max_batch = int(max_batch)
        self.max_seq_len = int(max_seq_len)
        self.kv_quant = bool(kv_quant)
        self._on_launch = on_launch
        kv_dtype = dtype if dtype is not None else self.params["tok_emb"].dtype
        if num_pages is None:
            # the draft pool mirrors the main pool's TOKEN capacity (the
            # draft attends over the same contexts); it is "tiny" because
            # it holds draft_layers layers, not num_layers
            num_pages = self.max_batch * pages_needed(self.max_seq_len,
                                                      page_size)
        # a PRIVATE registry: the manager's kv_* gauge names would
        # otherwise collide with (and overwrite) the main pool's on the
        # predictor's shared registry
        self.cache = KVCacheManager(
            self.draft_layers, config.num_heads, config.head_dim,
            num_pages=num_pages, max_batch=self.max_batch,
            max_seq_len=self.max_seq_len, page_size=page_size,
            num_q_heads=config.num_heads, dtype=kv_dtype,
            quantize_kv=self.kv_quant, mesh=mesh,
            metrics=MetricsRegistry())
        self._catchup = build_draft_step(
            config, self.draft_layers, self.cache.page_size, self.chunk,
            use_kernel=use_kernel, kv_quant=self.kv_quant, mesh=mesh)
        # round 22: the k-step proposal chain is ONE fused jit
        # (models/gpt.py build_draft_chain) — a lax.scan over the chain
        # steps, so a speculative round costs ONE draft dispatch instead
        # of k. Chains build lazily per requested depth through the
        # process-wide jit cache (an adaptive-k backoff round runs a
        # shorter scan, never masked steps it didn't ask for); ``max_k``
        # (the predictor passes its spec_k) pre-builds the steady-state
        # geometry so construction-time validation fires loudly.
        # ``mega`` routes the chain's layer blocks through the
        # persistent mega kernels (default: the config flag — the chain
        # matches the parent build's kernel family).
        self.max_k = int(max_k) if max_k else 0
        self.mega = bool(getattr(config, "mega_decode", False)
                         if mega is None else mega)
        self._config = config
        self._use_kernel = use_kernel
        self._mesh = mesh
        if self.max_k:
            self._chain_fn(self.max_k)   # build-time validation fires HERE
        self._t_catchup = self.max_batch * self.chunk
        b = self.max_batch
        self._no_cow = jnp.full((b,), self.cache.num_pages, jnp.int32)
        self._zero_prev = jnp.zeros((b,), jnp.int32)
        self._zero_keys = jnp.zeros((b, 2), jnp.uint32)
        self._zero_f32 = jnp.zeros((b,), jnp.float32)
        self._zero_i32 = jnp.zeros((b,), jnp.int32)
        self._one_f32 = jnp.ones((b,), jnp.float32)
        self._np = np
        self._jnp = jnp
        # req_id -> {"slot": draft slot, "fed": [token ids written]},
        # insertion-ordered oldest-proposer-first (the eviction order)
        from collections import OrderedDict

        self._lanes: "OrderedDict[int, dict]" = OrderedDict()
        self.model_steps = 0          # draft jit launches (all geometries)

    # -- lifecycle ---------------------------------------------------------

    def release(self, req_id) -> None:
        """Drop a request's draft lane (terminal teardown — the predictor
        calls this wherever it drops the request's proposer)."""
        st = self._lanes.pop(req_id, None)
        if st is not None:
            self.cache.free(st["slot"])

    def _evict_one(self, keep: set) -> bool:
        """Free the oldest draft lane not in ``keep``."""
        for rid in list(self._lanes):
            if rid not in keep:
                self.release(rid)
                return True
        return False

    def _lane_for(self, req_id, ctx, keep: set):
        """The request's draft lane, admitted on first use. Returns None
        when the pool cannot hold this context even after evicting every
        other idle lane (the lane then proposes nothing this round)."""
        st = self._lanes.get(req_id)
        if st is not None:
            self._lanes.move_to_end(req_id)
            return st
        while True:
            hit = self.cache.admit_prefix(ctx, soft=True)
            if hit is not None:
                st = {"slot": hit[0], "fed": [], "rid": req_id}
                self._lanes[req_id] = st
                return st
            if not self._evict_one(keep):
                return None

    # -- the per-round proposal pass ---------------------------------------

    def _dispatch(self, fn, t, rows, q_lens, last_idx, emit, prev):
        """One draft-step launch over packed ``rows`` (list of
        (w, slot, tok, pos) with tok None for feedback rows)."""
        np, jnp = self._np, self._jnp
        cache = self.cache
        b = self.max_batch
        tok_ids = np.zeros((t,), np.int32)
        tok_slot = np.full((t,), -1, np.int32)
        tok_pos = np.zeros((t,), np.int32)
        feedback = np.zeros((t,), np.int32)
        for w, slot, tok, pos in rows:
            tok_slot[w] = slot
            tok_pos[w] = pos
            if tok is None:
                feedback[w] = 1
            else:
                tok_ids[w] = tok
        args = (self.params, jnp.asarray(tok_ids), jnp.asarray(tok_slot),
                jnp.asarray(tok_pos), jnp.asarray(q_lens),
                cache.seq_lens_device(), jnp.asarray(last_idx),
                jnp.asarray(feedback), prev, jnp.asarray(emit),
                self._zero_i32)
        pools = ((cache.k_pages, cache.v_pages, cache.k_scales,
                  cache.v_scales) if self.kv_quant
                 else (cache.k_pages, cache.v_pages))
        tail = (cache.page_table_device(), self._no_cow, self._no_cow,
                self._zero_keys, self._zero_f32, self._zero_i32,
                self._one_f32)
        res = fn(*args, *pools, *tail)
        cache.update_pages(*res[2:])
        self.model_steps += 1
        if self._on_launch is not None:
            self._on_launch()
        return res[0]                 # next_toks [b] (greedy argmax)

    def propose(self, lanes: dict) -> dict:
        """Draft for every lane in one batched pass.

        ``lanes``: ``{key: (req_id, context, k)}`` — ``context`` is the
        lane's VALUE-COMPLETE context (prompt + landed outputs; the
        scheduler reconciles in-flight tokens before proposing) and ``k``
        the already-clamped draft count (> 0). Returns ``{key: [ints]}``
        (a lane the draft pool cannot hold maps to ``[]``).
        """
        np = self._np
        cache = self.cache
        keep = {rid for rid, _, _ in lanes.values()}
        active = {}                    # key -> (st, ctx, k)
        for key, (rid, ctx, k) in lanes.items():
            st = self._lane_for(rid, ctx, keep)
            if st is None:
                continue
            # self-heal: roll the draft KV back to the longest prefix of
            # the CURRENT context it holds (capped at len-1: the chain
            # must feed the live last token itself)
            fed, limit = st["fed"], len(ctx) - 1
            p = 0
            while p < min(len(fed), limit) and fed[p] == ctx[p]:
                p += 1
            if len(fed) > p:
                cache.rollback(st["slot"], p)
                del fed[p:]
            active[key] = (st, ctx, int(k))
        # -- catch-up: replay context the pool does not hold yet ----------
        while True:
            rows = []
            q_lens = np.zeros((self.max_batch,), np.int32)
            last_idx = np.full((self.max_batch,), self._t_catchup, np.int32)
            emit = np.zeros((self.max_batch,), np.int32)
            w = 0
            drop = []
            for key, (st, ctx, k) in active.items():
                need = len(ctx) - 1 - len(st["fed"])
                if need <= 0:
                    continue
                n = min(self.chunk, need, self._t_catchup - w)
                if n <= 0:
                    continue
                if not self._ensure(st, len(st["fed"]) + n, keep):
                    drop.append(key)
                    continue
                base = len(st["fed"])
                for i in range(n):
                    rows.append((w + i, st["slot"], ctx[base + i],
                                 base + i))
                q_lens[st["slot"]] = n
                w += n
            for key in drop:
                st, _, _ = active.pop(key)
                self.release(st["rid"])
            if not rows:
                break
            self._dispatch(self._catchup, self._t_catchup, rows, q_lens,
                           last_idx, emit, self._zero_prev)
            for key, (st, ctx, k) in active.items():
                n = int(q_lens[st["slot"]])
                if n:
                    cache.advance(st["slot"], n)
                    st["fed"].extend(ctx[len(st["fed"]):len(st["fed"]) + n])
        if not active:
            return {key: [] for key in lanes}
        # -- the fused k-step chain: ONE dispatch for the whole round -----
        # (round 22: the per-step loop collapsed into build_draft_chain's
        # device-side lax.scan — intermediates never touch the host). The
        # page table is FIXED for the whole chain, so capacity is
        # pre-reserved here: a lane the pool cannot grow for clamps its
        # chain length down to what fits (0 = it sits the round out).
        k_max = max(k for _, _, k in active.values())
        b = self.max_batch
        first = np.zeros((b,), np.int32)
        steps = np.zeros((b,), np.int32)
        reach = {}                     # key -> chain steps the lane runs
        for key, (st, ctx, k) in active.items():
            L = len(ctx)
            s = int(k)
            while s > 0 and not self._ensure(st, L - 1 + s, keep):
                s -= 1
            reach[key] = s
            if s > 0:
                first[st["slot"]] = ctx[-1]
                steps[st["slot"]] = s
        if not any(reach.values()):
            return {key: [] for key in lanes}
        fn = self._chain_fn(k_max)
        jnp = self._jnp
        res = fn(self.params, jnp.asarray(first), jnp.asarray(steps),
                 cache.seq_lens_device(),
                 *((cache.k_pages, cache.v_pages, cache.k_scales,
                    cache.v_scales) if self.kv_quant
                   else (cache.k_pages, cache.v_pages)),
                 cache.page_table_device())
        cache.update_pages(*res[1:])
        self.model_steps += 1
        if self._on_launch is not None:
            self._on_launch()
        # ONE hard sync lands every lane's whole chain
        arr = np.asarray(res[0])                      # [b, k_build]
        drafts = {key: [] for key in lanes}
        for key, (st, ctx, k) in active.items():
            r = reach[key]
            if r <= 0:
                continue
            cache.advance(st["slot"], r)
            d = [int(arr[st["slot"], i]) for i in range(r)]
            drafts[key] = d
            # KV now holds ctx[-1] + the first r-1 drafts
            st["fed"].extend([ctx[-1]] + d[:r - 1])
        return drafts

    def _chain_fn(self, k: int):
        """The fused chain jit at geometry ``k`` — the round's actual
        max requested depth, so an adaptive-k backoff round never pays
        masked scan steps it didn't ask for. The process-wide cache in
        models/gpt.py bounds this to one executable per distinct depth
        (at most ``max_k`` of them; the constructor pre-builds the
        steady-state ``max_k`` geometry)."""
        from ..models.gpt import _draft_chain_fn

        return _draft_chain_fn(
            self._config, self.draft_layers, self.cache.page_size,
            int(k), self._use_kernel,
            kv_quant=self.kv_quant, mesh=self._mesh, mega=self.mega)

    def _ensure(self, st, new_len: int, keep: set) -> bool:
        """Grow a draft lane, evicting idle lanes under pressure — but
        never another lane proposing THIS round (``keep``)."""
        while not self.cache.ensure_capacity(st["slot"], new_len):
            if new_len > self.max_seq_len or not self._evict_one(
                    keep | {st["rid"]}):
                return False
        return True
