"""Continuous-batching autoregressive serving over the paged KV cache.

The round-7 serving front end: the classic continuous-batching loop
(Orca/vLLM; reference surface: the fused-transformer serving family that
``block_multihead_attention`` feeds) on top of

- :class:`~paddle_tpu.inference.kv_cache.KVCacheManager` — page pool,
  admission, eviction;
- ``models/gpt.py`` ``build_prefill`` / ``build_decode_step`` — one jit for
  each prompt-length bucket, ONE fixed-shape jit for the decode step.

Request lifecycle: WAITING (queued) -> RUNNING (owns a decode slot + pages)
-> FINISHED (eos / max_new_tokens). Between decode steps the scheduler
admits waiting requests into free slots (prefilling their prompts straight
into their pages) and frees finished ones — sequences join and leave the
batch WITHOUT restarting it, so short requests never wait for long ones and
the decode jit's batch lanes (``max_batch``) stay the fixed compile shape
with empty lanes masked by ``seq_len == 0``.

Capacity pressure: when a running sequence cannot grow (page pool
exhausted) the YOUNGEST running request is preempted back to the waiting
queue — its pages are freed and its prompt + generated prefix re-prefills
on the next admission (vLLM's recompute-mode preemption, the policy that
needs no swap space).

Knobs: ``max_batch`` (decode lanes), ``num_pages``/``page_size`` (pool
geometry = max cached tokens), ``max_seq_len`` (page-table width).
"""
from __future__ import annotations

from collections import deque

import numpy as np

import jax.numpy as jnp

from .kv_cache import KVCacheManager, pages_needed

WAITING, RUNNING, FINISHED = "waiting", "running", "finished"


class Request:
    """One generation request; ``output_ids`` fills as decode steps land."""

    _next_id = [0]

    def __init__(self, prompt_ids, max_new_tokens=32, eos_token_id=None):
        self.req_id = Request._next_id[0]
        Request._next_id[0] += 1
        self.prompt_ids = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not self.prompt_ids:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.output_ids: list[int] = []
        self.state = WAITING
        self.preempt_count = 0
        self.truncated = False  # stopped by the max_seq_len ceiling

    @property
    def done(self) -> bool:
        if self.truncated:
            return True
        if len(self.output_ids) >= self.max_new_tokens:
            return True
        return (self.eos_token_id is not None and self.output_ids
                and self.output_ids[-1] == self.eos_token_id)

    def _context_ids(self) -> list[int]:
        """Prompt + generated-so-far — what a re-prefill after preemption
        replays (all but the LAST token go through prefill; the last one is
        the next decode step's input)."""
        return self.prompt_ids + self.output_ids


class ServingPredictor:
    """Continuous-batching decode predictor for a GPT model.

    ``add_request`` enqueues; ``step`` runs one decode step for every
    running sequence (admitting/evicting around it); ``generate`` is the
    batch convenience that drives ``step`` until a set of prompts finishes.
    """

    def __init__(self, model, *, max_batch=8, num_pages=None, page_size=None,
                 max_seq_len=None, use_kernel=None, prefill_bucket=16,
                 dtype=None):
        from ..models.gpt import (_serving_params_cached, build_decode_step,
                                  build_prefill, serving_params)

        gpt = model.gpt if hasattr(model, "gpt") else model
        self.config = gpt.config
        cfg = self.config
        if dtype is None:
            # share the weak-keyed extraction with generate() — a second
            # predictor (or generate call) on one model reuses the stacks
            self.params = _serving_params_cached(model)
        else:
            import jax

            self.params = jax.tree.map(lambda a: a.astype(dtype),
                                       serving_params(model))
        # the model's position table bounds every context
        self.max_seq_len = min(int(max_seq_len or cfg.max_seq_len),
                               cfg.max_seq_len)
        self.max_batch = int(max_batch)
        self.prefill_bucket = int(prefill_bucket)
        kv_dtype = self.params["tok_emb"].dtype
        if num_pages is None:
            # default pool: every lane can reach max_seq_len
            from ..ops.pallas.paged_attention import preferred_page_size

            ps = page_size or preferred_page_size(
                cfg.num_heads, cfg.num_heads, cfg.head_dim, kv_dtype)
            num_pages = self.max_batch * pages_needed(self.max_seq_len, ps)
        self.cache = KVCacheManager(
            cfg.num_layers, cfg.num_heads, cfg.head_dim,
            num_pages=num_pages, max_batch=self.max_batch,
            max_seq_len=self.max_seq_len, page_size=page_size,
            num_q_heads=cfg.num_heads, dtype=kv_dtype)
        self._decode = build_decode_step(cfg, self.cache.page_size,
                                         use_kernel=use_kernel)
        # one jitted prefill; jax.jit caches one executable per prompt
        # bucket shape (prompts are padded to _bucket multiples)
        self._prefill = build_prefill(cfg, self.cache.page_size)
        self.waiting: deque[Request] = deque()
        self.running: dict[int, Request] = {}   # slot -> request
        self._next_token = np.zeros((self.max_batch,), np.int32)
        self.steps = 0

    # -- queue API ---------------------------------------------------------

    def add_request(self, prompt_ids, max_new_tokens=32,
                    eos_token_id=None) -> Request:
        req = Request(prompt_ids, max_new_tokens, eos_token_id)
        if len(req.prompt_ids) > self.max_seq_len:
            raise ValueError(
                f"prompt of {len(req.prompt_ids)} tokens exceeds "
                f"max_seq_len {self.max_seq_len}")
        self.waiting.append(req)
        return req

    @property
    def decode_trace_count(self) -> int:
        """Times the decode step has been (re)traced — the no-retrace gate
        asserts this stays constant after warmup."""
        return self._decode.trace_count[0]

    # -- internals ---------------------------------------------------------

    def _bucket(self, n: int) -> int:
        b = self.prefill_bucket
        return max(b, ((n + b - 1) // b) * b)

    def _admit_one(self, req: Request) -> bool:
        """Claim a slot + pages and prefill ``req``'s context into them."""
        ctx = req._context_ids()
        prefix, last = ctx[:-1], ctx[-1]
        # all but the LAST context token prefill; the last token becomes
        # the next decode step's input, and that step produces its
        # successor. A 1-token context has no prefix to split: prefill the
        # token itself and take the prefill's greedy argmax as the first
        # output instead.
        if not prefix:
            prefix, last = ctx, None
        need_len = len(prefix)
        # vLLM-style watermark: with other sequences running, keep one
        # free page of growth headroom past the prompt's own need —
        # an exactly-fitting admission would be preempted (its whole
        # prefill discarded) by the same step's growth pass
        headroom = 1 if self.running else 0
        if (not self.cache.can_admit(need_len)
                or self.cache.free_page_count
                < self.cache.pages_needed(need_len) + headroom):
            return False
        if len(ctx) > self.max_seq_len:
            raise ValueError(
                f"request {req.req_id}: context {len(ctx)} exceeds "
                f"max_seq_len {self.max_seq_len}")
        slot = self.cache.admit(need_len)
        # bucket rounding must not push the prefill shape past the model's
        # position table (max_seq_len need not be a bucket multiple)
        padded = min(self._bucket(need_len), self.config.max_seq_len)
        ids = np.zeros((1, padded), np.int32)
        ids[0, :need_len] = prefix
        next_ids, _, kp, vp = self._prefill(
            self.params, jnp.asarray(ids),
            jnp.asarray([need_len], jnp.int32),
            self.cache.k_pages, self.cache.v_pages,
            self.cache.slot_pages(slot)[None])
        self.cache.update_pages(kp, vp)
        if last is None:
            # 1-token context: the prefill's greedy token IS the first
            # generated token; decode continues from it
            tok = int(np.asarray(next_ids)[0])
            req.output_ids.append(tok)
            self._next_token[slot] = tok
        else:
            # multi-token context (fresh prompt or preemption replay):
            # the last context token enters the next decode step, which
            # produces its not-yet-recorded successor
            self._next_token[slot] = last
        req.state = RUNNING
        self.running[slot] = req
        return True

    def _admit_waiting(self) -> None:
        while self.waiting and self.cache.free_slot_count:
            req = self.waiting[0]
            # a request finished by its prefill token alone never decodes
            if req.done:
                self.waiting.popleft()
                req.state = FINISHED
                continue
            if len(req._context_ids()) > self.max_seq_len:
                # preempted while sitting AT the length ceiling (its own
                # truncation check never ran that round): finish it as
                # truncated, same as the in-loop ceiling stop
                self.waiting.popleft()
                req.truncated = True
                req.state = FINISHED
                continue
            if not self._admit_one(req):
                # head-of-line blocking keeps FIFO order — but if nothing
                # is running and the whole pool is free, this request can
                # NEVER fit: fail with the real cause instead of letting
                # generate() spin empty steps into its budget error
                if (not self.running and self.cache.free_page_count
                        == self.cache.num_pages):
                    need = self.cache.pages_needed(
                        len(req._context_ids()) - 1)
                    raise RuntimeError(
                        f"request {req.req_id}: context of "
                        f"{len(req._context_ids())} tokens needs {need} "
                        f"pages but the pool only has "
                        f"{self.cache.num_pages} — raise num_pages or "
                        "page_size")
                break
            self.waiting.popleft()

    def _preempt_youngest(self) -> bool:
        """Free the youngest running request back to the waiting queue."""
        if not self.running:
            return False
        slot = max(self.running,
                   key=lambda s: self.running[s].req_id)
        req = self.running.pop(slot)
        self.cache.free(slot)
        req.state = WAITING
        req.preempt_count += 1
        self.waiting.appendleft(req)
        return True

    def _retire_finished(self) -> None:
        for slot in [s for s, r in self.running.items() if r.done]:
            req = self.running.pop(slot)
            self.cache.free(slot)
            req.state = FINISHED

    # -- the step ----------------------------------------------------------

    def step(self) -> dict[int, int]:
        """One scheduler round: retire finished, admit waiting, grow pages
        (preempting under pressure), ONE fixed-shape decode step. Returns
        ``{req_id: token}`` for the tokens produced this step."""
        self._retire_finished()
        # admit/retire to fixpoint: a fresh prompt whose prefill token
        # already satisfies done (budget 1, or prefill token == eos) must
        # retire BEFORE the decode step — it would otherwise collect a
        # second token past its contract — and its freed lane can admit
        # the next waiting request within this same round
        while True:
            self._admit_waiting()
            if not any(r.done for r in self.running.values()):
                break
            self._retire_finished()
        if not self.running:
            return {}
        # growth: every running sequence needs room for one more token.
        # sorted() snapshots the slots — a preemption further down this
        # loop removes entries, and a freed slot must not re-enter the
        # capacity path (it would allocate pages into a parked page table)
        for slot in sorted(self.running):
            if slot not in self.running:
                continue
            if self.cache.seq_len(slot) + 1 > self.max_seq_len:
                # hit the length ceiling: stop the sequence NOW (truncation-
                # stop, flagged on the request) and park its lane before the
                # decode would write past the page-table width
                req = self.running.pop(slot)
                req.truncated = True
                self.cache.free(slot)
                req.state = FINISHED
                continue
            while not self.cache.ensure_capacity(
                    slot, self.cache.seq_len(slot) + 1):
                # page pressure: shed the youngest request (never this one
                # unless it IS the youngest and alone — then it cannot run)
                victim_is_self = (max(self.running,
                                      key=lambda s: self.running[s].req_id)
                                  == slot)
                if victim_is_self and len(self.running) == 1:
                    raise RuntimeError(
                        f"slot {slot}: cannot grow to "
                        f"{self.cache.seq_len(slot) + 1} tokens — page pool "
                        "too small for a single sequence")
                self._preempt_youngest()
                if slot not in self.running:  # preempted itself
                    break
        ids = jnp.asarray(self._next_token)
        next_ids, _, kp, vp = self._decode(
            self.params, ids, self.cache.seq_lens_device(),
            self.cache.k_pages, self.cache.v_pages,
            self.cache.page_table_device())
        self.cache.update_pages(kp, vp)
        self.steps += 1
        out = np.asarray(next_ids)
        produced = {}
        for slot, req in self.running.items():
            tok = int(out[slot])
            req.output_ids.append(tok)
            self._next_token[slot] = tok
            self.cache.advance(slot)
            produced[req.req_id] = tok
        return produced

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    # -- convenience -------------------------------------------------------

    def generate(self, prompts, max_new_tokens=32, eos_token_id=None,
                 max_steps=None):
        """Enqueue ``prompts`` (list of id lists) and drive steps until all
        finish. Returns a list of output-id lists, in prompt order."""
        reqs = [self.add_request(p, max_new_tokens, eos_token_id)
                for p in prompts]
        limit = max_steps or (len(prompts) * (max_new_tokens + 2)
                              * (self.max_batch + 1))
        n = 0
        while any(r.state != FINISHED for r in reqs):
            self.step()
            # a drained scheduler with unfinished requests means they can
            # never be admitted (oversized); surface rather than spin
            if not self.has_work():
                break
            n += 1
            if n > limit:
                raise RuntimeError("serving loop exceeded step budget "
                                   f"({limit}) — scheduler stuck")
        return [list(r.output_ids) for r in reqs]


__all__ = ["Request", "ServingPredictor", "WAITING", "RUNNING", "FINISHED"]
