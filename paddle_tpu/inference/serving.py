"""Continuous-batching autoregressive serving over the paged KV cache.

Round 9: the serving front end schedules a UNIFIED ragged step — ONE
fixed-shape jit (``models/gpt.py build_unified_step``) serves decode tokens
and chunked-prefill tokens in the same program, driven by a per-step token
budget. The round-7 two-jit path (bucketed batch-1 prefill + fixed-shape
decode) is kept behind ``unified=False`` as the A/B baseline and the
token-for-token equivalence oracle until a later PR deletes it.

Scheduling (the Ragged-Paged-Attention / chunked-prefill shape, PAPERS.md):

- every running slot with exactly one context token left to feed is a
  DECODE lane — those pack first, one token each, so admission never
  head-of-line-blocks the decode batch behind a full prompt forward;
- the remaining token budget fills with PREFILL CHUNKS (FIFO by request
  age, up to ``chunk`` tokens per slot per step) from admitting or
  preemption-replaying sequences;
- a chunk that reaches the end of its context yields that slot's next
  token (greedy argmax bit-identical to round 7, or the fused seeded
  temperature/top-k/top-p epilogue).

Prefix caching: admission matches the prompt against the page-granular
content-hash registry (``KVCacheManager.admit_prefix``) and skips the
prefill compute for every hit page; fully-prefilled prompts register their
pages for later requests. Divergent writes into shared pages ride the
step's copy-on-write lanes.

Request lifecycle: WAITING (queued) -> RUNNING (owns a slot + pages;
prefilling until its context is fully fed, then decoding) -> FINISHED
(eos / max_new_tokens / length ceiling). Capacity pressure preempts the
YOUNGEST running request back to the queue (recompute-mode, vLLM policy);
its replay re-hits its own registered prefix pages.

Round 12 adds SPECULATIVE DECODING on the unified step
(``spec_decode_k``): every decode lane consults its request's n-gram /
prompt-lookup draft proposer (``inference/draft.py``, per-request table
fed from the already-tracked context ids, adaptive k backing off to plain
decode on low acceptance) and packs ``1 + k`` verify rows into the SAME
token budget (decode lanes first, prefill chunks still fill the
remainder — no new geometry). The step's fused accept epilogue emits the
accepted prefix + one bonus token (greedy bit-identical to plain decode;
sampled rows ride the per-request seeded streams keyed by
tokens-produced), and rejected drafts' over-allocated pages roll back
host-side (``KVCacheManager.trim_pages``) so page/refcount accounting
stays identical to a never-speculated run.

Round 13 adds the ASYNC DOUBLE-BUFFERED ENGINE (``async_engine=True``):
``step()`` packs and DISPATCHES step N, then reconciles step N-1's
deferred results — the host scheduler and the device execute
concurrently (JAX async dispatch), so the TPU never idles through the
pack/bookkeeping gap the synchronous loop pays between steps (the
inter-step host bubble MPK diagnoses). The enabler is device-resident
sampled-token feedback: the unified step returns a per-lane ``next_toks``
carry that the next step consumes as a traced input (``feedback`` mask +
``prev_toks``), so decode lanes advance WITHOUT materializing the token
on the host. Host bookkeeping that only needs token COUNTS (page growth,
capacity, admission, budget-retirement, prefix registration) runs at
pack time; bookkeeping that needs token VALUES (``output_ids``, eos
detection, TTFT, preemption-replay contexts, spec drafts/rollback)
reconciles one step behind on the deferred results. The hard host syncs
are exactly the emission boundaries: a step whose emissions could finish
a request (eos configured / output budget reachable) reconciles
behind-by-one; steps that cannot complete anything defer up to
``max_inflight_steps`` and drain in one batched materialization
(``flush()``). Greedy output is bit-identical and seeded sampling
stream-identical to the synchronous engine (per-request streams are
batch-order invariant); round 14 makes async the DEFAULT on the unified
path (PR 8 soaked green) — ``async_engine=False`` keeps the synchronous
engine as the oracle — both drive the SAME pack/capacity code, the sync
engine simply reconciles at pipeline depth zero.

Round 17 adds the RESILIENCE LAYER. Requests gain a terminal ``FAILED``
state with a per-request ``error`` record ``{"code", "message"}`` —
never-admittable prompts, retry-exhausted step failures, shed
admissions and missed deadlines fail INDIVIDUALLY while the predictor
keeps serving everyone else. ``deadline_s`` gives a request a wall-clock
budget (expired WAITING requests shed as ``deadline_exceeded`` at the
next scheduler round — the queue TTL; RUNNING requests past deadline
retire at the next round's reconcile point). ``slo=SLOConfig(...)``
arms admission control at ``add_request``: a bounded waiting queue plus
SLO-aware load shedding off the round-15 telemetry signals (pool
occupancy, in-flight ring depth, TTFT-p99 EMA); the verdicts
(:meth:`ServingPredictor.admission_verdict`) and the
:meth:`~ServingPredictor.healthz` snapshot are the load-signal surface
the fleet router consumes. Step execution is CRASH-CONSISTENT: an
exception inside ``_pack_dispatch`` (pack, H2D upload, launch) or
``_reconcile_one`` (materialization) drops the failed in-flight entry,
un-charges its dispatched-unmaterialized tokens, and requeues every
affected lane through the existing preemption-replay path (already
value-barriered and bit-identical on replay) with bounded retry +
exponential backoff before the affected requests FAIL — page / slot /
refcount / prefix-pin accounting is exact after any failure.
``inference/faults.py`` injects deterministic seeded faults at the
named seams (pool squeeze, h2d, dispatch, slow_step, reconcile);
disarmed, every seam is one module-global check. With no faults armed,
no deadlines set and shedding off, the engine is bit-identical to the
round-16 engine.

Round 19 makes speculation MODEL-BASED and composes it with the async
engine. ``draft_source="model"`` (or ``config.spec_draft_layers > 0``)
swaps the n-gram proposer for a truncated-layer SELF-DRAFT: a shared
:class:`~paddle_tpu.inference.draft.ModelDraftEngine` runs the first
``draft_layers`` layers of the SAME serving param stacks (shared
embeddings/LM head — zero extra weights) as its own small fixed-shape
unified-step jit over a DEDICATED draft KV pool, proposing k tokens per
decode lane in ONE device-chained pass per scheduler round (catch-up
prefill chunks + a chunk-1 decode chain threaded through the feedback
carry; one host sync lands every lane's drafts). Acceptance then tracks
truncation quality instead of workload repetitiveness — the n-gram
table's collapse on non-repetitive traffic. Per-request adaptive-k /
EMA / cooldown state rides the same ``_drafts`` dict (and survives
preemption replay); the draft pool self-heals against the lane's
CURRENT context, so replays, rejected drafts and dropped in-flight
steps all reconcile through one prefix comparison. Async x spec: a
DRAFTED spec step now dispatches BEHIND-BY-ONE — its n_emit-variable
advance/rollback reconciles at the START of the next round (every
completing lane charges one pending token, the guaranteed minimum
emission) — and DRAFTLESS spec rounds (adaptive k backed off) ride the
plain engine's deferral + steady-pack cache untouched, so speculation
and dispatch-ahead multiply instead of excluding each other
(``serving_spec_async_deferred_steps`` counts both shapes). Greedy and
seeded emissions stay bit-identical to the sync spec engine, with page
accounting in lockstep at every drain.

Knobs: ``max_batch`` (lanes), ``num_pages``/``page_size`` (pool geometry),
``max_seq_len`` (page-table width), ``chunk`` (per-slot prefill chunk,
autotuned default), ``token_budget`` (tokens per step, default
``max_batch * (1 + spec_k) + chunk``), ``prefix_cache`` (on by default
when unified), ``spec_decode_k`` (speculation build geometry, default
``config.spec_decode_k``), ``async_engine`` (the round-13 pipelined
engine) + ``max_inflight_steps`` (deferral bound for steps that cannot
complete any request), ``mega_decode`` (round 16, ragged since round 22;
default ``config.mega_decode``: EVERY round — mixed prefill+decode
included — runs the fused per-layer Pallas megakernels of
``ops/pallas/mega_decode`` at the unified step's packed ragged geometry,
activations pinned in VMEM, the draft chain collapsed to one dispatch;
emissions are bit-identical either way).
"""
from __future__ import annotations

import time
from collections import deque

import numpy as np

import jax.numpy as jnp

from ..observability import (MetricsRegistry, counter_event, monotonic,
                             request_begin, request_end, request_event,
                             span, tracing_active)
from ..profiler.record import recorder as _recorder
from .faults import InjectedFault, fault_point
from .kv_cache import KVCacheManager, kv_cache_quantized, pages_needed

WAITING, RUNNING, FINISHED, FAILED = ("waiting", "running", "finished",
                                      "failed")


def stream_done(output_ids, max_new_tokens, eos_token_id) -> bool:
    """The budget/eos stop rule over a MATERIALIZED output stream — the
    ONE spelling shared by the async emission-drop rule
    (:meth:`ServingPredictor._landed_done`) and the fleet router's
    failover dedup (``FleetRequest.done``): the two deciding the same
    question from different layers must never drift apart."""
    if len(output_ids) >= max_new_tokens:
        return True
    return (eos_token_id is not None and bool(output_ids)
            and output_ids[-1] == eos_token_id)


def deadline_passed(submit_time, deadline_s, now=None) -> bool:
    """Absolute-deadline check anchored at the ORIGINAL submission —
    shared by :class:`Request` and the fleet router's request handle."""
    if deadline_s is None:
        return False
    return (monotonic() if now is None else now) >= submit_time + deadline_s


class Request:
    """One generation request; ``output_ids`` fills as steps land."""

    _next_id = [0]

    def __init__(self, prompt_ids, max_new_tokens=32, eos_token_id=None,
                 temperature=0.0, top_k=0, top_p=1.0, seed=None,
                 deadline_s=None, submit_time=None, sample_offset=0):
        self.req_id = Request._next_id[0]
        Request._next_id[0] += 1
        self.prompt_ids = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not self.prompt_ids:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        # round 17: wall-clock budget (seconds from submission; None =
        # no deadline) and the terminal-failure record — a FAILED request
        # carries {"code", "message"} in ``error``
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        if self.deadline_s is not None and self.deadline_s < 0:
            raise ValueError(f"deadline_s must be >= 0, got {deadline_s}")
        self.error: dict | None = None
        # failure-driven requeues (NOT ordinary preemptions): bounded by
        # the predictor's max_step_retries before the request FAILS
        self.retry_count = 0
        self._finish_counted = False
        # sampling params (temperature == 0 -> greedy argmax, bit-identical
        # to round 7); seed defaults to the request id so replays after
        # preemption re-sample the SAME stream (keyed by tokens produced)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = self.req_id if seed is None else int(seed)
        # round 20: the tokens-produced base of the in-jit sample-key
        # fold. A re-admission that carries ALREADY-RECEIVED tokens in
        # its prompt (the fleet router's failover resume and the
        # disaggregated prefill->decode handoff both feed
        # ``original_prompt + received``) passes the received count
        # here, so token r+i samples with fold(base_key, r+i) — the
        # seeded stream continues bit-identically to an uninterrupted
        # run instead of restarting its fold at 0
        self.sample_offset = int(sample_offset)
        if self.sample_offset < 0:
            raise ValueError(f"sample_offset must be >= 0, "
                             f"got {sample_offset}")
        self.output_ids: list[int] = []
        # tokens the async engine has dispatched for this request but not
        # yet materialized on the host (always 0 in the sync engine once
        # a step returns): they count toward the output budget and the
        # context length, their VALUES land at reconcile
        self._pending_n = 0
        self.state = WAITING
        self.preempt_count = 0
        self.truncated = False  # stopped by the max_seq_len ceiling
        # serving metrics: time-to-first-token + prefix-cache hit size.
        # round 18: ``submit_time`` may be supplied by a RE-ADMISSION path
        # (the fleet router's failover re-admit): a request's wall-clock
        # budget is anchored at its ORIGINAL submission — re-admitting
        # must never restart the TTL (``past_deadline`` reads
        # submit_time + deadline_s, so carrying the stamp carries the
        # absolute deadline). In-predictor preemption replay requeues the
        # SAME Request object, which preserves the stamp by construction.
        self.submit_time = (monotonic() if submit_time is None
                            else float(submit_time))
        self.first_token_time: float | None = None
        self.cached_prefix_len = 0   # tokens served from the prefix cache
        self._registered = False     # prompt pages in the prefix registry

    @property
    def done(self) -> bool:
        if self.truncated:
            return True
        if len(self.output_ids) + self._pending_n >= self.max_new_tokens:
            return True
        return (self.eos_token_id is not None and self.output_ids
                and self.output_ids[-1] == self.eos_token_id)

    @property
    def _ctx_len(self) -> int:
        """Context length INCLUDING dispatched-unmaterialized tokens —
        what the scheduler's count-based packing sees."""
        return len(self.prompt_ids) + len(self.output_ids) + self._pending_n

    @property
    def ttft(self) -> float | None:
        """Seconds from submission to the first generated token."""
        if self.first_token_time is None:
            return None
        return self.first_token_time - self.submit_time

    def _context_ids(self) -> list[int]:
        """Prompt + generated-so-far — what a re-prefill after preemption
        replays."""
        return self.prompt_ids + self.output_ids

    def past_deadline(self, now=None) -> bool:
        return deadline_passed(self.submit_time, self.deadline_s, now)


class SLOConfig:
    """Admission-control / load-shedding policy for one predictor
    (round 17). ``slo=None`` (the default) disables shedding entirely;
    an armed config sheds at :meth:`ServingPredictor.add_request` — the
    request comes back terminal FAILED with a ``shed_*`` error code
    instead of queueing into an overload the SLO can never recover from.

    - ``max_waiting`` — the bounded waiting queue (always enforced once
      armed; ``shed_queue_full``).
    - ``max_pool_occupancy`` — shed while the KV pool's claimed fraction
      (1 - available/total) is at/above this AND a backlog exists
      (``shed_pool_pressure``).
    - ``max_inflight_depth`` — shed while the async in-flight ring sits
      at/above this depth with a backlog (``shed_inflight_depth``).
    - ``ttft_p99_slo_ms`` — shed while the TTFT-p99 EMA (an EMA over the
      registry histogram's p99 estimate, updated per first token) is
      above the SLO with a backlog (``shed_ttft_slo``).

    The thresholds other than ``max_waiting`` default to None (off) so a
    config can arm exactly the signals its deployment trusts.
    """

    def __init__(self, *, max_waiting=256, max_pool_occupancy=None,
                 max_inflight_depth=None, ttft_p99_slo_ms=None,
                 ema_alpha=0.2):
        self.max_waiting = None if max_waiting is None else int(max_waiting)
        if self.max_waiting is not None and self.max_waiting < 1:
            raise ValueError(f"max_waiting must be >= 1, got {max_waiting}")
        self.max_pool_occupancy = (None if max_pool_occupancy is None
                                   else float(max_pool_occupancy))
        if self.max_pool_occupancy is not None \
                and not 0.0 < self.max_pool_occupancy <= 1.0:
            raise ValueError(f"max_pool_occupancy is a fraction in (0, 1], "
                             f"got {max_pool_occupancy}")
        self.max_inflight_depth = (None if max_inflight_depth is None
                                   else int(max_inflight_depth))
        if self.max_inflight_depth is not None and self.max_inflight_depth < 0:
            raise ValueError(f"max_inflight_depth must be >= 0, "
                             f"got {max_inflight_depth}")
        self.ttft_p99_slo_ms = (None if ttft_p99_slo_ms is None
                                else float(ttft_p99_slo_ms))
        if self.ttft_p99_slo_ms is not None and self.ttft_p99_slo_ms <= 0:
            raise ValueError(f"ttft_p99_slo_ms must be > 0, "
                             f"got {ttft_p99_slo_ms}")
        self.ema_alpha = float(ema_alpha)
        if not 0.0 < self.ema_alpha <= 1.0:
            raise ValueError(f"ema_alpha must be in (0, 1], got {ema_alpha}")


class _Pending:
    """One dispatched-but-unreconciled unified step — an entry of the
    async engine's in-flight ring. Holds the DEVICE handles of the step's
    emission outputs (unmaterialized jax arrays) plus the host records
    needed to land them one step behind: materializing ``out``/``ne`` is
    the engine's ONE hard sync."""

    __slots__ = ("out", "ne", "completing", "spec", "spec_slots",
                 "must_sync")

    def __init__(self, out, ne, completing, spec, spec_slots, must_sync):
        self.out = out                 # device next_toks / out_ids
        self.ne = ne                   # device n_emit (spec builds)
        self.completing = completing   # [(slot, req, k_i, was_decode)]
        self.spec = spec
        self.spec_slots = spec_slots   # lanes advancing by n_emit + trim
        self.must_sync = must_sync     # some emission could finish a req


class ServingPredictor:
    """Continuous-batching predictor for a GPT model.

    ``add_request`` enqueues; ``step`` runs one scheduler round (admit /
    grow / preempt around ONE unified-step launch); ``generate`` drives
    ``step`` until a set of prompts finishes. ``unified=False`` falls back
    to the round-7 two-jit path (per-bucket prefill at admission + decode
    step) — the A/B baseline. ``async_engine`` (round 13; the DEFAULT on
    the unified path since round 14) overlaps host scheduling with device
    execution: ``step()`` dispatches round N and reconciles round N-1's
    deferred emissions (see the module docstring for the sync-boundary
    contract); ``flush()`` drains the in-flight ring; ``False`` selects
    the synchronous oracle engine.
    """

    def __init__(self, model, *, max_batch=8, num_pages=None, page_size=None,
                 max_seq_len=None, use_kernel=None, prefill_bucket=16,
                 dtype=None, unified=True, chunk=None, token_budget=None,
                 prefix_cache=None, kv_cache_dtype=None, mesh=None,
                 spec_decode_k=None, async_engine=None,
                 max_inflight_steps=4, metrics=None, mega_decode=None,
                 slo=None, max_step_retries=3, retry_backoff_s=0.02,
                 replica_id=0, role="colocated", draft_source=None,
                 draft_layers=None, draft_num_pages=None,
                 host_tier_bytes=0):
        from ..distributed.mesh import as_serving_mesh
        from ..models.gpt import (_serving_params_cached, build_decode_step,
                                  build_prefill, build_unified_step,
                                  serving_params, shard_serving_params)

        gpt = model.gpt if hasattr(model, "gpt") else model
        self.config = gpt.config
        cfg = self.config
        # round 15: the structured metrics registry — every counter/timer
        # this predictor used to keep as ad-hoc attributes lives here
        # (always-enabled by default: these ARE the bench metrics), shared
        # with the KV cache manager so ONE snapshot covers the serving
        # stack; back-compat read properties keep the round-13/14 surface
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        if not self.metrics.enabled:
            # these counters BACK the behavioral read surface
            # (tokens_emitted/steps/TTFT/step_gap_frac/telemetry): a
            # disabled registry would silently report zeros — fail loud
            # (the library-wide default_registry is off by default; pass
            # a dedicated MetricsRegistry() or enable it first)
            raise ValueError(
                "ServingPredictor requires an enabled metrics registry; "
                "the one passed is disabled")
        self._init_instruments()
        # round 11: mesh (None | int mp degree | Mesh(("mp",))) serves the
        # steps tensor-parallel — params + KV pools sharded by head, the
        # scheduler and page/slot/prefix bookkeeping below stay host-global
        self.mesh = as_serving_mesh(mesh)
        if dtype is None:
            # share the weak-keyed extraction with generate() — a second
            # predictor (or generate call) on one model reuses the stacks
            # (quantized per cfg.weight_dtype, sharded per mesh signature,
            # inside the cache)
            self.params = _serving_params_cached(model, mesh=self.mesh)
            # the round-19 draft engine slices its truncated stacks off
            # the UNSHARDED extraction (it re-shards with its own config)
            params_unsharded = (self.params if self.mesh is None
                                else _serving_params_cached(model,
                                                            mesh=None))
        else:
            import jax

            self.params = jax.tree.map(lambda a: a.astype(dtype),
                                       serving_params(model))
            if cfg.weight_dtype is not None:
                from .quantize import quantize_serving_params

                self.params = quantize_serving_params(
                    self.params, cfg.weight_dtype,
                    cfg.weight_quant_group_size)
            params_unsharded = self.params
            if self.mesh is not None:
                self.params = shard_serving_params(self.params, self.mesh,
                                                   cfg)
        # the model's position table bounds every context
        self.max_seq_len = min(int(max_seq_len or cfg.max_seq_len),
                               cfg.max_seq_len)
        self.max_batch = int(max_batch)
        self.prefill_bucket = int(prefill_bucket)
        self.unified = bool(unified)
        self.kv_quant = kv_cache_quantized(kv_cache_dtype
                                           or cfg.kv_cache_dtype)
        if self.kv_quant and not self.unified:
            raise ValueError(
                "int8 KV cache rides the unified step's quantize-on-write "
                "lanes; the legacy two-jit path serves fp only")
        kv_dtype = self.params["tok_emb"].dtype
        from ..ops.pallas.paged_attention import (preferred_chunk_size,
                                                  preferred_page_size)

        if num_pages is None:
            # default pool: every lane can reach max_seq_len
            ps = page_size or preferred_page_size(
                cfg.num_heads, cfg.num_heads, cfg.head_dim, kv_dtype)
            num_pages = self.max_batch * pages_needed(self.max_seq_len, ps)
        if prefix_cache is None:
            prefix_cache = self.unified
        self.cache = KVCacheManager(
            cfg.num_layers, cfg.num_heads, cfg.head_dim,
            num_pages=num_pages, max_batch=self.max_batch,
            max_seq_len=self.max_seq_len, page_size=page_size,
            num_q_heads=cfg.num_heads, dtype=kv_dtype,
            enable_prefix_cache=prefix_cache, quantize_kv=self.kv_quant,
            mesh=self.mesh, metrics=self.metrics,
            # round 21: the host-DRAM spill tier under the HBM pool
            # (0 disables — evictions drop exactly like pre-21)
            host_tier_bytes=host_tier_bytes)
        self.chunk = int(chunk or preferred_chunk_size(
            cfg.num_heads, cfg.num_heads, cfg.head_dim, kv_dtype))
        # round 12: speculative decoding — build geometry for the verify
        # rows ([b, k+1] outputs); per-request adaptive k only varies the
        # spec_len values, so one executable serves every k <= spec_k
        self.spec_k = int(spec_decode_k if spec_decode_k is not None
                          else getattr(cfg, "spec_decode_k", 0) or 0)
        if self.spec_k < 0:
            raise ValueError(f"spec_decode_k must be >= 0, got "
                             f"{self.spec_k}")
        if self.spec_k and not self.unified:
            raise ValueError(
                "speculative decoding rides the unified step's verify "
                "rows; the legacy two-jit path serves plain decode only")
        if self.spec_k and self.spec_k >= self.chunk:
            raise ValueError(
                f"spec_decode_k {self.spec_k} needs 1 + k <= chunk "
                f"{self.chunk} (verify rows ride the per-slot chunk "
                "block)")
        self.token_budget = int(
            token_budget
            or (self.max_batch * (1 + self.spec_k) + self.chunk))
        # round 16 → 22: the megakernelized build. Round 16 kept a
        # second all-decode-geometry program and routed by round content;
        # round 22's ragged mega kernels accept the SAME packed
        # (token_budget, chunk) geometry as the per-op step, so mega is
        # now a build flavor of the ONE unified program — every round
        # (mixed prefill+decode included) runs the fused per-layer Pallas
        # kernels, and the round-content router is gone. Build-time
        # validation (int4 weights) raises HERE — a predictor must fail
        # loudly at construction, not on its first round.
        # mega_decode=False stays bit-identical to round-15 behavior.
        self.mega_decode = bool(
            getattr(cfg, "mega_decode", False) if mega_decode is None
            else mega_decode)
        if self.mega_decode and not self.unified:
            raise ValueError(
                "mega_decode rides the unified step's packed layout; the "
                "legacy two-jit path serves the per-op chain only")
        if self.unified:
            self._unified = build_unified_step(
                cfg, self.cache.page_size, self.chunk,
                use_kernel=use_kernel, kv_quant=self.kv_quant,
                mesh=self.mesh, spec_k=self.spec_k,
                mega=self.mega_decode)
            self._prefill = self._decode = None
        else:
            self._unified = None
            self._decode = build_decode_step(cfg, self.cache.page_size,
                                             use_kernel=use_kernel,
                                             mesh=self.mesh)
            # one jitted prefill; jax.jit caches one executable per prompt
            # bucket shape (prompts are padded to _bucket multiples)
            self._prefill = build_prefill(cfg, self.cache.page_size,
                                          mesh=self.mesh)
        # round 19: the draft SOURCE behind spec_decode_k — "ngram" (the
        # round-12 prompt-lookup table) or "model" (the truncated-layer
        # self-draft: ModelDraftEngine runs the first draft_layers layers
        # of the SAME param stacks over a dedicated draft KV pool and
        # proposes k tokens per decode lane in one device-chained pass
        # per round). Defaults follow the config: spec_draft_layers > 0
        # selects the model source.
        self.draft_layers = int(
            draft_layers if draft_layers is not None
            else getattr(cfg, "spec_draft_layers", 0) or 0)
        if draft_source is None:
            draft_source = ("model" if (self.spec_k and self.draft_layers)
                            else "ngram")
        if draft_source not in ("ngram", "model"):
            raise ValueError(f"draft_source must be 'ngram' or 'model', "
                             f"got {draft_source!r}")
        self.draft_source = draft_source
        self._draft_engine = None
        if self.draft_source == "model":
            if not self.spec_k:
                raise ValueError(
                    "draft_source='model' needs spec_decode_k > 0 "
                    "(there is nothing to draft)")
            from .draft import ModelDraftEngine

            # draft_config inside the engine rejects draft_layers < 1
            # and >= num_layers loudly AT CONSTRUCTION
            self._draft_engine = ModelDraftEngine(
                cfg, params_unsharded, self.draft_layers,
                page_size=self.cache.page_size, chunk=self.chunk,
                max_batch=self.max_batch, max_seq_len=self.max_seq_len,
                num_pages=draft_num_pages, use_kernel=use_kernel,
                kv_quant=self.kv_quant, mesh=self.mesh,
                on_launch=self._note_draft_launch,
                # round 22: pin the fused chain's build geometry to the
                # predictor's spec_k (one executable for every round) and
                # match its kernel family to the parent build
                max_k=self.spec_k, mega=self.mega_decode)
        # round 13: the async double-buffered engine — dispatch-ahead on
        # the unified step's device-resident token feedback; the sync
        # engine is the same pack/capacity code at pipeline depth zero.
        # round 14: async is the DEFAULT on the unified path (PR 8 soaked:
        # greedy bit-identical + seeded stream-identical to sync); pass
        # async_engine=False for the explicit sync baseline, and the
        # legacy two-jit path stays sync (it has no feedback carry)
        if async_engine is None:
            async_engine = self.unified
        self.async_engine = bool(async_engine)
        self.max_inflight_steps = max(1, int(max_inflight_steps))
        if self.async_engine and not self.unified:
            raise ValueError(
                "the async engine rides the unified step's device-resident "
                "token feedback; the legacy two-jit path serves sync only")
        self._inflight: deque[_Pending] = deque()
        self._did_sync = False   # set by _reconcile_one, charged per call
        self.waiting: deque[Request] = deque()
        self.running: dict[int, Request] = {}   # slot -> request
        self._next_token = np.zeros((self.max_batch,), np.int32)
        self._no_cow = jnp.full((self.max_batch,), self.cache.num_pages,
                                jnp.int32)
        # feedback plumbing: the carry chains device-side step to step in
        # the async engine; the sync engine pins the all-zero constants
        # (no per-step upload, the in-jit where() degenerates to identity)
        self._no_feedback = jnp.zeros((self.token_budget,), jnp.int32)
        self._zero_prev = jnp.zeros((self.max_batch,), jnp.int32)
        self._carry = None       # device next_toks of the LAST dispatch
        # per-lane base PRNG keys ([b, 2], content-cached upload: rows
        # only change on admission) — the in-jit fold keys row j by
        # tokens-produced (+ j under speculation)
        self._lane_keys = np.zeros((self.max_batch, 2), np.uint32)
        # slowly-changing host arrays -> cached device uploads
        self._feed_cache: dict[str, tuple[np.ndarray, object]] = {}
        # steady-decode pack cache (async): previous step's device arrays
        # re-served while the schedule signature holds
        self._steady: dict | None = None
        self._base_keys: dict[int, np.ndarray] = {}   # req_id -> PRNGKey
        # perf accounting (bench_serve step_gap_frac / host_ms_per_step):
        # wall-clock intervals with NO dispatched-unmaterialized step are
        # the host-observable upper bound on device idle between steps;
        # the accumulated durations live on the registry, the window marks
        # (reset_perf_stats) stay plain timestamps
        self._span_start = None
        self._last_event = None
        self._idle_since = None
        self._w_marks = {"step_s": 0.0, "sync_s": 0.0, "gap_s": 0.0,
                         "calls": 0.0, "draft_s": 0.0}
        # round 17: resilience knobs — SLO-aware admission control (off
        # when slo is None), bounded step retry + exponential backoff,
        # and the deadline sweep (armed lazily by the first deadlined
        # request so the disarmed path pays one bool check)
        if slo is not None and not isinstance(slo, SLOConfig):
            raise ValueError(f"slo must be an SLOConfig or None, "
                             f"got {type(slo).__name__}")
        self.slo = slo
        # round 18: fleet identity + liveness stamp — ``replica_id``
        # names this predictor in a fleet's healthz feeds, and
        # ``_last_round_end`` (bumped every completed step()/flush()
        # round) is the monotonic progress mark behind healthz's
        # ``snapshot_age_s``: a STUCK replica's age grows while a merely
        # QUIET one, still being driven, keeps stamping fresh snapshots
        self.replica_id = int(replica_id)
        if self.replica_id < 0:
            raise ValueError(f"replica_id must be >= 0, got {replica_id}")
        # round 20: disaggregation identity — the fleet role this
        # predictor plays ("prefill" runs prompts and streams KV pages
        # out; "decode" receives pages and serves the decode phase;
        # "colocated" is the single-role default — the predictor itself
        # behaves identically in all three, the label steers the fleet
        # router) and the sender-side transfer backlog (unacked KV-page
        # frames originating here, stamped by the router's transfer
        # drive) the healthz surface exposes for role-aware scoring
        if role not in ("colocated", "prefill", "decode"):
            raise ValueError(f"role must be 'colocated', 'prefill' or "
                             f"'decode', got {role!r}")
        self.role = role
        self.transfer_backlog = 0
        self._last_round_end = monotonic()
        self.max_step_retries = int(max_step_retries)
        if self.max_step_retries < 0:
            raise ValueError(f"max_step_retries must be >= 0, "
                             f"got {max_step_retries}")
        self.retry_backoff_s = float(retry_backoff_s)
        self._deadlines_armed = False
        self._consec_failures = 0
        self._ttft_ema_ms: float | None = None
        # round 19: predictor-level draft-acceptance EMA (healthz exposes
        # it so the fleet router can score spec-effective replicas)
        self._accept_ema: float | None = None
        # req_id -> DraftProposer (kept across preemption — the request's
        # context replays identically, so the table stays consistent)
        self._drafts: dict[int, object] = {}
        # req_id -> recorder generation of its recorded lane 'b' (tracing
        # only): a lane is OPEN iff its generation matches the recorder's
        # CURRENT one — a window clear discards recorded begins, so a
        # stale entry means "re-open before emitting" (each RECORD window
        # must be self-consistent: no 'n'/'e' without an in-window 'b')
        self._traced_reqs: dict[int, int] = {}

    def _init_instruments(self):
        """Declare this predictor's registry instruments (round 15). The
        names are the snapshot/telemetry schema ARCHITECTURE.md documents;
        the back-compat properties below read them."""
        m = self.metrics
        self._m_steps = m.counter(
            "serving_steps", "scheduler rounds that dispatched a step")
        self._m_step_calls = m.counter(
            "serving_step_calls", "step() invocations (perf-window unit)")
        self._m_tokens = m.counter(
            "serving_tokens_emitted", "tokens emitted, all paths")
        self._m_hard_syncs = m.counter(
            "serving_hard_syncs", "step()/flush() calls that materialized")
        self._m_steady = m.counter(
            "serving_steady_hits", "async steady-decode pack-cache hits")
        self._m_preempt = m.counter(
            "serving_preemptions", "requests preempted back to the queue")
        self._m_admitted = m.counter(
            "serving_requests_admitted", "admissions incl. replay")
        self._m_finished = m.counter(
            "serving_requests_finished", "requests reaching FINISHED")
        self._m_step_s = m.counter(
            "serving_step_seconds", "host wall seconds inside step()/flush()")
        self._m_sync_s = m.counter(
            "serving_sync_seconds", "seconds blocked materializing outputs")
        self._m_gap_s = m.counter(
            "serving_gap_seconds", "wall seconds with no step in flight")
        self._m_ttft = m.histogram(
            "serving_ttft_ms", "submit -> first generated token",
            buckets=(1, 2, 5, 10, 25, 50, 100, 250, 1000, 5000))
        self._m_inflight = m.gauge(
            "serving_inflight_depth", "dispatched-unreconciled steps")
        self._m_running = m.gauge(
            "serving_running_lanes", "slots in RUNNING after a step")
        self._m_waiting = m.gauge(
            "serving_waiting_requests", "queued requests after a step")
        # speculative decoding: per completing DECODE lane-step
        self._m_spec_lane_steps = m.counter(
            "serving_spec_lane_steps", "decode lane-steps while spec is on")
        self._m_spec_emitted = m.counter(
            "serving_spec_tokens_emitted", "tokens emitted by spec lanes")
        self._m_draft_proposed = m.counter(
            "serving_draft_proposed", "draft tokens proposed")
        self._m_draft_accepted = m.counter(
            "serving_draft_accepted", "draft tokens accepted by verify")
        self._m_draft_rollback = m.counter(
            "serving_draft_rollback_pages", "over-allocated pages trimmed")
        # round 19: the model-based draft source + async x spec
        self._m_draft_model_steps = m.counter(
            "serving_draft_model_steps",
            "draft-model jit launches (catch-up chunks + chain steps)")
        self._m_draft_src = m.counter(
            "serving_draft_tokens_proposed",
            "draft tokens proposed, by source", labels=("source",))
        self._m_spec_deferred = m.counter(
            "serving_spec_async_deferred_steps",
            "spec-build dispatches reconciled behind-by-one or deferred")
        self._m_draft_s = m.counter(
            "serving_draft_seconds",
            "host wall seconds inside the draft-model proposal pass")
        # round 17: resilience — shed / deadline / fault / retry counters
        self._m_failed = m.counter(
            "serving_requests_failed", "requests reaching terminal FAILED")
        self._m_fail_reasons = m.counter(
            "serving_fail_reasons", "terminal failures by error code",
            labels=("reason",))
        self._m_shed = m.counter(
            "serving_requests_shed", "admissions shed by the SLO policy")
        self._m_deadline = m.counter(
            "serving_deadline_misses", "requests failed past their deadline")
        self._m_step_failures = m.counter(
            "serving_step_failures", "pack/dispatch/reconcile exceptions")
        self._m_retries = m.counter(
            "serving_step_retries", "lane requeues after a failed step")
        self._m_faults = m.counter(
            "serving_faults_injected", "injected faults observed, by seam",
            labels=("seam",))

    # -- back-compat metric reads (pre-round-15 attribute surface) ---------

    @property
    def steps(self) -> int:
        return int(self._m_steps.value)

    @property
    def tokens_emitted(self) -> int:
        return int(self._m_tokens.value)

    @property
    def hard_syncs(self) -> int:
        return int(self._m_hard_syncs.value)

    @property
    def steady_hits(self) -> int:
        return int(self._m_steady.value)

    @property
    def spec_lane_steps(self) -> int:
        return int(self._m_spec_lane_steps.value)

    @property
    def spec_emitted(self) -> int:
        return int(self._m_spec_emitted.value)

    @property
    def spec_proposed(self) -> int:
        return int(self._m_draft_proposed.value)

    @property
    def spec_accepted(self) -> int:
        return int(self._m_draft_accepted.value)

    def telemetry(self) -> dict[str, float]:
        """Flat snapshot of the serving-stack registry (predictor + KV
        cache instruments) — the ``telemetry`` sub-object bench_serve
        rides on its JSON lines."""
        return self.metrics.snapshot_flat()

    # -- queue API ---------------------------------------------------------

    def add_request(self, prompt_ids, max_new_tokens=32, eos_token_id=None,
                    temperature=0.0, top_k=0, top_p=1.0, seed=None,
                    deadline_s=None, submit_time=None,
                    sample_offset=0) -> Request:
        req = Request(prompt_ids, max_new_tokens, eos_token_id,
                      temperature=temperature, top_k=top_k, top_p=top_p,
                      seed=seed, deadline_s=deadline_s,
                      submit_time=submit_time, sample_offset=sample_offset)
        if len(req.prompt_ids) > self.max_seq_len:
            raise ValueError(
                f"prompt of {len(req.prompt_ids)} tokens exceeds "
                f"max_seq_len {self.max_seq_len}")
        if self.slo is not None:
            verdict = self.admission_verdict()
            if verdict is not None:
                # shed: the request comes back terminal FAILED with a
                # loud error record instead of queueing into an overload
                self._m_shed.inc()
                self._fail(req, "shed_" + verdict,
                           f"admission shed under load ({verdict}): "
                           f"{len(self.waiting)} waiting, "
                           f"{len(self.running)} running")
                return req
        if req.deadline_s is not None:
            self._deadlines_armed = True
        self.waiting.append(req)
        return req

    # -- round 17: load-signal surface (the fleet router's view) -----------

    @property
    def pool_occupancy(self) -> float:
        """Claimed fraction of the KV page pool (evictable prefix-LRU
        pages count as available)."""
        cache = self.cache
        return 1.0 - cache.available_page_count / max(1, cache.num_pages)

    @property
    def ttft_p99_ema_ms(self) -> float:
        """EMA over the TTFT histogram's p99 estimate (0.0 before the
        first token) — the SLO shedding signal."""
        return 0.0 if self._ttft_ema_ms is None else self._ttft_ema_ms

    def admission_verdict(self) -> str | None:
        """Would :meth:`add_request` shed right now? ``None`` admits;
        otherwise the shed reason (``queue_full`` / ``pool_pressure`` /
        ``inflight_depth`` / ``ttft_slo``). Pure read — the fleet router
        polls this (and :meth:`healthz`) to steer traffic before paying
        a request submission."""
        slo = self.slo
        if slo is None:
            return None
        if (slo.max_waiting is not None
                and len(self.waiting) >= slo.max_waiting):
            return "queue_full"
        # backlog-gated signals: a full pool with an empty queue is the
        # healthy steady state of a saturated batch, not an overload
        if self.waiting:
            if (slo.max_pool_occupancy is not None
                    and self.pool_occupancy >= slo.max_pool_occupancy):
                return "pool_pressure"
            if (slo.max_inflight_depth is not None
                    and len(self._inflight) >= slo.max_inflight_depth):
                return "inflight_depth"
            if (slo.ttft_p99_slo_ms is not None
                    and self.ttft_p99_ema_ms > slo.ttft_p99_slo_ms):
                return "ttft_slo"
        return None

    def healthz(self) -> dict:
        """One JSON-able health/load snapshot — the per-predictor surface
        the fleet router consumes (schema locked by
        tests/test_observability.py)."""
        verdict = self.admission_verdict()
        cache = self.cache
        return {
            "status": "shedding" if verdict is not None else "ok",
            "shed_reason": verdict,
            # round 18: fleet identity + staleness — seconds since the
            # last COMPLETED scheduler round; a router distinguishes a
            # stale/stuck replica (age grows without bound) from a quiet
            # one (its driver keeps stepping it, age stays small)
            "replica_id": self.replica_id,
            # round 20: the disaggregation role + the sender-side
            # unacked-frame backlog (the router's prefill-scoring and
            # drain signals)
            "role": self.role,
            "transfer_backlog": int(self.transfer_backlog),
            "snapshot_age_s": round(
                max(0.0, monotonic() - self._last_round_end), 6),
            "waiting": len(self.waiting),
            "running": len(self.running),
            "inflight_steps": len(self._inflight),
            "free_slots": cache.free_slot_count,
            "pool_occupancy": round(self.pool_occupancy, 4),
            "withheld_pages": cache.withheld_page_count,
            # round 21: the host tier under the HBM pool — byte-budget
            # occupancy (0.0 when no tier) + absolute bytes resident
            "host_tier_occupancy": round(cache.host_tier_occupancy, 4),
            "host_tier_bytes": int(cache.host_tier_bytes_used),
            "ttft_p99_ema_ms": round(self.ttft_p99_ema_ms, 3),
            # round 19: the draft-acceptance EMA — a router scoring
            # replicas can prefer ones whose speculation is paying off
            "spec_accept_ema": round(self.spec_accept_ema, 4),
            "steps": self.steps,
            "tokens_emitted": self.tokens_emitted,
            "requests_shed": int(self._m_shed.value),
            "deadline_misses": int(self._m_deadline.value),
            "requests_failed": int(self._m_failed.value),
            "step_failures": int(self._m_step_failures.value),
            "step_retries": int(self._m_retries.value),
        }

    @property
    def decode_trace_count(self) -> int:
        """Times the serving step has been (re)traced — the no-retrace
        gate asserts this stays constant after warmup. Unified mode counts
        the ONE unified step; legacy counts the decode jit."""
        fn = self._unified if self.unified else self._decode
        return fn.trace_count[0]

    @property
    def prefill_trace_count(self) -> int:
        """Times a prefill program was traced. The unified step has NO
        separate prefill jit (always 0); the legacy path compiles one
        executable per prompt-length bucket — this makes that count
        visible (bench_serve reports + gates it)."""
        return 0 if self.unified else self._prefill.trace_count[0]

    @property
    def prefix_hit_rate(self) -> float:
        return self.cache.prefix_hit_rate

    @property
    def accepted_tokens_per_step(self) -> float:
        """Tokens emitted per completing decode lane-step — the
        speculation multiplier (1.0 = plain decode: one token per lane
        per step; > 1.0 = accepted drafts amortizing each weight-read
        over multiple tokens)."""
        if not self.spec_lane_steps:
            return 1.0
        return self.spec_emitted / self.spec_lane_steps

    @property
    def draft_acceptance_rate(self) -> float:
        """Fraction of proposed draft tokens the verify pass accepted."""
        if not self.spec_proposed:
            return 0.0
        return self.spec_accepted / self.spec_proposed

    @property
    def draft_overhead_frac(self) -> float:
        """Fraction of the measured window's step() wall time spent in
        the draft-model proposal pass (0.0 for the n-gram source — its
        table lookups are noise) — what the model drafter costs against
        the accepted tokens it buys."""
        step = self._window("step_s", self._m_step_s)
        if step <= 0:
            return 0.0
        return min(1.0, self._window("draft_s", self._m_draft_s) / step)

    @property
    def spec_accept_ema(self) -> float:
        """EMA over per-step draft acceptance fractions (0.0 before any
        drafted step) — the healthz signal a fleet router scores
        spec-effective replicas by."""
        return 0.0 if self._accept_ema is None else self._accept_ema

    def _note_draft_launch(self) -> None:
        """One draft-engine jit launch: counted, and marked as a dispatch
        so the gap accounting knows the device has draft work (the chain
        runs while the host packs the verify step around it)."""
        self._m_draft_model_steps.inc()
        self._mark_dispatch()

    # -- perf accounting (the round-13 bench metrics) ----------------------

    def _mark_dispatch(self) -> None:
        """A step was dispatched: any interval since the pipeline last
        drained was a host-side bubble the device could not fill."""
        now = monotonic()
        if self._span_start is None:
            self._span_start = now
        if self._idle_since is not None:
            self._m_gap_s.inc(now - self._idle_since)
            self._idle_since = None
        self._last_event = now

    def _mark_drained(self) -> None:
        """No dispatched-unmaterialized work remains: the device has
        nothing of ours to run until the next dispatch."""
        now = monotonic()
        self._idle_since = now
        self._last_event = now

    def _window(self, key: str, counter) -> float:
        """A duration counter's accumulation since the last
        :meth:`reset_perf_stats` (the bench measurement window)."""
        return max(0.0, counter.value - self._w_marks[key])

    @property
    def step_gap_frac(self) -> float:
        """Fraction of the measured window with NO step in flight — the
        host-observable upper bound on the device-idle gap between steps
        (the sync engine's pack/bookkeeping bubble; ~0 for the async
        engine, which always has the next step dispatched before it
        materializes the previous one). Window starts at the first
        dispatch after :meth:`reset_perf_stats`."""
        if self._span_start is None or self._last_event is None:
            return 0.0
        window = self._last_event - self._span_start
        if window <= 0:
            return 0.0
        return min(1.0, self._window("gap_s", self._m_gap_s) / window)

    @property
    def host_ms_per_step(self) -> float:
        """Host milliseconds spent per ``step()`` OUTSIDE the blocking
        device waits — the scheduling/bookkeeping cost the async engine
        overlaps with device execution."""
        calls = self._window("calls", self._m_step_calls)
        if not calls:
            return 0.0
        busy = (self._window("step_s", self._m_step_s)
                - self._window("sync_s", self._m_sync_s))
        return max(0.0, busy * 1e3 / calls)

    def reset_perf_stats(self) -> None:
        """Start a fresh measurement window (bench: call after warmup).
        The registry counters are monotonic; the window is their delta
        against the marks taken here."""
        self._span_start = None
        self._last_event = None
        self._idle_since = None if self._inflight else monotonic()
        if self._idle_since is not None:
            self._span_start = self._idle_since
            self._last_event = self._idle_since
        self._w_marks = {"step_s": self._m_step_s.value,
                         "sync_s": self._m_sync_s.value,
                         "gap_s": self._m_gap_s.value,
                         "calls": self._m_step_calls.value,
                         "draft_s": self._m_draft_s.value}

    # -- shared scheduler internals ----------------------------------------

    def _preempt_youngest(self) -> bool:
        """Free the youngest running request back to the waiting queue."""
        if not self.running:
            return False
        slot = max(self.running,
                   key=lambda s: self.running[s].req_id)
        req = self.running.pop(slot)
        self.cache.free(slot)
        req.state = WAITING
        req.preempt_count += 1
        req._registered = False   # fresh pages on replay; re-register
        self.waiting.appendleft(req)
        self._m_preempt.inc()
        self._req_event(req.req_id, "preempt",
                        args={"count": req.preempt_count})
        return True

    def _close_request(self, req: Request, event: str, args) -> None:
        """Terminal teardown shared by BOTH terminal paths: drop
        per-request scheduler state (a retained n-gram table or PRNG key
        would leak per request over a long-lived predictor) and close the
        request's async trace lane (_req_event (re-)opens it if this
        window has no 'b' yet)."""
        self._base_keys.pop(req.req_id, None)
        self._drafts.pop(req.req_id, None)
        if self._draft_engine is not None:
            # the draft KV lane goes with the request (preemption KEEPS
            # it — the replayed context self-heals against the pool)
            self._draft_engine.release(req.req_id)
        if tracing_active():
            self._req_event(req.req_id, event, args=args)
            request_end(req.req_id)
        self._traced_reqs.pop(req.req_id, None)

    def _count_finished(self, req: Request) -> None:
        """Increment the finished counter once per request, and only once
        its emissions are VALUE-final (no dispatched-unmaterialized
        tokens): a count-finished request whose final tokens are lost
        with a dropped ring entry re-opens for replay, and its eventual
        terminal state may be FAILED — counting early would make
        finished + failed overshoot the requests submitted."""
        if not req._finish_counted and req._pending_n == 0:
            req._finish_counted = True
            self._m_finished.inc()

    def _finish(self, req: Request) -> None:
        """Mark FINISHED — EVERY finish path must come through here."""
        req.state = FINISHED
        self._count_finished(req)
        self._close_request(req, "eos" if not req.truncated
                            else "truncated",
                            {"outputs": len(req.output_ids)})

    def _fail(self, req: Request, code: str, message) -> None:
        """Terminal FAILED with a loud error record — EVERY failure path
        (shed, deadline, never-admittable, retry-exhausted, stuck) comes
        through here; the predictor keeps serving everyone else. The
        caller releases any slot/pages the request held FIRST."""
        req.state = FAILED
        req.error = {"code": code, "message": str(message)[:300]}
        self._m_failed.inc()
        self._m_fail_reasons.labels(reason=code).inc()
        self._close_request(req, "failed", dict(req.error))

    def _retire_finished(self) -> None:
        for slot in [s for s, r in self.running.items() if r.done]:
            req = self.running.pop(slot)
            self.cache.free(slot)
            self._finish(req)

    def _finish_waiting_unservable(self, req: Request) -> bool:
        """Queue-head checks shared by both admission paths. Returns True
        when the request was consumed (finished) off the queue."""
        if req.done:
            # finished while waiting (e.g. budget satisfied by its prefill
            # token before a preemption parked it)
            self.waiting.popleft()
            self._finish(req)
            return True
        if req._ctx_len > self.max_seq_len:
            # preempted while sitting AT the length ceiling (its own
            # truncation check never ran that round): finish it as
            # truncated, same as the in-loop ceiling stop
            self.waiting.popleft()
            req.truncated = True
            self._finish(req)
            return True
        return False

    def _fail_never_admittable(self, req: Request, need: int) -> None:
        """A context that can NEVER fit the pool fails individually (loud
        error record) instead of poisoning the predictor for everyone
        (the pre-round-17 behavior raised out of step()). The caller has
        already popped ``req`` off the waiting queue."""
        self._fail(req, "never_admittable",
                   f"context of {len(req._context_ids())} tokens needs "
                   f"{need} pages but the pool only has "
                   f"{self.cache.num_pages} — raise num_pages or "
                   "page_size")

    def _shed_expired(self) -> None:
        """The deadline sweep (one scheduler round granularity): expired
        WAITING requests shed off the queue (the queue TTL); RUNNING
        requests past deadline retire — both terminal FAILED
        ``deadline_exceeded``. Runs only once a deadlined request has
        ever been submitted."""
        now = monotonic()
        if any(r.deadline_s is not None for r in self.waiting):
            keep: deque[Request] = deque()
            while self.waiting:
                req = self.waiting.popleft()
                if req.past_deadline(now):
                    self._m_deadline.inc()
                    self._fail(req, "deadline_exceeded",
                               f"queued past its {req.deadline_s}s "
                               "deadline")
                else:
                    keep.append(req)
            self.waiting = keep
        for slot in [s for s, r in self.running.items()
                     if r.past_deadline(now)]:
            req = self.running.pop(slot)
            self.cache.free(slot)
            self._m_deadline.inc()
            self._fail(req, "deadline_exceeded",
                       f"still running past its {req.deadline_s}s "
                       f"deadline with {len(req.output_ids)} tokens out")

    def has_work(self) -> bool:
        return bool(self.waiting or self.running or self._inflight)

    # -- unified path ------------------------------------------------------

    def _admit_one_unified(self, req: Request) -> bool:
        """Claim a slot + pages (prefix-cache hits attach shared pages);
        the context feeds through chunks in subsequent steps."""
        # vLLM-style watermark: with other sequences running, keep one
        # free page of growth headroom — an exactly-fitting admission
        # would be preempted (its prefill work discarded) by the same
        # step's growth pass
        headroom = 1 if self.running else 0
        hit = self.cache.admit_prefix(req._context_ids(),
                                      headroom=headroom, soft=True)
        if hit is None:
            return False
        slot, cached = hit
        req.cached_prefix_len = cached
        req.state = RUNNING
        self.running[slot] = req
        self._note_admit(req, slot, cached)
        return True

    def _note_admit(self, req, slot, cached) -> None:
        """Telemetry for one (re-)admission: counter + the request's
        async trace lane ('b' once per window; replays get an instant)."""
        self._m_admitted.inc()
        if not tracing_active():
            return
        already_open = self._lane_open(req.req_id)
        self._req_event(req.req_id, "readmit" if already_open else "admit",
                        args={"slot": slot, "cached_prefix": int(cached)})

    def _lane_open(self, req_id) -> bool:
        return self._traced_reqs.get(req_id) == _recorder.generation

    def _req_event(self, req_id, name, args=None) -> None:
        """An instant on one request's trace lane. The lane must be open
        IN THE CURRENT RECORDER WINDOW — a 'b' recorded before a window
        clear is gone from the buffer, and an 'n'/'e' without its 'b'
        renders as an unmatched phase — so a stale (or absent) lane is
        (re-)opened here first: every window's trace is self-consistent
        and a request spanning windows appears in each of them."""
        if not tracing_active():
            return
        if not self._lane_open(req_id):
            if request_begin(req_id, args={"req_id": req_id}):
                self._traced_reqs[req_id] = _recorder.generation
        request_event(req_id, name, args=args)

    def _admit_waiting_unified(self) -> None:
        while self.waiting and self.cache.free_slot_count:
            req = self.waiting[0]
            if self._finish_waiting_unservable(req):
                continue
            if not self._admit_one_unified(req):
                # head-of-line blocking keeps FIFO order — but if nothing
                # is running and the whole pool is free, this request can
                # NEVER fit: fail IT (not the predictor) with the real
                # cause and keep admitting behind it
                if (not self.running and self.cache.available_page_count
                        == self.cache.num_pages):
                    self.waiting.popleft()
                    self._fail_never_admittable(
                        req, self.cache.pages_needed(
                            len(req._context_ids())))
                    continue
                break
            self.waiting.popleft()

    def _req_key(self, req: Request) -> np.ndarray:
        """Per-request base PRNG key; the per-token key folds in the count
        of tokens produced, so a preemption replay re-samples the same
        stream."""
        hit = self._base_keys.get(req.req_id)
        if hit is None:
            import jax

            hit = np.asarray(jax.random.PRNGKey(req.seed), np.uint32)
            self._base_keys[req.req_id] = hit
        return hit

    def _proposer_for(self, req: Request):
        """The request's draft proposer (created on first use; persists
        across preemption replay so the adaptive-k EMA AND the cooldown
        re-probe state survive — round-19 satellite: a replay must resume
        the backoff where it left off, not restart from the floor)."""
        prop = self._drafts.get(req.req_id)
        if prop is None:
            from .draft import DraftProposer, ModelDraftProposer

            if self._draft_engine is not None:
                prop = ModelDraftProposer(self.spec_k, self._draft_engine,
                                          req.req_id)
            else:
                prop = DraftProposer(self.spec_k)
            self._drafts[req.req_id] = prop
        return prop

    def _proposer_k(self, req: Request) -> int:
        """The lane's CURRENT adaptive speculation length without
        creating a proposer (a fresh request starts optimistic at the
        build k)."""
        prop = self._drafts.get(req.req_id)
        return prop.k if prop is not None else self.spec_k

    def _draft_room(self, slot, req, budget_room: int) -> int:
        """The per-lane draft clamp shared by both sources: the token
        budget, the per-slot chunk block, the request's remaining output
        budget, the length ceiling, and — via ``draft_allowance`` — pages
        claimable WITHOUT evicting prefix pages or preempting anyone
        (rejected drafts must cost nothing). Re-checked at claim time in
        the capacity loop; this propose-time clamp only avoids wasted
        draft work."""
        written = self.cache.seq_len(slot)
        return min(budget_room, self._proposer_k(req), self.chunk - 1,
                   req.max_new_tokens - len(req.output_ids) - 1,
                   self.max_seq_len - written - 1,
                   self.cache.draft_allowance(slot))

    def _draft_propose(self, slot, req, budget_room: int) -> list:
        """N-gram drafts for one decode lane (the model source batches
        through :meth:`_propose_model_drafts` instead)."""
        prop = self._proposer_for(req)
        room = self._draft_room(slot, req, budget_room)
        return prop.propose(req._context_ids(), room) if room > 0 else []

    def _propose_model_drafts(self, decode_slots, budget: int) -> dict:
        """ONE batched draft-engine pass for every decode lane that may
        speculate this round: per-lane rooms follow the n-gram path's
        sequential budget split (each lane's base token reserved before
        anyone's drafts), then the engine catch-up + k-step chain runs
        all lanes together — k draft jit launches per ROUND, not per
        lane, with the intermediate tokens device-resident. Contexts are
        value-complete here: the round-start reconcile landed any
        in-flight token of a lane whose proposer still speculates."""
        lanes: dict[int, tuple] = {}
        n_left = len(decode_slots)
        for slot in decode_slots:
            n_left -= 1
            room = budget - 1 - n_left
            req = self.running[slot]
            self._proposer_for(req)
            r = self._draft_room(slot, req, room)
            budget -= 1
            if r > 0:
                lanes[slot] = (req.req_id, req._context_ids(), r)
                budget -= r
        if not lanes:
            return {}
        t0 = monotonic()
        try:
            return self._draft_engine.propose(lanes)
        finally:
            self._m_draft_s.inc(monotonic() - t0)

    @staticmethod
    def _merge_produced(dst: dict, src: dict) -> None:
        for rid, toks in src.items():
            dst.setdefault(rid, []).extend(toks)

    @staticmethod
    def _landed_done(req: Request) -> bool:
        """``done`` over MATERIALIZED tokens only — the emission drop
        rule. Deliberately ignores pending counts (they are what is being
        landed) and the truncation flag (a truncation decision at pack
        N+1 must not discard the legitimate token step N produced —
        matching the sync engine, where that token landed a step before
        the truncation check ran)."""
        return stream_done(req.output_ids, req.max_new_tokens,
                           req.eos_token_id)

    def _put_cached(self, name: str, arr: np.ndarray):
        """Content-keyed device-upload cache for slowly-changing per-step
        arrays (sampling params, per-lane base keys): a steady greedy
        churn re-serves the same device array with zero H2D traffic."""
        import jax

        hit = self._feed_cache.get(name)
        if hit is not None and np.array_equal(hit[0], arr):
            return hit[1]
        host = arr.copy()   # private: the caller's buffer may mutate
        dev = jax.device_put(host)
        self._feed_cache[name] = (host, dev)
        return dev

    def flush(self) -> dict[int, list[int]]:
        """Materialize every in-flight step (the async engine's OUTPUT
        FLUSH — a hard sync boundary). Returns the landed tokens merged
        in emission order; no-op for the sync engine / legacy path."""
        t0 = monotonic()
        self._did_sync = False
        try:
            with span("flush"):
                out = self._reconcile_all()
                # round 19: a drained spec advance may complete a prompt
                # whose tail page registration was one round short (the
                # behind-by-one dispatch) — finish it so post-flush state
                # matches the sync engine's exactly
                self._register_prefixes()
                return out
        finally:
            if self._did_sync:
                self._m_hard_syncs.inc()
            self._m_step_s.inc(monotonic() - t0)
            self._last_round_end = monotonic()

    def _reconcile_all(self) -> dict[int, list[int]]:
        produced: dict[int, list[int]] = {}
        # bounded by the ring depth at entry (round 17): every iteration
        # pops exactly one entry (or a failure recovery clears the ring),
        # so a drain can never spin past the work that existed when it
        # started
        for _ in range(len(self._inflight)):
            if not self._inflight:
                break
            self._merge_produced(produced, self._reconcile_one())
        assert not self._inflight, "reconcile drain left ring entries"
        return produced

    def _reconcile_one(self) -> dict[int, list[int]]:
        """Land the OLDEST in-flight step's deferred results: materialize
        its emission outputs (the hard sync), append tokens / TTFT /
        metrics, and settle the value-dependent cache accounting
        (speculative advance + rollback). Count-based accounting (page
        growth, plain advance, prefix registration) already ran at pack
        time — this is the reconcile-behind half of the contract.

        Exception-safe (round 17): a materialization failure drops the
        popped entry AND everything younger (they consumed its device
        carry), un-charges their dispatched-unmaterialized tokens, and
        requeues every affected lane through the preemption-replay path
        — see :meth:`_recover_reconcile_failure`."""
        with span("reconcile"):
            e = self._inflight.popleft()
            self._m_inflight.set(len(self._inflight))
            # sample the ring-depth track on the way DOWN too — a trace
            # of a drain (flush) must show the ring emptying
            counter_event("inflight_steps", len(self._inflight))
            try:
                return self._reconcile_one_impl(e)
            except Exception as exc:
                # EVERY Exception is owned by the recovery (a host-side
                # code bug is indistinguishable from a device fault here;
                # the bounded retry keeps either from looping forever and
                # the error record carries repr(exc) for attribution)
                self._recover_reconcile_failure(e, exc)
                return {}

    def _note_first_token(self, req: Request) -> None:
        req.first_token_time = monotonic()
        self._m_ttft.observe((req.first_token_time - req.submit_time) * 1e3)
        # TTFT-p99 EMA (the round-17 shedding signal): smooth the
        # histogram's p99 estimate so one straggler neither trips nor
        # un-trips the SLO verdict on its own
        a = self.slo.ema_alpha if self.slo is not None else 0.2
        p99 = self._m_ttft.quantile(0.99)
        self._ttft_ema_ms = (p99 if self._ttft_ema_ms is None
                             else (1 - a) * self._ttft_ema_ms + a * p99)
        self._req_event(req.req_id, "first_token")

    def _reconcile_one_impl(self, e: _Pending) -> dict[int, list[int]]:
        cache = self.cache
        out = ne = None
        if e.completing:
            fault_point("reconcile")
            t0 = monotonic()
            out = np.asarray(e.out)
            if e.spec and e.spec_slots:
                # n_emit only matters when some lane actually drafted (a
                # draftless spec round emits exactly 1 per lane)
                ne = np.asarray(e.ne)
            self._m_sync_s.inc(monotonic() - t0)
            self._did_sync = True
        if not self._inflight:
            self._mark_drained()
        for slot in e.spec_slots:
            # speculative lane: the context token + accepted drafts are
            # the valid K/V; rejected drafts' over-allocated pages roll
            # back to the pool (refcounts/free lists end identical to a
            # never-speculated run)
            cache.advance(slot, int(ne[slot]))
            self._m_draft_rollback.inc(cache.trim_pages(slot))
        produced: dict[int, list[int]] = {}
        for slot, req, k_i, was_decode in e.completing:
            if e.spec:
                m = int(ne[slot]) if k_i else 1
                toks = [int(x) for x in out[slot, :m]]
            else:
                toks = [int(out[slot])]
            emitted = 0
            for tok in toks:
                if req.state == FAILED or self._landed_done(req):
                    # budget/eos hit mid-batch (drop the overhang), or
                    # the request failed with tokens in flight (deadline
                    # retire): its late emissions are discarded
                    break
                req.output_ids.append(tok)
                emitted += 1
                if req.first_token_time is None:
                    self._note_first_token(req)
                produced.setdefault(req.req_id, []).append(tok)
            # the pack charged ONE pending token per completing lane
            # (plain AND spec since round 19); it just landed — a spec
            # lane's extra accepted tokens are a same-instant surplus
            req._pending_n = max(0, req._pending_n - 1)
            if req.state == FINISHED:
                # a count-finished request's deferred finished-counter
                # lands with its final token values
                self._count_finished(req)
            self._m_tokens.inc(emitted)
            if self.spec_k and was_decode:
                acc = int(ne[slot]) - 1 if k_i else 0
                self._m_spec_lane_steps.inc()
                self._m_spec_emitted.inc(emitted)
                self._m_draft_proposed.inc(k_i)
                self._m_draft_src.labels(source=self.draft_source).inc(k_i)
                self._m_draft_accepted.inc(acc)
                if k_i:
                    # predictor-level acceptance EMA (healthz surface)
                    frac = acc / k_i
                    self._accept_ema = (
                        frac if self._accept_ema is None
                        else 0.8 * self._accept_ema + 0.2 * frac)
                    self._req_event(req.req_id, "spec_accept",
                                    args={"proposed": k_i, "accepted": acc})
                prop = self._drafts.get(req.req_id)
                if prop is not None:
                    prop.update(k_i, acc)
        return produced

    # -- round 17: crash-consistent step retry -----------------------------

    def _note_step_failure(self, exc) -> None:
        self._m_step_failures.inc()
        self._consec_failures += 1
        if isinstance(exc, InjectedFault):
            self._m_faults.labels(seam=exc.seam).inc()

    def _after_failure_backoff(self) -> None:
        """Exponential backoff after a failed step (consecutive failures
        double it, capped at 1s); a successful dispatch resets it. Only
        ever runs on the failure path."""
        if self.retry_backoff_s > 0:
            time.sleep(min(
                self.retry_backoff_s * (2 ** (self._consec_failures - 1)),
                1.0))

    def _requeue_req(self, req: Request, exc, code: str) -> None:
        """THE bounded-retry policy (one site): bump the request's
        failure-requeue count, FAIL it past ``max_step_retries``,
        otherwise send it back through the value-barriered
        preemption-replay path. The caller has already released any
        slot/pages/ring charge the request held."""
        req._registered = False
        req.retry_count += 1
        if req.retry_count > self.max_step_retries:
            self._fail(req, code,
                       f"step failed {req.retry_count} times over this "
                       f"request; last: {exc!r}")
            return
        req.state = WAITING
        self._m_retries.inc()
        self._req_event(req.req_id, "retry",
                        args={"count": req.retry_count})
        self.waiting.appendleft(req)

    def _requeue_one(self, slot: int, exc,
                     code: str = "step_retry_exhausted") -> None:
        """Requeue one running lane through the preemption-replay path
        after a failed step: ``free()`` returns its growth/CoW page
        claims exactly (shared and registered pages stay pinned by their
        other references), and the replay is value-barriered and
        bit-identical. Bounded: past ``max_step_retries`` the request
        FAILS instead."""
        req = self.running.pop(slot)
        self.cache.free(slot)
        if req.done and req._pending_n == 0:
            # its landed output is already value-final (e.g. eos landed
            # at an earlier reconcile, retirement hadn't run yet): there
            # is nothing to replay — retire it instead of spending a
            # retry (or worse, a spurious terminal FAIL) on a complete,
            # correct stream
            self._finish(req)
            return
        self._requeue_req(req, exc, code)

    def _requeue_running(self, exc) -> None:
        # youngest-first appendleft leaves the queue front oldest-first
        for slot in sorted(self.running,
                           key=lambda s: -self.running[s].req_id):
            self._requeue_one(slot, exc)

    def _recover_dispatch_failure(self, exc) -> None:
        """A failure inside ``_pack_dispatch`` (pack bookkeeping, H2D
        upload, or the launch itself): the entry never entered the ring
        and nothing advanced, so the transaction rolls back by requeueing
        every running lane — page/slot/prefix claims this step made are
        returned through ``free()``. Older ring entries dispatched
        healthy and stay; the requeued lanes' pending tokens force the
        value barrier to land them before any replay admission."""
        self._note_step_failure(exc)
        self._requeue_running(exc)
        self._steady = None
        self._after_failure_backoff()

    def _recover_reconcile_failure(self, e: _Pending, exc) -> None:
        """A failure materializing in-flight entry ``e``: its token
        values are lost and every YOUNGER entry consumed its device
        carry, so the whole remaining ring is poisoned — drop it all,
        un-charge the dispatched-unmaterialized tokens each dropped
        entry charged, re-open count-finished requests whose final
        tokens were in the dropped entries, and requeue every running
        lane for bit-identical replay."""
        self._note_step_failure(exc)
        dropped = [e] + list(self._inflight)
        self._inflight.clear()
        self._m_inflight.set(0)
        counter_event("inflight_steps", 0)
        reopen: dict[int, Request] = {}
        for entry in dropped:
            # round 19: spec entries charge one pending token per
            # completing lane too (behind-by-one dispatch) — un-charge
            # them exactly like plain entries
            for _slot, req, _k, _decode in entry.completing:
                req._pending_n = max(0, req._pending_n - 1)
                if req.state == FINISHED and not req.done:
                    # finished by COUNT, final token values lost with the
                    # dropped entry: back to the queue for replay
                    reopen[req.req_id] = req
                elif req.state == FINISHED:
                    # FINISHED and still done after the un-charge (eos
                    # landed earlier; the dropped token was pure
                    # overhang): its deferred finished-counter lands
                    # here — no other path will ever see it again
                    self._count_finished(req)
        self._requeue_running(exc)
        for req in reopen.values():
            # count-finished with the final token values lost: no slot
            # to free (retirement already freed it) — straight through
            # the shared bounded-retry policy
            self._requeue_req(req, exc, "step_retry_exhausted")
        self._carry = None
        self._steady = None
        self._mark_drained()
        self._after_failure_backoff()

    def _step_unified(self) -> dict[int, list[int]]:
        produced: dict[int, list[int]] = {}
        # round 19 — the behind-by-one half of async x spec: a DRAFTED
        # spec step's n_emit-variable advance/rollback (and the proposer
        # feedback + context values the next proposal depends on) must
        # land before this round schedules anything — INCLUDING the
        # deadline sweep, which frees slots the in-flight entry's
        # value-based advance still references — so a ring holding
        # drafted entries reconciles HERE, one round after its dispatch,
        # instead of inside it (the pre-round-19 hard sync). A draftless
        # spec ring defers like the plain engine and only syncs when a
        # lane that would draft again has its input token still in
        # flight (its proposal needs the value-complete context).
        if self._inflight and self.spec_k and (
                any(p.spec_slots for p in self._inflight)
                or any(r._pending_n and self._proposer_k(r) > 0
                       for r in self.running.values())):
            self._merge_produced(produced, self._reconcile_all())
            # the spec advance just landed: a lane whose final prompt
            # token rode the drained verify step can only NOW register
            # its partial tail page — complete the registration the
            # behind-by-one dispatch left one round short (idempotent),
            # BEFORE this round's admissions walk the registry (the sync
            # engine registered it last round)
            self._register_prefixes()
        if self._deadlines_armed:
            self._shed_expired()
        # value barrier: admission replays a preempted request's context
        # (token VALUES), so a waiting request with pending tokens forces
        # a full reconcile before the admission pass
        if self._inflight and any(r._pending_n for r in self.waiting):
            self._merge_produced(produced, self._reconcile_all())
        self._retire_finished()
        self._admit_waiting_unified()
        if not self.running:
            self._merge_produced(produced, self._reconcile_all())
            return produced
        with span("pack_dispatch"):
            try:
                entry = self._pack_dispatch()
            except Exception as exc:
                # transactional pack: the recovery requeues every lane
                # (claims returned exactly) and the next step() retries
                self._recover_dispatch_failure(exc)
                return produced
        if entry is None:
            self._merge_produced(produced, self._reconcile_all())
            return produced
        self._consec_failures = 0
        self._inflight.append(entry)
        self._m_inflight.set(len(self._inflight))
        counter_event("inflight_steps", len(self._inflight))
        self._m_steps.inc()
        if not self.async_engine:
            # sync engine: pipeline depth zero, reconcile the step just
            # dispatched (the oracle the async engine is gated against)
            self._merge_produced(produced, self._reconcile_all())
        elif entry.spec_slots:
            # round 19: a DRAFTED spec step dispatches BEHIND-BY-ONE —
            # its value-based advance/rollback reconciles at the START
            # of the next round (see _step_unified's ring drain), so the
            # device executes the verify step while the host runs the
            # next round's bookkeeping instead of blocking right here
            # (the pre-round-19 behavior: spec forced depth zero)
            pass
        else:
            # the double-buffer contract: reconcile BEHIND-BY-ONE while
            # an emission boundary (a step whose tokens could finish a
            # request) is in the ring; steps that cannot complete
            # anything defer — up to max_inflight_steps — and drain in
            # one batched materialization later (the general
            # no-completion-possible fast path). Round 19: DRAFTLESS
            # spec-build rounds ride this path too — their emission is
            # count-deterministic (n_emit == 1), exactly a plain step
            while self._inflight and (
                    len(self._inflight) > self.max_inflight_steps
                    or (len(self._inflight) > 1
                        and any(p.must_sync
                                for p in list(self._inflight)[:-1]))):
                self._merge_produced(produced, self._reconcile_one())
        if (self.spec_k and self._inflight
                and self._inflight[-1] is entry):
            # a spec-build dispatch whose reconcile outlived this call —
            # the async x spec multiplier the round-19 bench leg gates
            self._m_spec_deferred.inc()
        self._register_prefixes()
        return produced

    def _register_prefixes(self) -> None:
        """Register prompt prefills in the prefix cache PROGRESSIVELY —
        full pages as their chunks land (a request arriving one step
        later already hits them), the partial tail once the whole prompt
        is in (its K/V writes have been issued to the device pool).
        Prompt-progress only (token counts + prompt values the host owns)
        — runs after the step's cache accounting settles."""
        cache = self.cache
        for slot, req in self.running.items():
            if req._registered:
                continue
            plen = len(req.prompt_ids)
            written = min(cache.seq_len(slot), plen)
            if written >= plen:
                cache.register_prefix(slot, req.prompt_ids)
                req._registered = True
            elif written >= cache.page_size:
                cache.register_prefix(slot, req.prompt_ids[:written],
                                      include_tail=False)

    def _pack_dispatch(self) -> _Pending | None:
        """Pack the token budget, run capacity/CoW, build the step arrays
        and DISPATCH the unified step — everything that only needs token
        COUNTS. Returns the in-flight entry (None when nothing was
        scheduled). Does not materialize any device value.

        Exception-safe (round 17): every mutation before the launch is a
        CLAIM (pages, slots, CoW copies) the caller's recovery returns
        exactly by requeueing the lanes through ``free()`` — see
        :meth:`_recover_dispatch_failure`. The named fault seams
        (``pool``/``h2d``/``slow_step``/``dispatch``) cost one
        module-global check each when no plan is armed."""
        cache = self.cache
        # -- token-budget packing: decode lanes first, then prefill chunks
        budget = self.token_budget
        sched: dict[int, int] = {}          # slot -> tokens this step
        drafts: dict[int, list] = {}        # slot -> draft tokens
        decode_slots = []
        prefill_slots = []
        for slot in sorted(self.running):
            req = self.running[slot]
            remaining = req._ctx_len - cache.seq_len(slot)
            (decode_slots if remaining == 1 else prefill_slots).append(slot)
        # round 19: the model draft source proposes every lane in ONE
        # batched engine pass (k chain launches per round, not per lane)
        model_drafts: dict[int, list] = {}
        if self.spec_k and self._draft_engine is not None and decode_slots:
            model_drafts = self._propose_model_drafts(decode_slots, budget)
        for idx, slot in enumerate(decode_slots):
            if budget <= 0:
                break
            # drafts may only spend budget left after EVERY decode lane
            # still to pack has its base token reserved — one lane's
            # speculation must not starve another lane's plain decode
            # (a tight custom token_budget would otherwise skip the same
            # trailing lanes every step)
            room = budget - 1 - (len(decode_slots) - idx - 1)
            if self._draft_engine is not None:
                d = model_drafts.get(slot, [])[:max(0, room)]
            else:
                d = (self._draft_propose(slot, self.running[slot], room)
                     if self.spec_k else [])
            if d:
                drafts[slot] = d
            sched[slot] = 1 + len(d)
            budget -= 1 + len(d)
        # prefill fills the remainder, FIFO by request age
        for slot in sorted(prefill_slots,
                           key=lambda s: self.running[s].req_id):
            if budget <= 0:
                break
            req = self.running[slot]
            remaining = req._ctx_len - cache.seq_len(slot)
            n = min(self.chunk, remaining, budget)
            if n > 0:
                sched[slot] = n
                budget -= n
        # -- capacity: ceiling stops, page growth, CoW page claims -------
        # pages every scheduled slot will claim for its PLAIN tokens
        # (chunk growth + CoW): charged against draft allowances so a
        # draft can never consume a free page a later prefill chunk in
        # this same step needs (which would push IT into LRU eviction or
        # preemption — costs a plain step never pays). Only drafted
        # steps pay the bookkeeping: its one consumer is the draft clamp
        plain_need: dict[int, int] = {}
        pending_need = 0
        if drafts:
            plain_need = {s: cache.plain_step_page_need(
                s, sched[s] - len(drafts.get(s, []))) for s in sched}
            pending_need = sum(plain_need.values())
        cows: dict[int, tuple[int, int]] = {}
        for slot in sorted(sched):
            pending_need -= plain_need.pop(slot, 0)
            if slot not in self.running:
                continue
            req = self.running[slot]
            written = cache.seq_len(slot)
            if written + 1 > self.max_seq_len:
                # length ceiling: stop NOW (truncation-stop) before any
                # write past the page-table width
                del sched[slot]
                self.running.pop(slot)
                req.truncated = True
                cache.free(slot)
                self._finish(req)
                continue
            n = min(sched[slot], self.max_seq_len - written)
            if slot in drafts:
                # AUTHORITATIVE draft clamp, at claim time: earlier slots
                # in this loop may have consumed the free pages counted
                # at propose time, and slots still to come have their
                # plain needs reserved (pending_need) — shrink the drafts
                # (ceiling included) rather than let anyone's growth
                # evict prefix pages or preempt (costs plain decode
                # never pays)
                keep = max(0, min(len(drafts[slot]), n - 1,
                                  cache.draft_allowance(
                                      slot, reserve=pending_need)))
                if keep < len(drafts[slot]):
                    drafts[slot] = drafts[slot][:keep]
                if not drafts[slot]:
                    del drafts[slot]
                n = 1 + keep
            sched[slot] = n
            while True:
                # prepare_write ALLOCATES the copy's destination page
                # right here, so a later slot's CoW can never race this
                # one for the last page — the claim IS the reservation
                if cache.ensure_capacity(slot, written + n) and (
                        not cache.needs_cow(slot, written)
                        or cache.available_page_count >= 1):
                    cow = cache.prepare_write(slot, written)
                    if cow is not None:
                        cows[slot] = cow
                    break
                # page pressure: shed the youngest request
                victim_is_self = (max(self.running,
                                      key=lambda s: self.running[s].req_id)
                                  == slot)
                if victim_is_self and len(self.running) == 1:
                    # even with the pool to itself this sequence cannot
                    # grow (transient pressure, or a genuinely undersized
                    # pool): requeue through the bounded retry path —
                    # transient pressure heals on replay, a permanent
                    # exhaustion FAILS this one request after
                    # max_step_retries while the predictor keeps serving
                    self._requeue_one(slot, RuntimeError(
                        f"slot {slot}: cannot grow to {written + n} "
                        "tokens — page pool too small for this "
                        "sequence"), code="pool_exhausted")
                    break
                self._preempt_youngest()
                if slot not in self.running:  # preempted itself
                    break
            if slot not in self.running:
                sched.pop(slot, None)
        # a preemption may have freed slots mid-loop; drop stale schedule
        sched = {s: n for s, n in sched.items() if s in self.running}
        if not sched:
            return None
        import jax

        b = self.max_batch
        # round 22: the round-16 round-content route is GONE — the mega
        # build accepts the unified step's ragged packed geometry, so
        # EVERY round (mixed prefill+decode included) runs the one
        # program that was built at construction. One fixed shape, one
        # trace, one steady-pack cache.
        decode_set = set(decode_slots)
        t = self.token_budget
        step_fn = self._unified
        spec_len = np.zeros((b,), np.int32)
        # -- steady-decode fast path (async only) ------------------------
        # when EVERY scheduled lane is a feedback decode lane (its input
        # token rides the device carry) and the schedule matches the
        # previous step's, the packed arrays are CONTENT-FREE on the host
        # side: tok_ids is overridden by feedback, and tok_slot / q_lens /
        # last_idx / emit_mask / feedback are unchanged — so the host
        # re-serves the previous step's device arrays and uploads only
        # the advancing positions (+ produced counts for the in-jit key
        # folds). The sync engine can never take this path: it must ship
        # the token VALUES every step.
        steady_sig = None
        if (self.async_engine and not drafts and not cows
                and all(n == 1 for n in sched.values())
                and all(self.running[s]._pending_n > 0 for s in sched)):
            steady_sig = tuple(
                (s, self.running[s].req_id) for s in sorted(sched))
        st = self._steady
        if steady_sig is not None and st is not None \
                and st["sig"] == steady_sig:
            self._m_steady.inc()
            completing = st["completing"]
            tok_pos = np.zeros((t,), np.int32)
            produced_n = np.zeros((b,), np.int32)
            for w_i, (slot, req, _, _) in enumerate(completing):
                tok_pos[w_i] = cache.seq_len(slot)
                produced_n[slot] = (req.sample_offset
                                    + len(req.output_ids)
                                    + req._pending_n)
            fault_point("h2d")
            d_pos, d_prod = jax.device_put((tok_pos, produced_n))
            d_ids, d_slot, d_qlens, d_last, d_fb, d_emit = (
                st["d_ids"], st["d_slot"], st["d_qlens"], st["d_last"],
                st["d_fb"], st["d_emit"])
            # round 19: a spec-build steady round re-serves the all-zero
            # spec_len device array too (steady implies no drafts)
            d_spec = st["d_spec"]
            d_cow_src = d_cow_dst = self._no_cow
            temp, top_k, top_p = st["temp"], st["top_k"], st["top_p"]
        else:
            cow_src = np.full((b,), self.cache.num_pages, np.int32)
            cow_dst = cow_src.copy()
            live_cows = False
            for slot, (src, dst) in cows.items():
                if slot in sched:
                    cow_src[slot], cow_dst[slot] = src, dst
                    live_cows = True
            # -- build the fixed-shape packed step arrays ----------------
            tok_ids = np.zeros((t,), np.int32)
            tok_slot = np.full((t,), -1, np.int32)
            tok_pos = np.zeros((t,), np.int32)
            feedback = np.zeros((t,), np.int32)
            last_idx = np.full((b,), t, np.int32)   # idle-lane sentinel
            q_lens = np.zeros((b,), np.int32)
            emit_mask = np.zeros((b,), np.int32)
            produced_n = np.zeros((b,), np.int32)
            temp = np.zeros((b,), np.float32)
            top_k = np.zeros((b,), np.int32)
            top_p = np.ones((b,), np.float32)
            completing = []   # (slot, req, k_i, was_decode)
            w = 0
            for slot in sorted(sched):
                n = sched[slot]
                req = self.running[slot]
                written = cache.seq_len(slot)
                ctx = req._context_ids()
                d = drafts.get(slot, [])
                # a speculating decode lane feeds its last context token
                # then its draft tokens at the following positions;
                # everyone else feeds the next n context tokens (decode
                # or prefill chunk). A decode lane whose input token is
                # still IN FLIGHT (async deferral) reads it from the
                # device-side carry instead — the host never
                # materialized it.
                if d:
                    tok_ids[w:w + n] = [ctx[written]] + d
                elif req._pending_n:
                    # pending > 0 only ever holds for pure decode lanes
                    # (prefill/replay contexts are value-barriered), and
                    # only the final context token can be pending —
                    # exactly the one token this lane feeds
                    feedback[w] = 1
                else:
                    tok_ids[w:w + n] = ctx[written:written + n]
                tok_slot[w:w + n] = slot
                tok_pos[w:w + n] = np.arange(written, written + n)
                # the row whose logits decide the lane's next token: the
                # FIRST verify row when speculating, else the last fed
                last_idx[slot] = w + n - 1 - len(d)
                spec_len[slot] = len(d)
                q_lens[slot] = n
                w += n
                if written + n - len(d) == req._ctx_len:
                    emit_mask[slot] = 1
                    produced_n[slot] = (req.sample_offset
                                        + len(req.output_ids)
                                        + req._pending_n)
                    temp[slot] = req.temperature
                    top_k[slot] = req.top_k
                    top_p[slot] = req.top_p
                    if req.temperature > 0:
                        self._lane_keys[slot] = self._req_key(req)
                    completing.append((slot, req, len(d),
                                       slot in decode_set))
            # -- batched upload -----------------------------------------
            # ONE device_put for the per-step volatile arrays (replacing
            # ~10 separate jnp.asarray transfers on the latency path);
            # sampling params and base keys ride the content-keyed cache,
            # the CoW sentinel and feedback constants never re-upload
            volatile = [tok_ids, tok_slot, tok_pos, q_lens, last_idx,
                        feedback, emit_mask, produced_n]
            if self.spec_k:
                volatile.append(spec_len)
            if live_cows:
                volatile += [cow_src, cow_dst]
            fault_point("h2d")
            dev = jax.device_put(tuple(volatile))
            (d_ids, d_slot, d_pos, d_qlens, d_last, d_fb, d_emit,
             d_prod) = dev[:8]
            rest = list(dev[8:])
            d_spec = rest.pop(0) if self.spec_k else None
            d_cow_src, d_cow_dst = ((rest[0], rest[1]) if live_cows
                                    else (self._no_cow, self._no_cow))
            # prime the steady-decode cache for the next step
            self._steady = (dict(sig=steady_sig, completing=completing,
                                 d_ids=d_ids, d_slot=d_slot,
                                 d_qlens=d_qlens, d_last=d_last,
                                 d_fb=d_fb, d_emit=d_emit, d_spec=d_spec,
                                 temp=temp, top_k=top_k, top_p=top_p)
                            if steady_sig is not None else None)
        # could any of this step's emissions FINISH a request? (the async
        # engine's sync-boundary predicate: eos configured, or the output
        # budget reachable by this emission — up to 1 + k_i tokens for a
        # drafted spec lane) — recomputed on the steady path too: the
        # output budget closes in as pending grows
        must_sync = any(
            req.eos_token_id is not None
            or len(req.output_ids) + req._pending_n + 1 + k_i
            >= req.max_new_tokens
            for _, req, k_i, _ in completing)
        prev = (self._carry
                if (self.async_engine and self._carry is not None)
                else self._zero_prev)
        head = (self.params, d_ids, d_slot, d_pos, d_qlens,
                cache.seq_lens_device(), d_last)
        if self.spec_k:
            head = head + (d_spec,)
        head = head + (d_fb, prev, d_emit, d_prod)
        tail = (cache.page_table_device(), d_cow_src, d_cow_dst,
                self._put_cached("keys", self._lane_keys),
                self._put_cached("temp", temp),
                self._put_cached("top_k", top_k),
                self._put_cached("top_p", top_p))
        pools = ((cache.k_pages, cache.v_pages, cache.k_scales,
                  cache.v_scales) if self.kv_quant
                 else (cache.k_pages, cache.v_pages))
        # per-lane trace instants on the request lanes (tracing only):
        # what kind of work each scheduled request got this step
        if tracing_active():
            for slot, n in sched.items():
                req = self.running.get(slot)
                if req is None:
                    continue
                kind = (("spec_verify" if spec_len[slot] else "decode")
                        if slot in decode_set else "prefill_chunk")
                self._req_event(req.req_id, kind, args={"tokens": int(n)})
        fault_point("slow_step")
        fault_point("dispatch")
        with span("dispatch"):
            res = step_fn(*head, *pools, *tail)
        self._mark_dispatch()
        if self.spec_k:
            out_dev, ne_dev, carry = res[0], res[1], res[2]
            cache.update_pages(*res[4:])
        else:
            out_dev, ne_dev, carry = res[0], None, res[0]
            cache.update_pages(*res[2:])
        self._carry = carry
        # charge the dispatched-unmaterialized token per completing lane
        # only once the launch SUCCEEDED (round 17: a failed launch must
        # leave no pending to un-charge). Round 19 generalizes the charge
        # to SPEC lanes too (n_emit-variable emission): one pending token
        # is the GUARANTEED minimum — the accepted drafts beyond it land
        # as a reconcile-time surplus the output budget absorbs exactly
        # like the sync engine's multi-token emission
        for _, req, _, _ in completing:
            req._pending_n += 1
        # count-based cache accounting at pack time: plain lanes advance
        # by what they fed; speculative lanes advance at reconcile (their
        # watermark is n_emit, a device value)
        for slot, n in sched.items():
            if not spec_len[slot]:
                cache.advance(slot, n)
        spec_slots = [s for s in sched if spec_len[s]]
        # a speculating lane always completes, so a prefill-only round
        # (completing empty) carries nothing to materialize — the entry
        # still occupies the ring so the gap accounting knows the device
        # has work
        return _Pending(out_dev if completing else None,
                        ne_dev if (completing and self.spec_k) else None,
                        completing, bool(self.spec_k), spec_slots,
                        must_sync)

    # -- legacy (round-7 two-jit) path -------------------------------------

    def _bucket(self, n: int) -> int:
        b = self.prefill_bucket
        return max(b, ((n + b - 1) // b) * b)

    def _admit_one_legacy(self, req: Request) -> bool:
        """Claim a slot + pages and prefill ``req``'s context into them."""
        ctx = req._context_ids()
        prefix, last = ctx[:-1], ctx[-1]
        # all but the LAST context token prefill; the last token becomes
        # the next decode step's input, and that step produces its
        # successor. A 1-token context has no prefix to split: prefill the
        # token itself and take the prefill's greedy argmax as the first
        # output instead.
        if not prefix:
            prefix, last = ctx, None
        need_len = len(prefix)
        headroom = 1 if self.running else 0
        if (not self.cache.can_admit(need_len)
                or self.cache.available_page_count
                < self.cache.pages_needed(need_len) + headroom):
            return False
        if len(ctx) > self.max_seq_len:
            raise ValueError(
                f"request {req.req_id}: context {len(ctx)} exceeds "
                f"max_seq_len {self.max_seq_len}")
        slot = self.cache.admit(need_len)
        self._note_admit(req, slot, 0)
        # bucket rounding must not push the prefill shape past the model's
        # position table (max_seq_len need not be a bucket multiple)
        padded = min(self._bucket(need_len), self.config.max_seq_len)
        ids = np.zeros((1, padded), np.int32)
        ids[0, :need_len] = prefix
        next_ids, _, kp, vp = self._prefill(
            self.params, jnp.asarray(ids),
            jnp.asarray([need_len], jnp.int32),
            self.cache.k_pages, self.cache.v_pages,
            self.cache.slot_pages(slot)[None])
        self.cache.update_pages(kp, vp)
        if last is None:
            # 1-token context: the prefill's greedy token IS the first
            # generated token; decode continues from it
            tok = int(np.asarray(next_ids)[0])
            req.output_ids.append(tok)
            self._m_tokens.inc()
            if req.first_token_time is None:
                self._note_first_token(req)
            self._next_token[slot] = tok
        else:
            # multi-token context (fresh prompt or preemption replay):
            # the last context token enters the next decode step, which
            # produces its not-yet-recorded successor
            self._next_token[slot] = last
        req.state = RUNNING
        self.running[slot] = req
        return True

    def _admit_waiting_legacy(self) -> None:
        while self.waiting and self.cache.free_slot_count:
            req = self.waiting[0]
            if self._finish_waiting_unservable(req):
                continue
            if not self._admit_one_legacy(req):
                if (not self.running and self.cache.available_page_count
                        == self.cache.num_pages):
                    self.waiting.popleft()
                    self._fail_never_admittable(
                        req, self.cache.pages_needed(
                            len(req._context_ids()) - 1))
                    continue
                break
            self.waiting.popleft()

    def _step_legacy(self) -> dict[int, list[int]]:
        if self._deadlines_armed:
            self._shed_expired()
        self._retire_finished()
        # admit/retire to fixpoint: a fresh prompt whose prefill token
        # already satisfies done (budget 1, or prefill token == eos) must
        # retire BEFORE the decode step — it would otherwise collect a
        # second token past its contract — and its freed lane can admit
        # the next waiting request within this same round
        while True:
            self._admit_waiting_legacy()
            if not any(r.done for r in self.running.values()):
                break
            self._retire_finished()
        if not self.running:
            return {}
        # growth: every running sequence needs room for one more token.
        # sorted() snapshots the slots — a preemption further down this
        # loop removes entries, and a freed slot must not re-enter the
        # capacity path (it would allocate pages into a parked page table)
        for slot in sorted(self.running):
            if slot not in self.running:
                continue
            if self.cache.seq_len(slot) + 1 > self.max_seq_len:
                # hit the length ceiling: stop the sequence NOW
                req = self.running.pop(slot)
                req.truncated = True
                self.cache.free(slot)
                self._finish(req)
                continue
            while not self.cache.ensure_capacity(
                    slot, self.cache.seq_len(slot) + 1):
                victim_is_self = (max(self.running,
                                      key=lambda s: self.running[s].req_id)
                                  == slot)
                if victim_is_self and len(self.running) == 1:
                    # round 17: requeue through the bounded retry path
                    # (FAILS after max_step_retries) instead of poisoning
                    # the predictor — same policy as the unified path
                    self._requeue_one(slot, RuntimeError(
                        f"slot {slot}: cannot grow to "
                        f"{self.cache.seq_len(slot) + 1} tokens — page "
                        "pool too small for this sequence"),
                        code="pool_exhausted")
                    break
                self._preempt_youngest()
                if slot not in self.running:  # preempted itself
                    break
        if not self.running:
            # the growth loop requeued/retired every lane (round 17:
            # pool_exhausted no longer raises): nothing to decode
            return {}
        ids = jnp.asarray(self._next_token)
        with span("dispatch"):
            next_ids, _, kp, vp = self._decode(
                self.params, ids, self.cache.seq_lens_device(),
                self.cache.k_pages, self.cache.v_pages,
                self.cache.page_table_device())
        self._mark_dispatch()
        self.cache.update_pages(kp, vp)
        self._m_steps.inc()
        t_sync = monotonic()
        out = np.asarray(next_ids)
        self._m_sync_s.inc(monotonic() - t_sync)
        self._did_sync = True
        self._mark_drained()
        produced = {}
        for slot, req in self.running.items():
            tok = int(out[slot])
            req.output_ids.append(tok)
            self._m_tokens.inc()
            if req.first_token_time is None:
                self._note_first_token(req)
            self._next_token[slot] = tok
            self.cache.advance(slot)
            produced[req.req_id] = [tok]
        return produced

    # -- the step ----------------------------------------------------------

    def step(self) -> dict[int, list[int]]:
        """One scheduler round. Returns ``{req_id: [tokens]}`` for the
        tokens produced this step, in emission order — a speculative
        decode lane can emit several (accepted drafts + bonus) in one
        round; a unified round that only advanced prefill chunks
        produces none. The async engine returns the tokens RECONCILED by
        this call (one step behind the dispatch; drain with
        :meth:`flush`)."""
        t0 = monotonic()
        self._did_sync = False
        # the pool-squeeze seam ticks EVERY scheduler round (never
        # raises): it must sit above the empty-running early returns or
        # an active squeeze could never expire while its withheld pages
        # are exactly what blocks the next admission
        fault_point("pool", cache=self.cache)
        try:
            if self.unified:
                return self._step_unified()
            return self._step_legacy()
        finally:
            if self._did_sync:
                # ONE hard sync per step()/flush() call no matter how
                # many ring entries it landed: a drain materializes the
                # oldest (blocking) and the rest are already resident
                self._m_hard_syncs.inc()
            self._m_step_s.inc(monotonic() - t0)
            self._m_step_calls.inc()
            self._m_running.set(len(self.running))
            self._m_waiting.set(len(self.waiting))
            self._last_round_end = monotonic()

    # -- convenience -------------------------------------------------------

    def generate(self, prompts, max_new_tokens=32, eos_token_id=None,
                 max_steps=None, **sampling):
        """Enqueue ``prompts`` (list of id lists) and drive steps until all
        finish. Returns a list of output-id lists, in prompt order.
        ``sampling`` forwards temperature/top_k/top_p/seed to every
        request."""
        reqs = [self.add_request(p, max_new_tokens, eos_token_id, **sampling)
                for p in prompts]
        # budget covers the chunked-prefill rounds too: EVERY prompt feeds
        # ceil(len/chunk) chunks before its first token (prompts can
        # serialize through one lane, so the rounds sum, not max)
        pre_rounds = sum(len(r.prompt_ids) // self.chunk + 1 for r in reqs)
        limit = max_steps or ((len(prompts) * (max_new_tokens + 2)
                               + pre_rounds)
                              * (self.max_batch + 1))
        n = 0
        while any(r.state not in (FINISHED, FAILED) for r in reqs):
            self.step()
            # a drained scheduler with unfinished requests means they can
            # never be admitted (oversized); surface rather than spin
            if not self.has_work():
                break
            n += 1
            if n > limit:
                # round 17: mark every straggler terminal FAILED before
                # raising — no request is ever left non-terminal, and the
                # predictor stays serviceable for everyone else
                self._fail_stragglers(
                    reqs, f"serving loop exceeded step budget ({limit})")
                raise RuntimeError("serving loop exceeded step budget "
                                   f"({limit}) — scheduler stuck")
        # a request can finish by COUNT with its final tokens still in
        # flight (async deferral): drain before reading the outputs
        self.flush()
        return [list(r.output_ids) for r in reqs]

    def _fail_stragglers(self, reqs, message: str) -> None:
        """Terminal-FAIL every non-terminal request in ``reqs`` with
        ``scheduler_stuck``, releasing any slot/pages held — the
        step-budget overflow path must never leave a request in a
        non-terminal state."""
        stuck = [r for r in reqs if r.state not in (FINISHED, FAILED)]
        if not stuck:
            return
        ids = {id(r) for r in stuck}
        for slot in [s for s, r in self.running.items() if id(r) in ids]:
            self.running.pop(slot)
            self.cache.free(slot)
        if any(id(r) in ids for r in self.waiting):
            self.waiting = deque(r for r in self.waiting
                                 if id(r) not in ids)
        for req in stuck:
            self._fail(req, "scheduler_stuck", message)


__all__ = ["Request", "ServingPredictor", "SLOConfig", "WAITING",
           "RUNNING", "FINISHED", "FAILED"]
