"""Deterministic fault injection for the serving engine (round 17).

The resilience layer's test harness: a seeded :class:`FaultPlan` arms
named failure seams inside ``ServingPredictor``'s hot paths and fires
them from ONE ``numpy.random.RandomState`` stream, so a chaos run is
exactly reproducible from its seed. The plan is **context-manager
scoped** (``with FaultPlan(seed=7, dispatch=0.02): ...``) and the
disarmed path is one module-global ``None`` check per seam — a predictor
running without an armed plan pays nothing.

Seams (the names ``ServingPredictor`` calls :func:`fault_point` with):

- ``pool`` — pool-pressure squeeze: withholds ``squeeze_pages``
  strictly-free pages from the KV cache manager for ``squeeze_steps``
  scheduler rounds (via :meth:`KVCacheManager.withhold_pages`), forcing
  the capacity loop through its preemption / draft-clamp / grow-failure
  paths under transient pressure. Hit at the top of EVERY ``step()``
  call (not inside the pack, which an empty-running round skips — the
  squeeze must keep expiring while its withheld pages are exactly what
  blocks the next admission). Pages return to the free list when the
  squeeze expires (and unconditionally at plan exit) — accounting stays
  exact.
- ``h2d`` — raises :class:`InjectedFault` where the step's packed host
  arrays upload to the device (the batched ``jax.device_put``).
- ``dispatch`` — raises :class:`InjectedFault` where the unified step
  would launch.
- ``slow_step`` — sleeps ``slow_step_s`` before the launch (straggler /
  latency injection; exercises the deadline machinery, never corrupts
  state).
- ``reconcile`` — raises :class:`InjectedFault` where an in-flight
  entry's emissions would materialize (the async engine's hard sync) —
  the model of a device error surfacing at block time.

Round 18 adds the FLEET seams (``inference/fleet_serving.py`` hits them
once per replica per tick):

- ``replica_crash`` — raises :class:`InjectedFault` where the fleet
  router would step a replica: the model of a replica process dying.
  The router owns the recovery (declare the replica DEAD, migrate its
  non-terminal requests, restart a fresh predictor into the slot).
- ``replica_stall`` — a RETURNING seam: when it fires,
  :func:`fault_point` returns ``stall_ticks`` (the number of scheduler
  ticks the replica will make no progress — a hung device / wedged host
  loop) instead of raising; unfired hits return ``None``. The router
  applies the stall (skips the replica's step) and its health gate
  observes it through the stale ``snapshot_age_s`` stamp.

Round 20 adds the KV TRANSFER WIRE seams (``inference/kv_transfer.py``
hits them once per frame put on the wire — fresh sends AND
retransmits):

- ``transfer_drop`` — a RETURNING seam: a fired hit returns ``True``
  and the sender treats the frame as lost in flight (no delivery, no
  ack — the per-frame timeout + exponential backoff own recovery).
- ``transfer_corrupt`` — a RETURNING seam: a fired hit returns ``True``
  and the sender flips a byte of the ENCODED wire bytes before
  delivery. The corruption MUST be caught by the frame checksum at the
  receiver (detected -> nack -> retransmit), never silently ingested —
  the contract tests/test_kv_transfer.py locks.

Round 21 adds the HOST-TIER seams (``inference/kv_cache.py`` hits them
on the spill/restore paths of the tiered KV cache):

- ``host_spill_drop`` — a RETURNING seam: a fired hit returns ``True``
  and the cache manager silently loses the spill (the page evicts
  without its payload reaching the host tier — the model of a failed
  DMA / an OOM-killed host buffer). Purely a cache-effectiveness loss:
  the next admission recomputes, counted, never failed.
- ``tier_restore_corrupt`` — a RETURNING seam: a fired hit returns
  ``True`` and the cache manager flips a byte of the STORED host-tier
  payload before its restore checksum runs. The corruption MUST be
  detected (entry dropped + counted, the lookup degrades to a
  recompute miss), never scattered into the device pool — the contract
  tests/test_faults.py and tests/test_prefix_cache.py lock.

Raising seams model CRASHES, so they raise **before** the operation they
name (a half-applied operation is the scheduler's job to make
impossible, not the plan's). ``plan.fired`` counts firings per seam for
test assertions; the predictor separately counts observed injected
faults on its metrics registry (``serving_faults_injected{seam=...}``).
"""
from __future__ import annotations

import time

import numpy as np

__all__ = ["FaultPlan", "InjectedFault", "SEAMS", "active_plan",
           "fault_point"]

#: the named seams a plan may arm (a typo'd rate kwarg fails at __init__)
SEAMS = ("pool", "h2d", "dispatch", "slow_step", "reconcile",
         "replica_crash", "replica_stall", "transfer_drop",
         "transfer_corrupt", "host_spill_drop", "tier_restore_corrupt")

#: the armed plan; None = disarmed (the zero-cost fast path)
_PLAN: "FaultPlan | None" = None


class InjectedFault(RuntimeError):
    """A deliberately-injected failure; carries its seam name so the
    recovery path can attribute it (``serving_faults_injected``)."""

    def __init__(self, seam: str):
        super().__init__(f"injected fault at seam '{seam}'")
        self.seam = seam


def active_plan() -> "FaultPlan | None":
    return _PLAN


def fault_point(seam: str, cache=None):
    """The seam hook the serving engine calls. Disarmed cost is this one
    module-global check (and the disarmed return is always ``None``).
    Raising seams raise :class:`InjectedFault`; the ``replica_stall``
    seam RETURNS its stall-tick count when it fires."""
    if _PLAN is not None:
        return _PLAN.hit(seam, cache=cache)
    return None


class FaultPlan:
    """One seeded chaos schedule over the named seams.

    ``dispatch`` / ``h2d`` / ``reconcile`` / ``slow_step`` /
    ``pool_squeeze`` are independent per-hit firing probabilities in
    ``[0, 1]``. All draws come from one ``RandomState(seed)`` in seam-hit
    order, so a deterministic scheduler replays the identical fault
    sequence. Not re-entrant (one armed plan per process) and not
    thread-aware — the serving engine drives every seam from the
    scheduler thread.
    """

    def __init__(self, seed: int = 0, *, dispatch: float = 0.0,
                 h2d: float = 0.0, reconcile: float = 0.0,
                 slow_step: float = 0.0, slow_step_s: float = 0.001,
                 pool_squeeze: float = 0.0, squeeze_pages: int = 2,
                 squeeze_steps: int = 2, replica_crash: float = 0.0,
                 replica_stall: float = 0.0, stall_ticks: int = 2,
                 transfer_drop: float = 0.0,
                 transfer_corrupt: float = 0.0,
                 host_spill_drop: float = 0.0,
                 tier_restore_corrupt: float = 0.0):
        rates = {"dispatch": dispatch, "h2d": h2d, "reconcile": reconcile,
                 "slow_step": slow_step, "pool": pool_squeeze,
                 "replica_crash": replica_crash,
                 "replica_stall": replica_stall,
                 "transfer_drop": transfer_drop,
                 "transfer_corrupt": transfer_corrupt,
                 "host_spill_drop": host_spill_drop,
                 "tier_restore_corrupt": tier_restore_corrupt}
        for name, p in rates.items():
            if not 0.0 <= float(p) <= 1.0:
                raise ValueError(f"{name} rate must be in [0, 1], got {p}")
        self.rates = {k: float(v) for k, v in rates.items()}
        self.rng = np.random.RandomState(seed)
        self.slow_step_s = float(slow_step_s)
        self.squeeze_pages = int(squeeze_pages)
        self.squeeze_steps = int(squeeze_steps)
        self.stall_ticks = int(stall_ticks)
        if self.stall_ticks < 1:
            raise ValueError(f"stall_ticks must be >= 1, got {stall_ticks}")
        self.fired: dict[str, int] = {s: 0 for s in SEAMS}
        # one active squeeze at a time: (cache, rounds_left)
        self._squeeze: tuple[object, int] | None = None

    # -- arming -------------------------------------------------------------

    def __enter__(self) -> "FaultPlan":
        global _PLAN
        if _PLAN is not None:
            raise RuntimeError("a FaultPlan is already armed")
        _PLAN = self
        return self

    def __exit__(self, *exc) -> bool:
        global _PLAN
        assert _PLAN is self
        _PLAN = None
        self._release_squeeze()
        return False

    def _release_squeeze(self) -> None:
        if self._squeeze is not None:
            cache, _ = self._squeeze
            cache.restore_withheld()
            self._squeeze = None

    # -- the seams ----------------------------------------------------------

    def hit(self, seam: str, cache=None) -> None:
        if seam == "pool":
            # expire a running squeeze first so pressure is bounded
            if self._squeeze is not None:
                cache_held, left = self._squeeze
                if left <= 1:
                    self._release_squeeze()
                else:
                    self._squeeze = (cache_held, left - 1)
            elif (cache is not None and self.rates["pool"]
                    and self.rng.rand() < self.rates["pool"]):
                if cache.withhold_pages(self.squeeze_pages):
                    self.fired["pool"] += 1
                    self._squeeze = (cache, self.squeeze_steps)
            return
        if seam == "slow_step":
            if self.rates["slow_step"] \
                    and self.rng.rand() < self.rates["slow_step"]:
                self.fired["slow_step"] += 1
                time.sleep(self.slow_step_s)
            return
        if seam == "replica_stall":
            # a RETURNING seam: the caller (the fleet router) applies
            # the stall — this plan only schedules it
            if self.rates["replica_stall"] \
                    and self.rng.rand() < self.rates["replica_stall"]:
                self.fired["replica_stall"] += 1
                return self.stall_ticks
            return None
        if seam in ("transfer_drop", "transfer_corrupt",
                    "host_spill_drop", "tier_restore_corrupt"):
            # RETURNING seams: the transfer layer applies the loss /
            # byte-flip to its own wire bytes (a corrupt frame must
            # reach the receiver so the checksum DETECTS it); the
            # round-21 host-tier seams work the same way on the cache
            # manager's spill/restore paths (a corrupt stored payload
            # must reach the restore checksum so it DETECTS it)
            if self.rates[seam] and self.rng.rand() < self.rates[seam]:
                self.fired[seam] += 1
                return True
            return None
        if seam not in self.rates:
            raise ValueError(f"unknown fault seam {seam!r} "
                             f"(known: {', '.join(SEAMS)})")
        if self.rates[seam] and self.rng.rand() < self.rates[seam]:
            self.fired[seam] += 1
            raise InjectedFault(seam)
