"""AST transformer: tensor-dependent python control flow -> converted calls.

Parity: python/paddle/jit/dy2static/transformers/ (ifelse_transformer.py,
loop_transformer.py, logical_transformer.py). The rewrite is source-level:

    if cond: A            _v = _jst.ld(lambda: _v)         # per captured var
    else:    B       =>   def __tfn(vs): (..) = vs; A; return (..)
                          def __ffn(vs): (..) = vs; B; return (..)
                          (..) = _jst.convert_ifelse(cond, __tfn, __ffn, (..))

with the same shape for ``while`` (cond/body closures through
``convert_while_loop``), ``and``/``or``/``not`` through convert_logical_*,
and ternaries through convert_ifexp. The converted callables dispatch at
RUNTIME on whether the predicate is traced, so one converted function serves
both eager and compiled execution.

Conservative scope (graph-break-and-fallback covers the rest, api.py):
- ``if``/``while`` containing return/break/continue are left untouched —
  a traced predicate there falls back to eager with a warning.
- names are captured only if they are locals of the enclosing function
  (params or stored somewhere in its body); globals/builtins pass through.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Callable

_JST = "_jst_ops__"


class _NameCollector(ast.NodeVisitor):
    def __init__(self):
        self.stores: set[str] = set()
        self.loads: set[str] = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Store):
            self.stores.add(node.id)
        else:
            self.loads.add(node.id)

    def visit_FunctionDef(self, node):
        self.stores.add(node.name)  # nested defs bind a local name

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass  # separate scope


def _names(nodes) -> tuple[set, set]:
    c = _NameCollector()
    for n in nodes:
        c.visit(n)
    return c.stores, c.loads


def _has_flow_escape(nodes) -> bool:
    """return/break/continue anywhere in these statements (not crossing into
    nested function scopes)."""

    class V(ast.NodeVisitor):
        found = False

        def visit_Return(self, n):
            self.found = True

        def visit_Break(self, n):
            self.found = True

        def visit_Continue(self, n):
            self.found = True

        def visit_FunctionDef(self, n):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, n):
            pass

    v = V()
    for n in nodes:
        v.visit(n)
    return v.found


def _tuple_of(names, ctx):
    return ast.Tuple(elts=[ast.Name(id=n, ctx=ctx()) for n in names],
                     ctx=ctx())


def _jst_call(fn_name, args):
    return ast.Call(
        func=ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                           attr=fn_name, ctx=ast.Load()),
        args=args, keywords=[])


class ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self, local_names: set[str]):
        self.locals = local_names
        self.counter = 0

    # -- helpers -----------------------------------------------------------
    def _fresh(self, base):
        self.counter += 1
        return f"__{base}_{self.counter}"

    def _captured(self, nodes) -> list[str]:
        stores, loads = _names(nodes)
        cap = (stores | (loads & self.locals)) & self.locals | stores
        return sorted(cap)

    def _ld_preamble(self, names):
        out = []
        for n in names:
            # n = _jst.ld(lambda: n) — UNDEF sentinel when unbound
            out.append(ast.Assign(
                targets=[ast.Name(id=n, ctx=ast.Store())],
                value=_jst_call("ld", [ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                       kw_defaults=[], defaults=[]),
                    body=ast.Name(id=n, ctx=ast.Load()))])))
        return out

    def _branch_fn(self, name, names, body_stmts):
        vars_arg = "__vars"
        header = ast.Assign(
            targets=[_tuple_of(names, ast.Store)],
            value=ast.Name(id=vars_arg, ctx=ast.Load()))
        ret = ast.Return(value=_tuple_of(names, ast.Load))
        return ast.FunctionDef(
            name=name,
            args=ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=vars_arg)],
                kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=[header] + body_stmts + [ret],
            decorator_list=[])

    # -- statements --------------------------------------------------------
    def visit_If(self, node):
        node = self.generic_visit(node)
        if _has_flow_escape(node.body + node.orelse):
            return node
        names = self._captured(node.body + node.orelse)
        if not names:
            return node
        tname = self._fresh("true_fn")
        fname = self._fresh("false_fn")
        tfn = self._branch_fn(tname, names, node.body)
        ffn = self._branch_fn(
            fname, names, node.orelse or [ast.Pass()])
        call = ast.Assign(
            targets=[_tuple_of(names, ast.Store)],
            value=_jst_call("convert_ifelse", [
                node.test,
                ast.Name(id=tname, ctx=ast.Load()),
                ast.Name(id=fname, ctx=ast.Load()),
                _tuple_of(names, ast.Load)]))
        return self._ld_preamble(names) + [tfn, ffn, call]

    def visit_While(self, node):
        node = self.generic_visit(node)
        if node.orelse or _has_flow_escape(node.body):
            return node
        # Carry ONLY the names the body stores. Read-only locals resolve via
        # closure over the enclosing scope — keeping them out of the
        # lax.while_loop carry means gradients to them (used outside the
        # loop) do not route through the non-transposable while primitive.
        stores, _ = _names(node.body)
        names = sorted(stores & self.locals | stores)
        if not names:
            return node
        cname = self._fresh("while_cond")
        bname = self._fresh("while_body")
        vars_arg = "__vars"
        header = ast.Assign(targets=[_tuple_of(names, ast.Store)],
                            value=ast.Name(id=vars_arg, ctx=ast.Load()))
        cfn = ast.FunctionDef(
            name=cname,
            args=ast.arguments(posonlyargs=[], args=[ast.arg(arg=vars_arg)],
                               kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=[header, ast.Return(value=node.test)],
            decorator_list=[])
        bfn = self._branch_fn(bname, names, node.body)
        call = ast.Assign(
            targets=[_tuple_of(names, ast.Store)],
            value=_jst_call("convert_while_loop", [
                ast.Name(id=cname, ctx=ast.Load()),
                ast.Name(id=bname, ctx=ast.Load()),
                _tuple_of(names, ast.Load)]))
        return self._ld_preamble(names) + [cfn, bfn, call]

    # -- expressions -------------------------------------------------------
    def visit_BoolOp(self, node):
        node = self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        out = node.values[-1]
        for v in reversed(node.values[:-1]):
            out = _jst_call(fn, [_lambda(v), _lambda(out)])
        return out

    def visit_UnaryOp(self, node):
        node = self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return _jst_call("convert_logical_not", [node.operand])
        return node

    def visit_IfExp(self, node):
        node = self.generic_visit(node)
        return _jst_call("convert_ifexp", [
            node.test, _lambda(node.body), _lambda(node.orelse)])


def _lambda(expr):
    return ast.Lambda(
        args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                           kw_defaults=[], defaults=[]),
        body=expr)


@functools.lru_cache(maxsize=512)
def _convert_code(code, filename, fname):
    tree = ast.parse(code)
    fn_def = tree.body[0]
    if not isinstance(fn_def, (ast.FunctionDef, ast.AsyncFunctionDef)):
        # Lambda / assignment sources (``f = to_static(lambda ...)``): a
        # lambda body cannot contain statements, so there is no control flow
        # to convert. Signal "nothing to do" — convert_to_static catches
        # TypeError and uses the original function.
        raise TypeError(f"source of {fname!r} is not a function definition")
    fn_def.decorator_list = []  # strip @to_static etc.
    # local names: params + every stored name in the body
    params = {a.arg for a in (fn_def.args.posonlyargs + fn_def.args.args
                              + fn_def.args.kwonlyargs)}
    if fn_def.args.vararg:
        params.add(fn_def.args.vararg.arg)
    if fn_def.args.kwarg:
        params.add(fn_def.args.kwarg.arg)
    stores, _ = _names(fn_def.body)
    tr = ControlFlowTransformer(params | stores)
    new = tr.visit(tree)
    ast.fix_missing_locations(new)
    return compile(new, filename, "exec")


def convert_to_static(fn: Callable) -> Callable:
    """Return a control-flow-converted version of ``fn`` (or ``fn`` itself
    when its source is unavailable / has nothing to convert). Parity:
    dy2static program_translator convert_to_static."""
    if inspect.ismethod(fn):
        import types

        conv = convert_to_static(fn.__func__)
        if conv is fn.__func__:
            return fn
        return types.MethodType(conv, fn.__self__)
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        compiled = _convert_code(src, inspect.getfile(fn), fn.__name__)
    except (OSError, TypeError, SyntaxError):
        return fn
    from . import convert_operators

    glb = dict(fn.__globals__)
    glb[_JST] = convert_operators
    # Rebuild closure cells by name — the converted function is exec'd at
    # module level, so its frees resolve as globals. Closure values MUST
    # override same-named module globals (python scoping); the snapshot is
    # taken at conversion time (later cell mutations are not observed —
    # acceptable for the to_static use, which converts at decoration).
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError:
                pass
    ns: dict = {}
    exec(compiled, glb, ns)
    new_fn = ns[fn.__name__]
    new_fn.__wrapped__ = fn
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    return new_fn
