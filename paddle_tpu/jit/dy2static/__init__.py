"""dy2static: AST conversion of tensor-dependent python control flow.

Parity: python/paddle/jit/dy2static/ (transformers/ + convert_operators.py).
``convert_to_static`` rewrites ``if``/``while``/``and``/``or``/``not``/
ternaries into runtime dispatchers that become ``lax.cond``/``lax.while_loop``
when the predicate is traced — so ``@to_static`` functions with
data-dependent branches compile into ONE XLA graph instead of erroring.
Anything outside the converted subset falls back to eager with a warning
(SOT graph-break parity, see jit/api.py)."""
from .convert_operators import (  # noqa: F401
    UndefinedVar,
    convert_ifelse,
    convert_ifexp,
    convert_logical_and,
    convert_logical_not,
    convert_logical_or,
    convert_while_loop,
)
from .transformer import convert_to_static  # noqa: F401
