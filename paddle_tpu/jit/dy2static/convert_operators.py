"""Runtime conversion operators for dy2static.

Parity: python/paddle/jit/dy2static/convert_operators.py (convert_ifelse,
convert_while_loop, convert_logical_*). The AST transformer
(transformer.py) rewrites tensor-dependent python control flow into calls
here; each helper dispatches on whether the predicate is a traced Tensor:

- traced  -> ``lax.cond`` / ``lax.while_loop`` (XLA control flow, one graph)
- python  -> the original python semantics (zero overhead, exact behavior)

TPU-native stance: this IS the reference's convert layer with the op-level
targets swapped (cond_op/while_op ProgramDesc blocks -> lax primitives).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ...tensor.tensor import Tensor


class UndefinedVar:
    """Placeholder for a name unbound before a converted branch (parity:
    dy2static UndefinedVar).

    Any actual *use* raises NameError, preserving eager semantics: a name
    that stays unassigned on the taken branch of a converted if/while would
    raise UnboundLocalError in plain python, so the placeholder must not
    silently flow into arithmetic or calls."""

    __slots__ = ("name",)

    def __init__(self, name: str = "?"):
        self.name = name

    def __repr__(self):
        return f"UndefinedVar({self.name})"

    def _use(self, *_a, **_k):
        raise NameError(
            f"local variable '{self.name}' referenced before assignment — "
            "it was not assigned on the taken branch of a converted "
            "if/while (eager would raise UnboundLocalError)")

    __bool__ = __call__ = __iter__ = __len__ = __getitem__ = _use
    __int__ = __float__ = __index__ = __neg__ = __pos__ = __abs__ = _use
    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _use
    __truediv__ = __rtruediv__ = __floordiv__ = __rfloordiv__ = _use
    __mod__ = __rmod__ = __pow__ = __rpow__ = __matmul__ = __rmatmul__ = _use
    __lt__ = __le__ = __gt__ = __ge__ = __eq__ = __ne__ = _use
    __hash__ = object.__hash__  # keep hashable despite __eq__ override

    def __getattr__(self, attr):
        if attr.startswith("__") and attr.endswith("__"):
            # library probes (hasattr/getattr-with-default/deepcopy) expect
            # AttributeError for missing dunders, not a use-error
            raise AttributeError(attr)
        self._use()


_UNDEF = UndefinedVar


def ld(f):
    """Capture a possibly-unbound local for branch plumbing."""
    try:
        return f()
    except (NameError, UnboundLocalError):
        return UndefinedVar()


def _is_traced(x) -> bool:
    if isinstance(x, Tensor):
        x = x._data
    return isinstance(x, jax.core.Tracer)


def _to_bool(x):
    if isinstance(x, Tensor):
        return bool(x._data)
    return bool(x)


def _unwrap(tree):
    return jax.tree.map(
        lambda l: l._data if isinstance(l, Tensor) else l, tree,
        is_leaf=lambda l: isinstance(l, Tensor))


def _pred_data(pred):
    d = pred._data if isinstance(pred, Tensor) else pred
    return jnp.reshape(jnp.asarray(d), ()).astype(bool)


def convert_ifelse(pred, true_fn, false_fn, union_vars):
    """(v1, ..., vn) = convert_ifelse(cond, tfn, ffn, (v1, ..., vn)).

    Tensor/tracer ``pred`` -> lax.cond over both branches; both must produce
    structurally identical outputs (a variable bound in only one branch of a
    tensor-dependent ``if`` is an error, like the reference's static cond).
    Python ``pred`` -> run the taken branch only.

    Only Tensor/array leaves thread through the cond operands; python-value
    leaves (ints, strings, UndefinedVar placeholders) are closed over from
    the call site — they are trace-time constants, exactly like the
    reference bakes python attrs into the ProgramDesc."""
    if not _is_traced(pred):
        return (true_fn if _to_bool(pred) else false_fn)(union_vars)

    is_leaf = lambda x: isinstance(x, (Tensor, UndefinedVar))  # noqa: E731
    leaves, treedef = jax.tree.flatten(union_vars, is_leaf=is_leaf)
    tensor_pos = [i for i, l in enumerate(leaves)
                  if isinstance(l, (Tensor, jax.Array))]
    operands = tuple(
        leaves[i]._data if isinstance(leaves[i], Tensor) else leaves[i]
        for i in tensor_pos)

    def wrap(fn):
        def run(ops):
            rebuilt = list(leaves)
            for pos, d in zip(tensor_pos, ops):
                rebuilt[pos] = Tensor(d)
            out = fn(jax.tree.unflatten(treedef, rebuilt))
            out_leaves = jax.tree.leaves(out, is_leaf=is_leaf)
            if any(isinstance(l, UndefinedVar) for l in out_leaves):
                raise ValueError(
                    "to_static: a variable used after a tensor-dependent "
                    "`if` is only defined in one branch; define it before "
                    "the `if` or in both branches")
            return _unwrap(out)

        return run

    out = lax.cond(_pred_data(pred), wrap(true_fn), wrap(false_fn), operands)
    return jax.tree.map(
        lambda l: Tensor(l, stop_gradient=True)
        if isinstance(l, jax.Array) else l, out)


def convert_while_loop(cond_fn, body_fn, loop_vars):
    """Tensor-valued condition -> lax.while_loop; python -> plain while.

    Forward-only under a tensor condition: XLA cannot reverse-differentiate
    a dynamic-trip-count loop (the adjoint needs a dynamic activation stack;
    the reference's GPU while_op backward uses growable TensorArrays, which
    have no static-shape equivalent). Gradients flow through everything
    OUTSIDE the loop; differentiating THROUGH it raises jax's
    while-transpose error. Data-dependent *bounded* iteration that needs
    gradients should use ``lax.scan`` semantics (python ``for`` over a
    static range, which traces unrolled/scanned and differentiates fine)."""
    first = cond_fn(loop_vars)
    if not _is_traced(first):
        while _to_bool(cond_fn(loop_vars)):
            loop_vars = body_fn(loop_vars)
        return loop_vars

    def rewrap_like(template, flat):
        return jax.tree.map(
            lambda t, l: Tensor(l, stop_gradient=True)
            if isinstance(t, Tensor) else l,
            template, flat,
            is_leaf=lambda t: isinstance(t, Tensor))

    template = loop_vars

    def cond(carry):
        return _pred_data(cond_fn(rewrap_like(template, carry)))

    def body(carry):
        return _unwrap(body_fn(rewrap_like(template, carry)))

    # numeric python leaves must become arrays (the carry is traced)
    init = jax.tree.map(
        lambda l: l._data if isinstance(l, Tensor)
        else jnp.asarray(l) if isinstance(l, (int, float, bool)) else l,
        loop_vars, is_leaf=lambda l: isinstance(l, Tensor))
    out = lax.while_loop(cond, body, init)
    return jax.tree.map(
        lambda t, l: Tensor(l, stop_gradient=True)
        if isinstance(t, Tensor) or isinstance(l, jax.Array) else l,
        template, out, is_leaf=lambda t: isinstance(t, Tensor))


def convert_logical_and(lhs_fn, rhs_fn):
    lhs = lhs_fn()
    if _is_traced(lhs):
        from ...tensor.logic import logical_and

        rhs = rhs_fn()
        return logical_and(_as_tensor(lhs), _as_tensor(rhs))
    return lhs and rhs_fn()  # python short-circuit


def convert_logical_or(lhs_fn, rhs_fn):
    lhs = lhs_fn()
    if _is_traced(lhs):
        from ...tensor.logic import logical_or

        rhs = rhs_fn()
        return logical_or(_as_tensor(lhs), _as_tensor(rhs))
    return lhs or rhs_fn()


def convert_logical_not(x):
    if _is_traced(x):
        from ...tensor.logic import logical_not

        return logical_not(_as_tensor(x))
    return not x


def convert_ifexp(pred, true_fn, false_fn):
    """Ternary ``x if c else y``."""
    if not _is_traced(pred):
        return (true_fn if _to_bool(pred) else false_fn)()
    out = lax.cond(_pred_data(pred),
                   lambda _: _unwrap(true_fn()),
                   lambda _: _unwrap(false_fn()), ())
    return jax.tree.map(
        lambda l: Tensor(l, stop_gradient=True)
        if isinstance(l, jax.Array) else l, out)


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
