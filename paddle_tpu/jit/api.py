"""to_static / save / load implementation.

The conversion pipeline the reference spreads over SOT bytecode translation +
AST rewriting + PartialProgramLayer (python/paddle/jit/, ~32k LoC:
sot/translate.py:31, dy2static/program_translator.py:325,
dy2static/partial_program.py:151) collapses here to: functionalize the layer
(parameters become explicit inputs), trace with jax.jit (guards = jit cache
keys), and record the compiled program on the autograd tape as ONE node so
``loss.backward()`` works across the boundary (parity:
fluid/eager/to_static/run_program_op_func.h:136 run_program_ad_func).
"""
from __future__ import annotations

import os
import pickle
from typing import Any, Callable

import numpy as np

import jax
import jax.numpy as jnp

from ..autograd.engine import apply_op
from ..autograd.grad_mode import no_grad
from ..framework import dtype as dtype_mod
from ..nn import Layer
from ..tensor.tensor import Parameter, Tensor

_TO_STATIC_ENABLED = True
_IGNORED_MODULES: set = set()


def enable_to_static(flag: bool) -> None:
    """Globally toggle conversion (reference: jit/api.py enable_to_static)."""
    global _TO_STATIC_ENABLED
    _TO_STATIC_ENABLED = bool(flag)


def not_to_static(fn=None):
    """Mark a function to stay eager (reference: paddle.jit.not_to_static)."""
    if fn is None:
        return not_to_static
    fn._paddle_tpu_not_to_static = True
    return fn


def ignore_module(modules: list) -> None:
    """Compatibility API (reference: paddle.jit.ignore_module). Trace-based
    conversion traces through all python modules, so nothing to do."""
    _IGNORED_MODULES.update(id(m) for m in modules)


class InputSpec:
    """Shape/dtype spec for a traced input (parity: paddle.static.InputSpec).

    ``None`` dims mean "dynamic" in the reference; XLA wants static shapes, so
    None dims are trace-time-concrete — each distinct concrete shape gets its
    own compiled program (jit cache key), which is the SOT guard-retrace
    behavior (sot/opcode_translator/executor/guard.py parity).
    """

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor: Tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


# ---------------------------------------------------------------------------
# functional_call: the layer functionalizer
# ---------------------------------------------------------------------------


def _named_state(layer: Layer) -> dict[str, Tensor]:
    state: dict[str, Tensor] = {}
    for name, p in layer.named_parameters():
        state[name] = p
    for name, b in layer.named_buffers():
        if name not in state:
            state[name] = b
    return state


class _swap_state:
    """Context manager: substitute parameter/buffer ``_data`` by name, restore
    on exit. Tensor identity is preserved (hooks, sublayer references), only
    the array is swapped."""

    def __init__(self, layer: Layer, state_arrays: dict[str, Any]):
        state = _named_state(layer)
        missing = [n for n in state_arrays if n not in state]
        if missing:
            raise KeyError(f"functional_call: unknown parameter/buffer names {missing}")
        self._targets = {n: state[n] for n in state_arrays}
        self._new = state_arrays

    def __enter__(self):
        self._saved = {n: t._data for n, t in self._targets.items()}
        for n, t in self._targets.items():
            v = self._new[n]
            t._data = v._data if isinstance(v, Tensor) else v

    def __exit__(self, *exc):
        for n, t in self._targets.items():
            t._data = self._saved[n]


def functional_call(layer: Layer, state_arrays: dict[str, Any], *args,
                    _forward=None, return_state=False, **kwargs):
    """Run ``layer`` with parameters/buffers substituted by ``state_arrays``
    (name -> jax array or tracer), restoring the originals afterwards.

    The bridge from the stateful Layer world to pure functions that jax.jit /
    jax.grad / shard_map can transform. ``_forward`` overrides the callable
    (used by StaticFunction to reach the pre-conversion forward and avoid
    re-entering itself).

    ``return_state=True`` additionally returns ``{name: data}`` captured
    AFTER the forward but before restoration — this is how in-place buffer
    mutation (BatchNorm running stats in train mode) becomes functional
    state that a jit/scan caller can thread through its carry.
    """
    sw = _swap_state(layer, state_arrays)
    with sw:
        if _forward is not None:
            out = _forward(*args, **kwargs)
        else:
            out = layer(*args, **kwargs)
        if return_state:
            new_state = {n: t._data for n, t in sw._targets.items()}
    if return_state:
        return out, new_state
    return out


# ---------------------------------------------------------------------------
# StaticFunction
# ---------------------------------------------------------------------------


def _is_arraylike(x) -> bool:
    return isinstance(x, (Tensor, jax.Array, np.ndarray))


def _leaf_data(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x)


class _ConcreteProgram:
    """One traced+compiled program for a fixed input structure (parity:
    dy2static ConcreteProgram). jax.jit inside handles shape/dtype
    specialization (guards)."""

    def __init__(self, static: "StaticFunction", treedef, tensor_pos, const_leaves, train: bool):
        self.treedef = treedef
        self.tensor_pos = tensor_pos
        self.const_leaves = const_leaves  # pos -> python value
        self.out_info = [None]  # (out_treedef, tensor_mask) set at trace time
        layer = static._layer
        function = static._function
        n_leaves = treedef.num_leaves
        out_info = self.out_info
        # Train-mode buffer mutation (BatchNorm running stats) becomes
        # functional state: the traced program returns updated buffers and
        # __call__ writes them back (reference: buffers are program outputs
        # in dy2static partial programs too).
        buf_names = ([n for n, _ in layer.named_buffers()]
                     if (layer is not None and train) else [])
        self.buf_names = buf_names

        def pure(rng_key, param_arrays: dict, *tensor_datas):
            rebuilt = [None] * n_leaves
            for pos, val in const_leaves.items():
                rebuilt[pos] = val
            for pos, d in zip(tensor_pos, tensor_datas):
                rebuilt[pos] = Tensor(d)
            args, kwargs = jax.tree.unflatten(treedef, rebuilt)
            # Randomness is threaded as a per-call input: install the traced
            # key as the generator's trace key so every rng_arg()/next_key()
            # inside the program folds in from it. Without this, keys drawn
            # during tracing are baked as constants and a @to_static dropout
            # replays the identical mask every call (reference dy2static/SOT
            # re-draws per run via the DeviceContext generator).
            from ..framework.random import default_generator

            saved_tk = default_generator._trace_key
            saved_ctr = default_generator._counter
            default_generator._trace_key = rng_key
            default_generator._counter = 0
            new_state = {}
            try:
                with no_grad():
                    if layer is not None:
                        was_training = layer.training
                        (layer.train if train else layer.eval)()
                        try:
                            out, new_state = functional_call(
                                layer, param_arrays, *args,
                                _forward=function, return_state=True,
                                **kwargs
                            )
                        finally:
                            (layer.train if was_training else layer.eval)()
                    else:
                        out = function(*args, **kwargs)
            finally:
                default_generator._trace_key = saved_tk
                default_generator._counter = saved_ctr
            out_leaves, out_td = jax.tree.flatten(out, is_leaf=lambda x: isinstance(x, Tensor))
            # Non-array leaves (aux python values: strs, ints, None-likes)
            # bypass the compiled program and are reattached at unflatten time,
            # so eager and converted outputs have identical types.
            arr_pos = [i for i, l in enumerate(out_leaves) if _is_arraylike(l)]
            const_out = {i: l for i, l in enumerate(out_leaves) if not _is_arraylike(l)}
            out_info[0] = (out_td, arr_pos, const_out)
            main = tuple(_leaf_data(out_leaves[i]) for i in arr_pos)
            # Record the EMITTED buffer list (pure filters to names present
            # in new_state): __call__ zips buf_names against the program's
            # buffer outputs, so the two must be the same list or writeback
            # silently lands on the wrong buffers.
            emitted = [n for n in buf_names if n in new_state]
            self.buf_names = emitted
            bufs = tuple(_leaf_data(new_state[n]) for n in emitted)
            return main + bufs

        self.fn = jax.jit(pure)


def _hashable(v):
    try:
        hash(v)
        return v
    except TypeError:
        pass
    try:
        # Structural key for unhashable consts (lists, dicts, dataclasses):
        # equal values share a compiled program; identity-repr objects don't
        # leak one program per call.
        return pickle.dumps(v)
    except Exception:
        raise TypeError(
            f"to_static: argument of type {type(v).__name__} is neither "
            "hashable nor picklable and cannot key the program cache; pass "
            "it as a Tensor or make it hashable"
        ) from None


class StaticFunction:
    """A converted callable (parity: dy2static/program_translator.py:325).

    Call path: one ``apply_op`` over a cached jax.jit'd pure function; the
    tape sees ONE node whose vjp is the jax.vjp of the whole compiled program
    (PartialProgramLayer parity), so backward/retain_graph/param grads all
    behave exactly as in eager.
    """

    def __init__(self, function: Callable, input_spec=None, layer: Layer | None = None, full_graph=True):
        self._raw_function = function
        # AST-convert tensor-dependent control flow (dy2static parity); the
        # converted fn dispatches at runtime, so it also serves eager calls
        from .dy2static import convert_to_static

        self._function = convert_to_static(function)
        self._input_spec = input_spec
        self._layer = layer
        self._full_graph = full_graph
        self._programs: dict = {}
        self._fallback_keys: set = set()
        self.__name__ = getattr(function, "__name__", "static_fn")
        self.__wrapped__ = function

    @property
    def concrete_programs(self):
        return list(self._programs.values())

    def get_concrete_program(self, *args, **kwargs) -> _ConcreteProgram:
        leaves, treedef = jax.tree.flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor)
        )
        tensor_pos = tuple(i for i, l in enumerate(leaves) if _is_arraylike(l))
        const_leaves = {
            i: l for i, l in enumerate(leaves) if not _is_arraylike(l)
        }
        train = self._layer.training if self._layer is not None else False
        key = (
            treedef,
            tensor_pos,
            tuple(sorted((i, _hashable(v)) for i, v in const_leaves.items())),
            train,
        )
        prog = self._programs.get(key)
        if prog is None:
            prog = _ConcreteProgram(self, treedef, tensor_pos, const_leaves, train)
            self._programs[key] = prog
        return prog, leaves

    def _run_eager(self, *args, **kwargs):
        return self._function(*args, **kwargs)

    def __call__(self, *args, **kwargs):
        if not _TO_STATIC_ENABLED or getattr(
            self._raw_function, "_paddle_tpu_not_to_static", False
        ):
            return self._run_eager(*args, **kwargs)

        prog, leaves = self.get_concrete_program(*args, **kwargs)
        key = id(prog)
        if key in self._fallback_keys:
            return self._run_eager(*args, **kwargs)
        state = _named_state(self._layer) if self._layer is not None else {}
        names = sorted(state)
        param_args = {n: state[n] for n in names}
        tensor_args = [
            leaves[i] if isinstance(leaves[i], Tensor) else Tensor(jnp.asarray(leaves[i]))
            for i in prog.tensor_pos
        ]
        from ..framework.random import rng_arg

        try:
            outs = apply_op("jit_program", prog.fn, rng_arg(), param_args, *tensor_args)
        except Exception as e:
            if getattr(prog, "_ran_ok", False):
                raise  # post-compile runtime failure: a real error, surface it
            if isinstance(e, jax.errors.JaxRuntimeError):
                # Backend failure on the FIRST run — could be a transient
                # execution OOM (retryable) or a deterministic XLA/Mosaic
                # compile rejection (both surface as JaxRuntimeError). Run
                # eager NOW (SOT "always runs" guarantee) but only pin these
                # inputs to eager permanently after repeated failures, so a
                # transient OOM doesn't disable compilation forever.
                import warnings

                prog._rt_failures = getattr(prog, "_rt_failures", 0) + 1
                if prog._rt_failures >= 3:
                    self._fallback_keys.add(key)
                warnings.warn(
                    f"to_static: running '{self.__name__}' compiled failed "
                    f"({type(e).__name__}: {e}); falling back to eager for "
                    "this call", stacklevel=2)
                return self._run_eager(*args, **kwargs)
            # graph break: tracing/compiling this program failed — run eager
            # (reference SOT guarantee: "always runs, worst case eager",
            # sot/translate.py:31). A genuine user bug re-raises from the
            # eager run with a clean python traceback.
            import warnings

            warnings.warn(
                f"to_static: tracing '{self.__name__}' failed "
                f"({type(e).__name__}: {e}); falling back to eager "
                "execution for these inputs", stacklevel=2)
            self._fallback_keys.add(key)
            return self._run_eager(*args, **kwargs)
        prog._ran_ok = True
        out_td, arr_pos, const_out = prog.out_info[0]
        if not isinstance(outs, (tuple, list)):
            outs = (outs,)
        if prog.buf_names:
            # write updated buffers (BN running stats) back into the layer
            buf_outs = outs[len(arr_pos):]
            outs = outs[:len(arr_pos)]
            if len(buf_outs) != len(prog.buf_names):
                # buf_names comes from the LAST retrace; a program whose
                # emitted-buffer set varies across shape signatures would
                # misalign writeback — fail loudly instead
                raise RuntimeError(
                    f"to_static: program emitted {len(buf_outs)} buffer "
                    f"outputs but the last trace recorded "
                    f"{len(prog.buf_names)} buffer names; buffer emission "
                    "must be trace-invariant")
            for n, t in zip(prog.buf_names, buf_outs):
                target = state[n]
                target._data = t._data.astype(target._data.dtype)
        leaves_out = [None] * (len(arr_pos) + len(const_out))
        for i, t in zip(arr_pos, outs):
            leaves_out[i] = t
        for i, v in const_out.items():
            leaves_out[i] = v
        return jax.tree.unflatten(out_td, leaves_out)


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, full_graph=True, **kwargs):
    """Convert a function or Layer to compiled-graph execution.

    Reference: paddle.jit.to_static (jit/api.py:171). Usable as a decorator
    (with or without arguments) or called on a Layer instance / bound method.
    """

    def decorate(obj):
        if isinstance(obj, Layer):
            static = StaticFunction(obj.forward, input_spec, layer=obj, full_graph=full_graph)
            obj.forward = static
            return obj
        self_obj = getattr(obj, "__self__", None)
        if isinstance(self_obj, Layer):
            fn = obj.__func__

            def unbound(*a, **k):
                return fn(self_obj, *a, **k)

            unbound.__name__ = getattr(fn, "__name__", "forward")
            return StaticFunction(unbound, input_spec, layer=self_obj, full_graph=full_graph)
        return StaticFunction(obj, input_spec, layer=None, full_graph=full_graph)

    if function is not None:
        return decorate(function)
    return decorate


# ---------------------------------------------------------------------------
# save / load — serialized compiled programs (jit.save parity)
# ---------------------------------------------------------------------------


def _resolve_specs(input_spec):
    specs = []
    for s in input_spec or []:
        if isinstance(s, InputSpec):
            specs.append(s)
        elif isinstance(s, Tensor):
            specs.append(InputSpec.from_tensor(s))
        else:
            raise TypeError(f"unsupported input spec {type(s)}")
    return specs


def save(layer, path: str, input_spec=None, **configs):
    """Serialize a Layer (or function) into a portable program + params.

    Reference: paddle.jit.save (jit/api.py) producing .pdmodel/.pdiparams; the
    TPU-native artifact is a serialized StableHLO program via ``jax.export``
    (the serving IR — SURVEY.md §7.2 L4 "jit.save/load of StableHLO+weights")
    plus an .npz of parameter arrays. This doubles as the inference-export
    path (AnalysisPredictor parity is built on loading these artifacts).
    """
    from jax import export as jax_export

    if isinstance(layer, StaticFunction):
        static = layer
        base_layer = static._layer
        fn = static._function
    elif isinstance(layer, Layer):
        fwd = layer.forward
        if isinstance(fwd, StaticFunction):
            base_layer, fn = layer, fwd._function
        else:
            base_layer, fn = layer, fwd
    else:
        base_layer, fn = None, layer

    specs = _resolve_specs(input_spec)
    if not specs:
        raise ValueError("jit.save requires input_spec (export needs static shapes)")

    state = _named_state(base_layer) if base_layer is not None else {}
    names = sorted(state)
    param_arrays = {n: state[n]._data for n in names}

    def pure(params: dict, *in_datas):
        tensors = [Tensor(d) for d in in_datas]
        with no_grad():
            if base_layer is not None:
                was_training = base_layer.training
                base_layer.eval()
                try:
                    out = functional_call(base_layer, params, *tensors, _forward=fn)
                finally:
                    if was_training:
                        base_layer.train()
            else:
                out = fn(*tensors)
        leaves, _ = jax.tree.flatten(out, is_leaf=lambda x: isinstance(x, Tensor))
        return tuple(_leaf_data(t) for t in leaves)

    # None dims export as symbolic dimensions (dynamic batch etc.); one shared
    # scope so equal symbols could be constrained together later.
    has_dynamic = any(d is None for s in specs for d in s.shape)
    if has_dynamic:
        scope = jax_export.SymbolicScope()
        counter = [0]

        def dim_str(d):
            if d is None:
                counter[0] += 1
                return f"_dyn{counter[0]}"
            return str(d)

        arg_shapes = []
        for s in specs:
            shape_str = ", ".join(dim_str(d) for d in s.shape)
            sym = jax_export.symbolic_shape(shape_str or "()", scope=scope)
            arg_shapes.append(
                jax.ShapeDtypeStruct(sym, dtype_mod.to_jax_dtype(s.dtype))
            )
    else:
        arg_shapes = [
            jax.ShapeDtypeStruct(tuple(s.shape), dtype_mod.to_jax_dtype(s.dtype))
            for s in specs
        ]
    param_shapes = {n: jax.ShapeDtypeStruct(a.shape, a.dtype) for n, a in param_arrays.items()}
    exported = jax_export.export(jax.jit(pure))(param_shapes, *arg_shapes)

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(exported.serialize(vjp_order=1))
    np.savez(path + ".pdiparams.npz", **{n: np.asarray(a) for n, a in param_arrays.items()})
    meta = {
        "specs": [(s.shape, str(s.dtype), s.name) for s in specs],
        "param_names": names,
        "format": "stablehlo-v1",
    }
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump(meta, f)


class TranslatedLayer(Layer):
    """A loaded serialized program, callable like the original Layer
    (reference: paddle.jit.TranslatedLayer, jit/translated_layer.py)."""

    def __init__(self, exported, params: dict, meta: dict):
        super().__init__()
        self._exported = exported
        self._meta = meta
        self._param_names = sorted(params)
        for n in self._param_names:
            self.add_parameter(n.replace(".", "__"), Parameter(jnp.asarray(params[n]), name=n))

    def forward(self, *inputs):
        names = self._param_names
        params_tuple = tuple(self._parameters[n.replace(".", "__")] for n in names)
        tensor_inputs = [
            t if isinstance(t, Tensor) else Tensor(jnp.asarray(t)) for t in inputs
        ]

        def op_fn(params, *datas):
            params_dict = dict(zip(names, params))
            return tuple(self._exported.call(params_dict, *datas))

        outs = apply_op("jit_loaded_program", op_fn, params_tuple, *tensor_inputs)
        if not isinstance(outs, (tuple, list)):
            return outs
        return outs[0] if len(outs) == 1 else list(outs)


def load(path: str) -> TranslatedLayer:
    """Load a program saved by :func:`save`."""
    from jax import export as jax_export

    with open(path + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    params = dict(np.load(path + ".pdiparams.npz"))
    with open(path + ".pdmeta", "rb") as f:
        meta = pickle.load(f)
    return TranslatedLayer(exported, params, meta)
