"""paddle_tpu.jit — dynamic-to-static compilation.

Parity target: paddle.jit (reference: python/paddle/jit/api.py:171 to_static,
dy2static/program_translator.py:325 StaticFunction concrete-program cache,
dy2static/partial_program.py:151 PartialProgramLayer, jit/sot/translate.py:31
bytecode JIT with guards).

TPU-native design (SURVEY.md §7.2 L4): tracing IS the static converter. Every
framework op is a pure jax function, so running the python callable on jax
tracers yields the whole program; ``jax.jit``'s (shape, dtype) cache keys ARE
the SOT guards (guard.py parity: re-trace on spec change); XLA is CINN. The
compiled subgraph participates in autograd as ONE tape node (PartialProgramLayer
parity: run_program_ad_func, fluid/eager/to_static/run_program_op_func.h:136).
"""
from .api import (
    InputSpec,
    StaticFunction,
    TranslatedLayer,
    enable_to_static,
    functional_call,
    ignore_module,
    load,
    not_to_static,
    save,
    to_static,
)

__all__ = [
    "InputSpec",
    "StaticFunction",
    "TranslatedLayer",
    "enable_to_static",
    "functional_call",
    "ignore_module",
    "load",
    "not_to_static",
    "save",
    "to_static",
]


# --- dy2static logging controls (reference jit/dy2static/logging_utils.py:
# set_verbosity:187, set_code_level:226) -------------------------------------
_VERBOSITY = [0]
_CODE_LEVEL = [-1]


def set_verbosity(level=0, also_to_stdout=False):
    """Set the dy2static transform log verbosity (0 = silent). Mirrors the
    reference's env-overridable knob (TRANSLATOR_VERBOSITY)."""
    import os

    _VERBOSITY[0] = int(os.environ.get("TRANSLATOR_VERBOSITY", level))
    return _VERBOSITY[0]


def get_verbosity():
    return _VERBOSITY[0]


def set_code_level(level=100, also_to_stdout=False):
    """Print transformed code up to AST-pass ``level`` (reference
    TRANSLATOR_CODE_LEVEL). The dy2static rewriter consults this when
    emitting its transformed source."""
    import os

    _CODE_LEVEL[0] = int(os.environ.get("TRANSLATOR_CODE_LEVEL", level))
    return _CODE_LEVEL[0]


def get_code_level():
    return _CODE_LEVEL[0]
