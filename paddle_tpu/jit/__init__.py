"""paddle_tpu.jit — dynamic-to-static compilation.

Parity target: paddle.jit (reference: python/paddle/jit/api.py:171 to_static,
dy2static/program_translator.py:325 StaticFunction concrete-program cache,
dy2static/partial_program.py:151 PartialProgramLayer, jit/sot/translate.py:31
bytecode JIT with guards).

TPU-native design (SURVEY.md §7.2 L4): tracing IS the static converter. Every
framework op is a pure jax function, so running the python callable on jax
tracers yields the whole program; ``jax.jit``'s (shape, dtype) cache keys ARE
the SOT guards (guard.py parity: re-trace on spec change); XLA is CINN. The
compiled subgraph participates in autograd as ONE tape node (PartialProgramLayer
parity: run_program_ad_func, fluid/eager/to_static/run_program_op_func.h:136).
"""
from .api import (
    InputSpec,
    StaticFunction,
    TranslatedLayer,
    enable_to_static,
    functional_call,
    ignore_module,
    load,
    not_to_static,
    save,
    to_static,
)

__all__ = [
    "InputSpec",
    "StaticFunction",
    "TranslatedLayer",
    "enable_to_static",
    "functional_call",
    "ignore_module",
    "load",
    "not_to_static",
    "save",
    "to_static",
]
