/* Custom-op C ABI (reference parity: paddle's custom operator C API —
 * phi/capi/include/c_tensor.h + fluid/framework/custom_operator.cc, the
 * out-of-tree op plugin mechanism, SURVEY.md §2.1).
 *
 * A custom op library exports, per op:
 *   void <name>_forward(const PD_CTensor* ins, int n_in,
 *                       PD_CTensor* outs, int n_out);
 * and optionally
 *   void <name>_backward(const PD_CTensor* ins, int n_in,
 *                        PD_CTensor* outs, int n_out);
 * where backward receives [forward inputs..., forward outputs...,
 * output grads...] and writes grads for the FLOATING-dtype forward inputs
 * only, in input order (integer/bool inputs are non-differentiable and get
 * no grad buffer).
 *
 * Buffers are allocated by the framework (shapes from the python-side
 * InferShape), row-major contiguous. dtype codes below.
 */
#ifndef PD_CUSTOM_OP_H_
#define PD_CUSTOM_OP_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

enum PD_CDType {
  PD_FLOAT32 = 0,
  PD_FLOAT64 = 1,
  PD_INT32 = 2,
  PD_INT64 = 3,
  PD_BOOL = 4,
  PD_UINT8 = 5,
};

typedef struct {
  void* data;
  int64_t ndim;
  const int64_t* shape;
  int32_t dtype; /* PD_CDType */
} PD_CTensor;

static inline int64_t pd_numel(const PD_CTensor* t) {
  int64_t n = 1;
  for (int64_t i = 0; i < t->ndim; ++i) n *= t->shape[i];
  return n;
}

#ifdef __cplusplus
}
#endif

#endif /* PD_CUSTOM_OP_H_ */
